"""Service tests with live local servers on ephemeral ports (mirrors the
reference strategy: test_web_status.py / test_restful.py run real
tornado/twisted servers; golden-file plot tests)."""

import json
import os
import pickle
import urllib.request

import numpy
import pytest

from veles_tpu.backends import NumpyDevice
from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.snapshotter import SnapshotterToFile, load_snapshot
from veles_tpu.znicz.standard_workflow import StandardWorkflow


class TinyLoader(FullBatchLoader):
    def load_data(self):
        rng = numpy.random.default_rng(3)
        n = 80
        labels = (numpy.arange(n) % 4).astype(int)
        centers = rng.standard_normal((4, 8)) * 3
        self.original_data.mem = (
            centers[labels] + rng.standard_normal((n, 8)) * 0.5
        ).astype(numpy.float32)
        self.original_labels = [int(v) for v in labels]
        self.class_lengths[:] = [0, 20, 60]


LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
     "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 4},
     "<-": {"learning_rate": 0.1}},
]


def make_wf(tmp_path, max_epochs=3, **kwargs):
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=20),
        layers=[{**s} for s in LAYERS],
        decision_config={"max_epochs": max_epochs},
        **kwargs)
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    return wf


class TestSnapshotter:
    @pytest.mark.parametrize("compression", ["", "gz", "bz2", "xz"])
    def test_codec_roundtrip(self, tmp_path, compression):
        wf = DummyWorkflow()
        snap = SnapshotterToFile(wf, directory=str(tmp_path),
                                 compression=compression,
                                 time_interval=0.0)
        wf.initialize()
        snap.suffix = "t"
        snap.export()
        assert snap.destination and os.path.exists(snap.destination)
        restored = load_snapshot(snap.destination)
        assert type(restored).__name__ == "DummyWorkflow"

    def test_current_symlink(self, tmp_path):
        wf = DummyWorkflow()
        snap = SnapshotterToFile(wf, directory=str(tmp_path),
                                 compression="gz", time_interval=0.0)
        wf.initialize()
        snap.suffix = "one"
        snap.export()
        # reading .destination joins the in-flight background write —
        # the documented way to wait for the artifact (symlink included)
        assert snap.destination
        current = os.path.join(str(tmp_path),
                               "veles_tpu_current.pickle.gz")
        assert os.path.islink(current)
        assert load_snapshot(current) is not None

    def test_wired_into_standard_workflow(self, tmp_path):
        wf = make_wf(tmp_path, snapshotter_config={
            "directory": str(tmp_path), "time_interval": 0.0})
        wf.run()
        # improved at least once → snapshot written with metric suffix
        assert wf.snapshotter.destination is not None
        restored = load_snapshot(wf.snapshotter.destination)
        assert restored.decision.best_n_err_pt == \
            pytest.approx(wf.decision.best_n_err_pt)

    def test_improved_flag_one_shot(self, tmp_path):
        """The snapshotter clears Decision.improved after exporting, so
        one improvement → exactly one snapshot."""
        wf = make_wf(tmp_path, snapshotter_config={
            "directory": str(tmp_path), "time_interval": 0.0})
        exports = []
        original = SnapshotterToFile.export
        SnapshotterToFile.export = \
            lambda self: (exports.append(1), original(self))
        try:
            wf.run()
        finally:
            SnapshotterToFile.export = original
        improvements = wf.decision.best_epoch + 1  # epochs that improved
        assert 0 < len(exports) <= max(improvements, 1) + 1
        assert not bool(wf.decision.improved)

    def test_full_training_resume_from_file(self, tmp_path):
        wf = make_wf(tmp_path, max_epochs=2, snapshotter_config={
            "directory": str(tmp_path), "time_interval": 0.0})
        wf.run()
        restored = load_snapshot(wf.snapshotter.destination)
        restored.launcher = DummyLauncher()
        restored.decision.complete <<= False
        restored.decision.max_epochs = 4
        restored.initialize(device=NumpyDevice())
        restored.run()
        assert restored.loader.epoch_number >= 2


class TestPlotting:
    def test_plotters_stream_to_client(self, tmp_path):
        from veles_tpu.graphics_client import GraphicsClient
        from veles_tpu.graphics_server import GraphicsServer
        server = GraphicsServer.launch()
        client = GraphicsClient(server.endpoint,
                                output_dir=str(tmp_path))
        import time
        time.sleep(0.2)          # PUB/SUB slow-joiner
        wf = make_wf(tmp_path, max_epochs=2, plotters_config={})
        wf.run()
        seen = 0
        while client.process_one(500):
            seen += 1
            if seen > 200:
                break
        assert seen > 0
        assert client.rendered > 0
        pngs = [f for f in os.listdir(str(tmp_path))
                if f.endswith(".png")]
        assert pngs, "viewer rendered no files"
        server.shutdown()
        client.stop()

    def test_plotter_pickle_self_contained(self, tmp_path):
        from veles_tpu.plotting_units import AccumulatingPlotter, Plotter

        class Unpicklable(object):
            v = 1.5

            def __reduce__(self):
                raise TypeError("not picklable")

        wf = DummyWorkflow()
        plotter = AccumulatingPlotter(wf, label="x")
        plotter.input = Unpicklable()
        plotter.input_field = "v"
        plotter.fill()
        Plotter._plot_message_mode = True
        try:
            blob = pickle.dumps(plotter)   # input dropped in message mode
        finally:
            Plotter._plot_message_mode = False
        clone = pickle.loads(blob)
        assert clone.values == [1.5]
        # snapshot mode keeps graph state (links_from survives)
        plotter.input = None
        blob2 = pickle.dumps(plotter)
        assert pickle.loads(blob2).links_from is not None


class TestWebStatus:
    def test_status_roundtrip(self, tmp_path):
        from veles_tpu.web_status import StatusNotifier, WebStatus
        status = WebStatus(port=0).start()
        wf = make_wf(tmp_path, max_epochs=1)
        wf.run()
        notifier = StatusNotifier(
            "http://127.0.0.1:%d/update" % status.port, run_id="r1")
        assert notifier.notify(wf)
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/status" % status.port) as resp:
            data = json.loads(resp.read())
        assert "r1" in data
        assert data["r1"]["stopped"] is True
        assert "best_validation_error_pt" in data["r1"]["results"]
        status.stop()


class TestRestful:
    def test_inference_endpoint(self, tmp_path):
        from veles_tpu.restful_api import RESTfulAPI
        wf = make_wf(tmp_path, max_epochs=2)
        wf.run()
        api = RESTfulAPI(wf, port=0)
        api.forwards = wf.forwards
        api.initialize()
        x = numpy.array(wf.loader.original_data.mem[:3])
        req = urllib.request.Request(
            "http://127.0.0.1:%d/service" % api.port,
            data=json.dumps({"input": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            out = json.loads(resp.read())
        result = numpy.asarray(out["result"])
        assert result.shape == (3, 4)
        assert numpy.allclose(result.sum(axis=1), 1.0, atol=1e-3)
        # probe: malformed body → 400 with error json
        bad = urllib.request.Request(
            "http://127.0.0.1:%d/service" % api.port,
            data=b"not json",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(bad, timeout=5)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())
        # training still works after serving (link restored)
        wf.decision.complete <<= False
        wf.decision.max_epochs = 3
        wf.run()
        api.stop()


class TestPlotterVariants:
    """MultiHistogram / MaxMinPlotter / SlaveStats
    (ref ``plotting_units.py:681,769,822``)."""

    def _axes(self):
        import matplotlib
        matplotlib.use("Agg", force=True)
        from matplotlib.figure import Figure
        return Figure().add_subplot(1, 1, 1)

    def test_multi_histogram(self):
        from veles_tpu.plotting_units import MultiHistogram
        wf = DummyWorkflow()
        p = MultiHistogram(wf, hist_number=4, n_bars=10)
        p.input = numpy.random.default_rng(0).standard_normal((6, 20))
        p.fill()
        assert p.counts.shape == (4, 10)
        assert (p.counts.sum(axis=1) == 20).all()
        p.redraw(self._axes())

    def test_maxmin_plotter(self):
        from veles_tpu.plotting_units import MaxMinPlotter
        wf = DummyWorkflow()
        p = MaxMinPlotter(wf)
        p.input = numpy.array([1.0, -3.0, 2.0])
        p.fill()
        p.input = numpy.array([5.0, 0.0])
        p.fill()
        assert p.maxes == [2.0, 5.0]
        assert p.mins == [-3.0, 0.0]
        p.redraw(self._axes())

    def test_slave_stats_rates(self):
        import time as _time
        from veles_tpu.plotting_units import SlaveStats

        class FakeSlave(object):
            def __init__(self, done):
                self.state = "WORKING"
                self.power = 100.0
                self.jobs_done = done
                self.in_flight = 1

        class FakeServer(object):
            slaves = {"s1": FakeSlave(0), "s2": FakeSlave(5)}

        wf = DummyWorkflow()
        p = SlaveStats(wf, server=FakeServer())
        p.fill()                       # first fill: rate 0 (no history)
        assert [r[5] for r in p.rows] == [0.0, 0.0]
        FakeServer.slaves["s1"].jobs_done = 10
        _time.sleep(0.05)
        p.fill()
        rates = {r[0]: r[5] for r in p.rows}
        assert rates["s1"] > 0
        assert rates["s2"] == 0.0
        assert {r[0] for r in p.rows} == {"s1", "s2"}
        p.redraw(self._axes())


def test_load_snapshot_from_url(tmp_path):
    """-w/--snapshot accepts an http URL (ref ``__main__.py:539-590``):
    the snapshot is fetched and resumed exactly like a local file."""
    import functools
    import http.server
    import threading

    wf = make_wf(tmp_path, max_epochs=1)
    wf.run()
    from veles_tpu.snapshotter import save_snapshot
    path = save_snapshot(wf, str(tmp_path / "wf_url.pickle"))
    handler = functools.partial(
        http.server.SimpleHTTPRequestHandler, directory=str(tmp_path))
    httpd = http.server.HTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = "http://127.0.0.1:%d/wf_url.pickle" % httpd.server_port
        restored = load_snapshot(url)
        assert restored.checksum() == wf.checksum()
    finally:
        httpd.shutdown()


def test_manhole_stack_dump_and_repl(tmp_path):
    """The debug backdoor (ref external/manhole + --manhole): SIGUSR1
    dumps thread stacks; SIGUSR2 serves a socket REPL that evaluates
    in the armed process."""
    import os
    import signal
    import subprocess
    import sys
    import time

    script = tmp_path / "armed.py"
    script.write_text(
        "import sys, time\n"
        "from veles_tpu import manhole\n"
        "manhole.install(namespace={'answer': 41})\n"
        "print('ARMED', flush=True)\n"
        "time.sleep(60)\n")
    import veles_tpu
    env_root = os.path.dirname(os.path.dirname(veles_tpu.__file__))
    pythonpath = env_root + os.pathsep + os.environ.get("PYTHONPATH",
                                                        "")
    proc = subprocess.Popen(
        [sys.executable, str(script)], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env={**os.environ, "PYTHONPATH": pythonpath,
             "JAX_PLATFORMS": "cpu"})
    try:
        assert proc.stdout.readline().strip() == "ARMED"
        # REPL: evaluate inside the process
        from veles_tpu import manhole
        transcript = manhole.connect(
            proc.pid, commands=["answer + 1", "pid == %d" % proc.pid])
        assert "42" in transcript
        assert "True" in transcript
        # stack dump: SIGUSR1 → faulthandler on stderr
        os.kill(proc.pid, signal.SIGUSR1)
        time.sleep(0.5)
        proc.terminate()
        _out, err = proc.communicate(timeout=10)
        assert "Current thread" in err or "Thread" in err
    finally:
        if proc.poll() is None:
            proc.kill()


def test_snapshot_db_store_roundtrip(tmp_path):
    """SQLite snapshot store (the reference's ODBC variant): export
    rows, resume by id or latest via -w 'db://path#id'."""
    from veles_tpu.snapshotter import SnapshotterToDB, load_snapshot

    db = str(tmp_path / "snaps.sqlite")
    wf = make_wf(tmp_path, max_epochs=1)
    wf.run()
    snap = SnapshotterToDB(wf, database=db, time_interval=0.0)
    snap.export()
    first = snap.destination
    assert first.startswith("db://") and first.endswith("#1")
    snap.suffix = "better"
    snap.export()
    restored = load_snapshot(first)
    assert restored.checksum() == wf.checksum()
    latest = load_snapshot("db://%s#latest" % db)
    assert latest.checksum() == wf.checksum()
    import pytest as _pytest
    with _pytest.raises(KeyError):
        load_snapshot("db://%s#99" % db)
    # a '#' in the database path itself must not confuse parsing
    import os as _os
    weird_dir = tmp_path / "run#3"
    weird_dir.mkdir()
    db2 = str(weird_dir / "s.sqlite")
    snap2 = SnapshotterToDB(wf, database=db2, time_interval=0.0)
    snap2.export()
    assert load_snapshot(snap2.destination).checksum() == wf.checksum()
    assert load_snapshot("db://%s" % db2).checksum() == wf.checksum()
    # resume from a typo'd path fails WITHOUT creating the file
    with _pytest.raises(KeyError):
        load_snapshot("db://%s.typo#latest" % db2)
    assert not _os.path.exists(db2 + ".typo")


def test_graphics_server_multicast_degrades_gracefully():
    """The reference binds an epgm:// multicast plot endpoint
    (graphics_server.py:100-110); ours accepts the same spec and MUST
    NOT take training down when libzmq lacks OpenPGM or the group is
    bad — tcp keeps publishing."""
    from veles_tpu.graphics_server import GraphicsServer

    server = GraphicsServer(multicast="epgm://127.0.0.1;239.192.1.1:5555")
    try:
        assert server.endpoint.startswith("tcp://")
        assert server.endpoints[0] == server.endpoint
        # whether or not the bind succeeded, the server works:
        server.send(b"blob")
    finally:
        server._socket.close(linger=0)


def test_sqlite_log_duplication_with_ttl_gc(tmp_path):
    """Every log record mirrors into SQLite and expires by TTL — the
    reference's MongoDB duplication + TTL index (logger.py:292)."""
    import logging
    import time as _time

    from veles_tpu.logger import duplicate_logs_to_db

    db = str(tmp_path / "logs.db")
    handler = duplicate_logs_to_db(db, session="sess-a", ttl_days=1.0)
    try:
        log = logging.getLogger("TTLTest")
        log.warning("watch this space")
        log.error("and this one")
        rows = handler.query(session="sess-a")
        assert len(rows) == 2
        assert rows[0][4] == "and this one"      # newest first
        assert handler.query(min_level=logging.ERROR,
                             session="sess-a")[0][2] == "TTLTest"
        # TTL expiry: purge as if 2 days passed — everything goes
        assert handler.purge(now=_time.time() + 2 * 86400) == 2
        assert handler.query(session="sess-a") == []
        # a second session's rows are isolated by the session column
        log.warning("after purge")
        assert len(handler.query(session="sess-a")) == 1
        assert handler.query(session="other") == []
    finally:
        logging.getLogger().removeHandler(handler)
        handler.close()


def test_weights2d_grid_dense_and_conv():
    """Weights2D (ref nn_plotting_units, knob: limit): dense columns
    become square tiles, conv kernels become per-kernel tiles, packed
    into a separator grid."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.plotting_units import Weights2D

    wf = DummyWorkflow()
    rng = numpy.random.default_rng(0)
    p = Weights2D(wf, name="w", limit=6)
    p.input = rng.standard_normal((16, 10)).astype(numpy.float32)
    p.fill()
    # 6 tiles of 4x4 in a 3x2 grid with 1-px separators
    assert p.grid.shape == (2 * 5 - 1, 3 * 5 - 1)
    assert p.grid.min() >= 0.0 and p.grid.max() <= 1.0

    p_rgb = Weights2D(wf, name="wc", limit=4)
    p_rgb.input = rng.standard_normal((5, 5, 3, 9)).astype(
        numpy.float32)
    p_rgb.fill()
    assert p_rgb.grid.shape == (2 * 6 - 1, 2 * 6 - 1, 3)
    # viewer round trip: redraw onto a real axes
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, axes = plt.subplots()
    p.redraw(axes)
    p_rgb.redraw(axes)
    plt.close(fig)


def test_image_saver_writes_misclassified(tmp_path):
    """ImageSaver (ref znicz.image_saver, knob: out_dirs): wrong
    samples land as PNGs named epoch_truth_pred_<counter> in the
    minibatch class's directory; a new epoch's first write resets the
    gallery; names stay unique across minibatches."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.znicz.image_saver import ImageSaver

    wf = DummyWorkflow()
    dirs = [str(tmp_path / d) for d in ("test", "valid", "train")]
    s = ImageSaver(wf, out_dirs=dirs, limit=10)
    rng = numpy.random.default_rng(1)
    s.input = rng.standard_normal((4, 784)).astype(numpy.float32)
    s.labels = numpy.array([1, 2, 3, 4])
    s.max_idx = numpy.array([1, 9, 3, 9])       # samples 1, 3 wrong
    s.minibatch_class = 2                        # TRAIN
    s.minibatch_size = 4
    s.epoch_number = 7
    s.run()
    import os
    names = sorted(os.listdir(dirs[2]))
    assert names == ["7_2_9_00000.png", "7_4_9_00001.png"]
    from PIL import Image
    assert Image.open(os.path.join(dirs[2], names[0])).size == (28, 28)
    # a SECOND minibatch of the same epoch with the same wrong slots
    # must not overwrite — the per-gallery counter uniquifies
    s.run()
    assert len(os.listdir(dirs[2])) == 4

    # next epoch: the gallery resets on its first write
    s.epoch_number = 8
    s.max_idx = numpy.array([1, 2, 3, 0])        # only sample 3 wrong
    s.run()
    assert sorted(os.listdir(dirs[2])) == ["8_4_0_00000.png"]
    # out-of-range class index: silently no-op
    s.minibatch_class = 5
    s.run()


def test_standard_workflow_image_saver_and_weights_plotter(tmp_path):
    """End-to-end: StandardWorkflow wires the ImageSaver (after the
    Decision) and the Weights2D plotter from their documented config
    knobs; a real 2-epoch run produces mistake PNGs and a filled
    weight grid."""
    import os

    from veles_tpu import prng
    from veles_tpu.backends import CPUDevice
    from veles_tpu.plotting_units import Weights2D
    from veles_tpu.samples import mnist

    prng.seed_all(8)
    dirs = [str(tmp_path / d) for d in ("test", "valid", "train")]
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=1000,
        plotters_config={"weights": {"limit": 9}},
        image_saver_config={"out_dirs": dirs, "limit": 5})
    wf.run()
    assert wf.image_saver is not None
    # synthetic data + 1 training epoch: plenty of mistakes captured
    assert len(os.listdir(dirs[1])) > 0       # validation mistakes
    w2d = [p for p in wf.plotters if isinstance(p, Weights2D)]
    assert len(w2d) == 1 and w2d[0].grid is not None
    # 9 tiles of 28x28 -> 3x3 grid with separators
    assert w2d[0].grid.shape == (3 * 29 - 1, 3 * 29 - 1)


def test_pickle_diagnostics_names_offending_attribute():
    """--debug-pickle parity: a failed snapshot pickle is diagnosed
    down to the attribute path that cannot pickle."""
    from veles_tpu.snapshotter import diagnose_pickle

    class Inner:
        def __init__(self):
            self.fine = 42
            self.broken = lambda: None       # unpicklable

    class Outer:
        def __init__(self):
            self.name = "ok"
            self.child = Inner()

    lines = diagnose_pickle(Outer(), path="wf")
    assert any("wf.child.broken" in line for line in lines)
    assert not any(".fine" in line or ".name" in line
                   for line in lines)
    assert diagnose_pickle({"a": 1}) == []


def test_graphics_client_pdf_toggle(tmp_path):
    """The documented SIGUSR2 PDF mode: toggling switches rendered
    plot files from .png to .pdf."""
    from veles_tpu.graphics_server import GraphicsServer
    from veles_tpu.graphics_client import GraphicsClient
    from veles_tpu.plotting_units import AccumulatingPlotter

    server = GraphicsServer.launch()
    client = GraphicsClient(server.endpoint, output_dir=str(tmp_path))
    try:
        wf = DummyWorkflow()
        plotter = AccumulatingPlotter(wf, name="curve")
        plotter.values = [1.0, 2.0]
        client.render(plotter)
        assert (tmp_path / "curve.png").exists()
        client.toggle_pdf()
        assert client.pdf_mode
        client.render(plotter)
        assert (tmp_path / "curve.pdf").exists()
    finally:
        client.stop()
        server.shutdown()


def test_immediate_and_autohistogram_plotters(tmp_path):
    """ImmediatePlotter (N styled curves per run) and
    AutoHistogramPlotter (Freedman-Diaconis bins) — the last two
    reference plotter classes."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from veles_tpu.plotting_units import (AutoHistogramPlotter,
                                          ImmediatePlotter)

    wf = DummyWorkflow()

    class Holder:
        curve = numpy.linspace(0.0, 1.0, 20)

    p = ImmediatePlotter(wf, name="imm", ylim=(0, 2))
    p.inputs = [Holder(), [10.0, 20.0, 30.0]]
    p.input_fields = ["curve", 1]
    p.input_styles = ["k-"]
    p.fill()
    assert len(p.curves) == 2
    assert p.curves[1][0] == 20.0            # int field indexes
    fig, axes = plt.subplots()
    p.redraw(axes)
    plt.close(fig)

    rng = numpy.random.default_rng(0)
    h = AutoHistogramPlotter(wf, name="auto")
    h.input = rng.standard_normal(4000).astype(numpy.float32)
    h.fill()
    assert h.counts is not None
    assert len(h.counts) >= 3
    assert h.counts.sum() == 4000
    # constant data degrades to the 3-bin floor, not a crash
    h2 = AutoHistogramPlotter(wf, name="flat")
    h2.input = numpy.ones(64, numpy.float32)
    h2.fill()
    assert len(h2.counts) == 3


def test_udp_multicast_frame_roundtrip():
    """The stdlib multicast transport (the reference's epgm lab-wide
    plot broadcast, ``graphics_server.py:100-110``, rebuilt over plain
    UDP): single-chunk and multi-chunk frames survive the group."""
    import pytest

    from veles_tpu.multicast import CHUNK, McastReceiver, McastSender

    endpoint = "udp://239.255.42.99:15995"
    try:
        recv = McastReceiver(endpoint, interface="127.0.0.1")
        send = McastSender(endpoint, interface="127.0.0.1")
    except OSError as exc:
        pytest.skip("multicast unavailable in this sandbox: %s" % exc)
    try:
        send.send(b"small-frame")
        got = recv.recv_frame(timeout=5.0)
        if got is None:
            pytest.skip("multicast datagrams not looped back here")
        assert got == b"small-frame"
        big = bytes(range(256)) * 1024  # 256 KiB -> 5 chunks
        assert len(big) > 4 * CHUNK
        send.send(big)
        assert recv.recv_frame(timeout=5.0) == big
    finally:
        send.close()
        recv.close()


def test_udp_multicast_graphics_end_to_end(tmp_path):
    """GraphicsServer publishes over udp:// alongside tcp and a
    GraphicsClient subscribed to the group renders the plotter."""
    import pytest

    from veles_tpu.graphics_client import GraphicsClient
    from veles_tpu.graphics_server import GraphicsServer
    from veles_tpu.multicast import McastReceiver

    endpoint = "udp://239.255.42.99:15996"
    try:
        probe = McastReceiver(endpoint, interface="127.0.0.1")
    except OSError as exc:
        pytest.skip("multicast unavailable in this sandbox: %s" % exc)
    server = GraphicsServer(multicast=endpoint)
    client = None
    try:
        if server._mcast is None:
            pytest.skip("server could not open the multicast endpoint")
        server._mcast._sock.setsockopt(
            __import__("socket").IPPROTO_IP,
            __import__("socket").IP_MULTICAST_IF,
            __import__("socket").inet_aton("127.0.0.1"))
        server.send(b"probe")
        if probe.recv_frame(timeout=5.0) is None:
            pytest.skip("multicast datagrams not looped back here")
        client = GraphicsClient(endpoint, output_dir=str(tmp_path))

        from veles_tpu.plotting_units import AccumulatingPlotter
        from veles_tpu.dummy import DummyWorkflow
        wf = DummyWorkflow()
        plotter = AccumulatingPlotter(wf, name="mcast test")
        plotter.input = 0.5
        plotter.fill()
        server.enqueue(plotter)
        assert client.process_one(timeout_ms=5000)
        assert client.rendered == 1
    finally:
        probe.close()
        if client is not None:
            client.stop()
        server.shutdown()


def test_udp_multicast_two_senders_do_not_interleave():
    """Chunks are keyed by sender, so two publishers (the reference's
    many-masters lab scenario) can share a group without corrupting
    each other's frames — and a sender restart reusing frame ids with
    a different chunk count starts a clean reassembly."""
    import pytest

    from veles_tpu.multicast import CHUNK, McastReceiver, McastSender

    endpoint = "udp://127.0.0.1;239.255.42.99:16000"
    try:
        recv = McastReceiver(endpoint)
        a = McastSender(endpoint)
        b = McastSender(endpoint)
    except OSError as exc:
        pytest.skip("multicast unavailable in this sandbox: %s" % exc)
    try:
        frame_a = b"A" * (2 * CHUNK + 100)   # 3 chunks
        frame_b = b"B" * (CHUNK + 100)       # 2 chunks, same frame_id=1
        a.send(frame_a)
        b.send(frame_b)
        got = [recv.recv_frame(timeout=5.0) for _ in range(2)]
        if got[0] is None:
            pytest.skip("multicast datagrams not looped back here")
        assert sorted(g for g in got if g) == sorted([frame_a, frame_b])
    finally:
        a.close()
        b.close()
        recv.close()


def test_web_status_ui_page():
    """GET / (and /ui) serves the packaged browser UI — the
    reference's web/ JS site equivalent (VERDICT r4 missing item 4)."""
    from veles_tpu.web_status import WebStatus
    status = WebStatus(port=0).start()
    try:
        for path in ("/", "/ui"):
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d%s" % (status.port, path)) as r:
                assert r.headers["Content-Type"].startswith("text/html")
                body = r.read().decode()
            assert "veles-tpu training status" in body
            assert "status.json" in body     # the page polls the API
    finally:
        status.stop()
