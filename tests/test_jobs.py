"""Master–slave job layer tests — single process, localhost, real ZMQ
sockets (mirrors reference ``tests/test_network.py:52-140``: scripted
workflows first, then a full distributed training run, then fault
injection with requeue)."""

import threading
import time

import numpy
import pytest

from veles_tpu.backends import NumpyDevice
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.parallel.jobs import JobClient, JobServer
from veles_tpu.znicz.standard_workflow import StandardWorkflow


class ScriptedMaster(object):
    """Reference-style scripted workflow: N jobs, records updates."""

    def __init__(self, n_jobs=5):
        self.n_jobs = n_jobs
        self.served = 0
        self.updates = []
        self.dropped = []

    def checksum(self):
        return "scripted-v1"

    def generate_data_for_slave(self, slave):
        if self.served >= self.n_jobs:
            return None
        self.served += 1
        return {"job_number": self.served}

    def apply_data_from_slave(self, data, slave):
        self.updates.append((slave.id, data))

    def drop_slave(self, slave):
        self.dropped.append(slave.id)


class ScriptedSlave(object):
    def __init__(self, checksum="scripted-v1"):
        self._checksum = checksum
        self.jobs = []

    def checksum(self):
        return self._checksum

    def do_job(self, data, callback):
        self.jobs.append(data)
        callback({"result": data["job_number"] * 10})


def test_handshake_job_update_cycle():
    master = ScriptedMaster(n_jobs=3)
    server = JobServer(master).start()
    try:
        client = JobClient(ScriptedSlave(), server.endpoint)
        client.handshake()
        assert client.run()
        client.close()
        assert master.served == 3
        assert len(master.updates) == 3
        assert master.updates[0][1] == {"result": 10}
    finally:
        server.stop()


def test_checksum_mismatch_rejected():
    master = ScriptedMaster()
    server = JobServer(master).start()
    try:
        client = JobClient(ScriptedSlave(checksum="other"),
                           server.endpoint)
        with pytest.raises(ConnectionError):
            client.handshake()
        client.close()
    finally:
        server.stop()


def test_two_slaves_share_jobs():
    master = ScriptedMaster(n_jobs=10)
    server = JobServer(master).start()
    try:
        clients = [JobClient(ScriptedSlave(), server.endpoint)
                   for _ in range(2)]
        threads = []
        for client in clients:
            client.handshake()
            t = threading.Thread(target=client.run)
            threads.append(t)
            t.start()
        for t in threads:
            t.join(20)
        # all jobs complete exactly once; which slave got how many is a
        # scheduling race, so only completeness is asserted
        assert len(master.updates) == 10
        assert sorted(u["result"] for _, u in master.updates) == \
            [i * 10 for i in range(1, 11)]
        for client in clients:
            client.close()
    finally:
        server.stop()


def test_dead_slave_requeued():
    """Slave dies mid-job (fault injection) → timeout reap → drop_slave →
    master requeues (ref --slave-death-probability + loader requeue)."""
    master = ScriptedMaster(n_jobs=3)
    server = JobServer(master, slave_timeout=1.0,
                       heartbeat_interval=0.3).start()
    try:
        dead = JobClient(ScriptedSlave(), server.endpoint,
                         death_probability=1.0)
        dead.handshake()
        assert dead.run() is False      # died mid-job
        deadline = time.time() + 5
        while not master.dropped and time.time() < deadline:
            time.sleep(0.1)
        assert master.dropped
    finally:
        server.stop()


# -- full distributed training (reference §3.2 flow) ------------------------

class DistLoader(FullBatchLoader):
    def load_data(self):
        rng = numpy.random.default_rng(5)
        n = 200
        labels = (numpy.arange(n) % 5).astype(int)
        centers = rng.standard_normal((5, 16)) * 3
        self.original_data.mem = (
            centers[labels] + rng.standard_normal((n, 16)) * 0.5
        ).astype(numpy.float32)
        self.original_labels = [int(v) for v in labels]
        self.class_lengths[:] = [0, 50, 150]


DIST_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 12},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 5},
     "<-": {"learning_rate": 0.05}},
]


def make_dist_wf(is_master=False, is_slave=False, fused=False,
                 fused_config=None):
    from veles_tpu import prng
    prng.seed_all(21)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: DistLoader(w, minibatch_size=25),
        layers=[{**s} for s in DIST_LAYERS],
        fused=fused, fused_config=fused_config or {},
        decision_config={"max_epochs": 3})
    wf.launcher = DummyLauncher(is_master=is_master, is_slave=is_slave)
    wf.initialize(device=NumpyDevice())
    return wf


def test_distributed_training_end_to_end():
    master_wf = make_dist_wf(is_master=True)
    slave_wf = make_dist_wf(is_slave=True)
    assert master_wf.checksum() == slave_wf.checksum()
    w_before = numpy.array(master_wf.forwards[0].weights.mem)

    server = JobServer(master_wf).start()
    try:
        client = JobClient(slave_wf, server.endpoint)
        client.handshake()
        client.run(max_jobs=24)        # 3 epochs × 8 minibatches
        client.close()
        assert client.jobs_done > 0
        w_after = numpy.array(master_wf.forwards[0].weights.mem)
        assert not numpy.allclose(w_before, w_after), \
            "slave deltas must reach master weights"
        # master-side decision accounted distributed stats
        assert master_wf.decision.epoch_samples != [0, 0, 0] or \
            master_wf.decision.best_n_err_pt < 100.0
    finally:
        server.stop()


def test_distributed_training_fused_end_to_end():
    """The flagship fused step under the elastic job layer (VERDICT r4
    weak #8): slaves train through the ONE jitted program, the job
    protocol still moves weights out / deltas back via the forwards."""
    master_wf = make_dist_wf(is_master=True, fused=True)
    slave_wf = make_dist_wf(is_slave=True, fused=True)
    assert master_wf.checksum() == slave_wf.checksum()
    w_before = numpy.array(master_wf.forwards[0].weights.mem)

    server = JobServer(master_wf).start()
    try:
        client = JobClient(slave_wf, server.endpoint)
        client.handshake()
        client.run(max_jobs=24)        # 3 epochs × 8 minibatches
        client.close()
        assert client.jobs_done > 0
        # the slave actually built and trained the fused program
        assert slave_wf.fused_trainer.capture_state() is not None
        w_after = numpy.array(master_wf.forwards[0].weights.mem)
        assert not numpy.allclose(w_before, w_after), \
            "fused slave deltas must reach master weights"
    finally:
        server.stop()


def test_fused_job_protocol_reseeds_and_syncs():
    """Direct (socket-free) protocol check: a job's payload reaches the
    fused device params, and the returned deltas reproduce the slave's
    trained weights on the master."""
    master_wf = make_dist_wf(is_master=True, fused=True)
    slave_wf = make_dist_wf(is_slave=True, fused=True)
    w0 = numpy.array(master_wf.forwards[0].weights.mem)

    # one epoch of jobs (2 validation + 6 train minibatches), merging
    # each update as the real master does
    for _ in range(8):
        updates = []
        slave_wf.do_job(master_wf.generate_data_for_slave(None),
                        updates.append)
        assert updates and updates[0] is not None
        master_wf.apply_data_from_slave(updates[0], None)
    w1 = numpy.array(master_wf.forwards[0].weights.mem)
    assert not numpy.allclose(w0, w1)
    # master's merged weights == the slave's trained weights (delta
    # from the identical starting point; float-add round-trip)
    slave_w = numpy.array(slave_wf.forwards[0].weights.mem)
    numpy.testing.assert_allclose(w1, slave_w, rtol=1e-5, atol=1e-6)

    # job 2: master-side weight changes must reach the ALREADY-BUILT
    # fused params (refresh_from_forwards), not just the Vectors
    master_wf.forwards[0].weights.map_write()
    master_wf.forwards[0].weights.mem[...] = 0.123
    slave_wf.apply_data_from_master(
        master_wf.generate_data_for_slave(None))
    state = slave_wf.fused_trainer.capture_state()
    numpy.testing.assert_allclose(
        numpy.asarray(state[0]["w"], numpy.float32), 0.123, atol=1e-6)


def test_fused_pod_slice_slave_on_mesh():
    """A slave that is a whole pod slice: its fused step shards the job
    minibatch over the local device mesh (DP + grad all-reduce inside
    the step) while the master stays plain — legal because the
    handshake checksum hashes code + graph, not per-host config
    (docs/distributed_training.md 'a slave is a whole pod slice')."""
    master_wf = make_dist_wf(is_master=True, fused=True)
    # data=5 divides the 25-sample job minibatch exactly: every train
    # job runs the clean DP shard path, not the tail-rounding path
    slave_wf = make_dist_wf(
        is_slave=True, fused=True,
        fused_config={"mesh_axes": {"data": 5}})
    assert master_wf.checksum() == slave_wf.checksum()
    w0 = numpy.array(master_wf.forwards[0].weights.mem)

    for _ in range(8):                 # one epoch of jobs
        updates = []
        slave_wf.do_job(master_wf.generate_data_for_slave(None),
                        updates.append)
        master_wf.apply_data_from_slave(updates[0], None)
    w1 = numpy.array(master_wf.forwards[0].weights.mem)
    assert not numpy.allclose(w0, w1)
    numpy.testing.assert_allclose(
        w1, numpy.array(slave_wf.forwards[0].weights.mem),
        rtol=1e-5, atol=1e-6)
    # job payloads really entered the mesh-sharded params
    master_wf.forwards[0].weights.map_write()
    master_wf.forwards[0].weights.mem[...] = 0.25
    slave_wf.apply_data_from_master(
        master_wf.generate_data_for_slave(None))
    state = slave_wf.fused_trainer.capture_state()
    numpy.testing.assert_allclose(
        numpy.asarray(state[0]["w"], numpy.float32), 0.25, atol=1e-6)


def test_fused_refresh_preserves_solver_state():
    """refresh_from_forwards overwrites ONLY the w/b leaves: momentum
    velocities accumulated across jobs stay slave-local (the async-DP
    consistency model — optimizer dynamics live with the slave like
    the eager chain's gradient Vectors)."""
    master_wf = make_dist_wf(is_master=True, fused=True)
    slave_wf = make_dist_wf(is_slave=True, fused=True)
    for _ in range(8):                 # one epoch: builds + trains
        slave_wf.do_job(master_wf.generate_data_for_slave(None),
                        lambda update: None)
    before = slave_wf.fused_trainer.capture_state()
    assert numpy.abs(before[0]["vw"]).max() > 0, \
        "momentum must have accumulated"
    master_wf.forwards[0].weights.map_write()
    master_wf.forwards[0].weights.mem[...] = 0.5
    slave_wf.apply_data_from_master(
        master_wf.generate_data_for_slave(None))
    after = slave_wf.fused_trainer.capture_state()
    numpy.testing.assert_array_equal(after[0]["vw"], before[0]["vw"])
    numpy.testing.assert_allclose(
        numpy.asarray(after[0]["w"], numpy.float32), 0.5, atol=1e-6)


def test_fused_epoch_mode_rejected_on_slave():
    """Whole-epoch-in-one-program conflicts with per-minibatch jobs —
    fail closed (fused_unit.initialize guard)."""
    from veles_tpu import prng
    prng.seed_all(21)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: DistLoader(w, minibatch_size=25),
        layers=[{**s} for s in DIST_LAYERS],
        fused=True, fused_config={"epoch_mode": True},
        decision_config={"max_epochs": 3})
    wf.launcher = DummyLauncher(is_slave=True)
    with pytest.raises(NotImplementedError):
        wf.initialize(device=NumpyDevice())


def test_distributed_stop_on_complete():
    master_wf = make_dist_wf(is_master=True)
    slave_wf = make_dist_wf(is_slave=True)
    master_wf.decision.complete <<= True    # already done
    server = JobServer(master_wf).start()
    try:
        client = JobClient(slave_wf, server.endpoint)
        client.handshake()
        assert client.run() is True
        assert client.jobs_done == 0        # no_more_jobs immediately
        client.close()
    finally:
        server.stop()


def test_prefetch_double_buffering():
    """run_prefetch overlaps compute with the next job_request (ref
    async mode _balance=2, server.py:262-281): the master must see at
    least one job_request arriving while the slave is still WORKING."""
    class RecordingMaster(ScriptedMaster):
        def __init__(self, n_jobs):
            super().__init__(n_jobs)
            self.states_at_request = []

        def generate_data_for_slave(self, slave):
            self.states_at_request.append(slave.state)
            return super().generate_data_for_slave(slave)

    master = RecordingMaster(6)
    server = JobServer(master).start()

    class SlowSlave(ScriptedSlave):
        def do_job(self, job, callback):
            time.sleep(0.05)
            self.jobs.append(job)
            callback({"result": job["job_number"]})

    try:
        client = JobClient(SlowSlave(), server.endpoint)
        client.handshake()
        assert client.run_prefetch()
        client.close()
        assert master.served == 6
        assert len(master.updates) == 6
        assert sorted(u[1]["result"] for u in master.updates) == \
            [1, 2, 3, 4, 5, 6]
        # the overlap actually happened: requests arrived mid-compute
        assert "WORKING" in master.states_at_request
    finally:
        server.stop()


def test_stale_pong_skipped_before_later_rpc():
    """Satellite regression (the comment in ``_rpc`` was untested): a
    timed-out heartbeat's LATE pong arriving before a later rpc's
    reply must be skipped without desyncing the DEALER stream — the
    later rpc still gets ITS reply."""
    import pickle

    import zmq

    context = zmq.Context.instance()
    router = context.socket(zmq.ROUTER)
    port = router.bind_to_random_port("tcp://127.0.0.1")
    client = JobClient(ScriptedSlave(),
                       "tcp://127.0.0.1:%d" % port)
    try:
        # heartbeat ping times out (master stalled, not dead)
        with pytest.raises(TimeoutError):
            client._rpc({"op": "ping", "id": client.sid},
                        timeout_ms=200)
        identity, blob = router.recv_multipart()
        ping = pickle.loads(blob)
        assert ping["op"] == "ping"
        # ...then the master wakes up and answers the OLD ping
        router.send_multipart([identity, pickle.dumps(
            {"op": "pong", "req": ping.get("req")})])
        time.sleep(0.1)

        def master_side():
            ident2, blob2 = router.recv_multipart()
            request = pickle.loads(blob2)
            assert request["op"] == "job_request"
            router.send_multipart([ident2, pickle.dumps(
                {"op": "job", "data": {"job_number": 1},
                 "req": request.get("req")})])

        t = threading.Thread(target=master_side)
        t.start()
        reply = client._rpc({"op": "job_request", "id": client.sid},
                            timeout_ms=3000)
        t.join(5)
        # the stale pong was skipped; the stream stayed in sync
        assert reply["op"] == "job"
        assert reply["data"] == {"job_number": 1}
    finally:
        client.close()
        router.close(linger=0)


def test_orphan_reply_of_timed_out_rpc_skipped():
    """The stale-pong rule generalized via the req echo: a late
    NON-pong reply to a timed-out rpc must also be skipped, so a
    retried request cannot consume its predecessor's answer."""
    import pickle

    import zmq

    context = zmq.Context.instance()
    router = context.socket(zmq.ROUTER)
    port = router.bind_to_random_port("tcp://127.0.0.1")
    client = JobClient(ScriptedSlave(),
                       "tcp://127.0.0.1:%d" % port)
    try:
        with pytest.raises(TimeoutError):
            client._rpc({"op": "job_request", "id": client.sid},
                        timeout_ms=200)
        identity, blob = router.recv_multipart()
        first = pickle.loads(blob)
        # the late answer to the TIMED-OUT request...
        router.send_multipart([identity, pickle.dumps(
            {"op": "job", "data": {"job_number": 1},
             "req": first.get("req")})])
        time.sleep(0.1)

        def master_side():
            ident2, blob2 = router.recv_multipart()
            request = pickle.loads(blob2)
            router.send_multipart([ident2, pickle.dumps(
                {"op": "job", "data": {"job_number": 2},
                 "req": request.get("req")})])

        t = threading.Thread(target=master_side)
        t.start()
        reply = client._rpc({"op": "job_request", "id": client.sid},
                            timeout_ms=3000)
        t.join(5)
        assert reply["data"] == {"job_number": 2}, \
            "the retry must get ITS reply, not the orphan"
    finally:
        client.close()
        router.close(linger=0)


def test_zero_progress_slave_blacklisted_on_timeout():
    """Satellite: a slave that joins, never completes a job and goes
    silent is blacklisted when the reaper times it out (jobs.py
    hung-slave sweep) — while a slave WITH progress is merely
    dropped."""
    master = ScriptedMaster(n_jobs=3)
    server = JobServer(master, slave_timeout=0.6,
                       heartbeat_interval=0.2).start()
    productive = JobClient(ScriptedSlave(), server.endpoint)
    hung = JobClient(ScriptedSlave(), server.endpoint)
    try:
        productive.handshake()
        assert productive.run() is True        # 3 jobs done, then idle
        hung.handshake()                       # joins, does NOTHING
        deadline = time.time() + 5
        while time.time() < deadline and \
                (hung.sid not in server.blacklist
                 or productive.sid in server.slaves):
            time.sleep(0.05)
        assert hung.sid in server.blacklist, \
            "zero-progress slave must be blacklisted"
        assert hung.sid in master.dropped
        assert productive.sid not in server.blacklist, \
            "a slave with jobs done is dropped, never blacklisted"
        assert productive.sid in master.dropped
    finally:
        productive.close()
        hung.close()
        server.stop()


def test_blacklisted_sid_rehandshake_rejected():
    """Satellite (jobs.py:276 untested): a blacklisted sid's
    re-handshake is rejected with reason="blacklisted" — it can never
    rejoin, even with a matching checksum."""
    master = ScriptedMaster(n_jobs=3)
    server = JobServer(master, slave_timeout=0.5,
                       heartbeat_interval=0.2).start()
    try:
        hung = JobClient(ScriptedSlave(), server.endpoint)
        hung.handshake()
        deadline = time.time() + 5
        while time.time() < deadline and \
                hung.sid not in server.blacklist:
            time.sleep(0.05)
        assert hung.sid in server.blacklist
        hung.close()
        retry = JobClient(ScriptedSlave(), server.endpoint,
                          sid=hung.sid)
        # same DEALER identity as the closed socket: the ROUTER may
        # still drop replies routed at the dying connection for a
        # moment — retry the handshake until the reject arrives
        outcome = None
        for _ in range(5):
            try:
                retry.handshake()
                outcome = "accepted"
                break
            except ConnectionError as e:
                outcome = str(e)
                break
            except TimeoutError:
                time.sleep(0.3)
        assert outcome is not None and "blacklisted" in outcome, outcome
        retry.close()
        # a FRESH sid still joins fine (the blacklist is per-id)
        fresh = JobClient(ScriptedSlave(), server.endpoint)
        fresh.handshake()
        assert fresh.run() is True
        fresh.close()
    finally:
        server.stop()


def test_client_default_power_from_db(tmp_path, monkeypatch):
    """Slaves advertise the autotune DB's measured device power when
    present (ref client.py:309-312 power reporting)."""
    import json

    import jax

    from veles_tpu import backends
    from veles_tpu.parallel import jobs

    model = jax.devices()[0].device_kind
    db_path = tmp_path / "db.json"
    db_path.write_text(json.dumps(
        {model: {"power": {"chain_seconds": 0.01, "gflops": 123456.0}}}))
    monkeypatch.setattr(backends, "DEVICE_INFOS_JSON", str(db_path))
    assert jobs._default_power() == 123456.0
    db_path.unlink()
    assert jobs._default_power() == 1.0


def test_print_stats_reports_job_latency_percentiles(caplog):
    """Satellite: per-slave job round-trip latency lands in the SHARED
    LatencyHistogram (one implementation for serve and jobs) and
    print_stats renders the percentile line."""
    import logging

    from veles_tpu import metrics as shared_metrics
    from veles_tpu.serve import metrics as serve_metrics

    # the lift: serve re-exports the shared class, no drifted copy
    assert serve_metrics.LatencyHistogram \
        is shared_metrics.LatencyHistogram

    master = ScriptedMaster(n_jobs=4)
    server = JobServer(master).start()
    try:
        client = JobClient(ScriptedSlave(), server.endpoint)
        client.handshake()
        assert client.run()
        slave = server.slaves[client.sid]
        assert isinstance(slave.latency,
                          shared_metrics.LatencyHistogram)
        assert slave.latency.count == 4
        assert slave.latency.mean > 0
        assert slave.latency.percentile(99) >= \
            slave.latency.percentile(50) > 0
        with caplog.at_level(logging.INFO):
            server.print_stats()
        lines = [r.getMessage() for r in caplog.records]
        assert any("job latency" in line and "p95" in line
                   for line in lines), lines
        client.close()
    finally:
        server.stop()
