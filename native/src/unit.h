// Unit: one inference-graph node.
// Role parity: libVeles Unit (inc/veles/unit.h:105-190 — Run→Execute,
// property assignment, output chaining) and UnitFactory
// (inc/veles/unit_factory.h — static name→constructor registry).
// The package's unit `type` string (veles_tpu/package.py MAPPING names)
// keys the factory, replacing the reference's UUID scheme.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "engine.h"
#include "json.h"
#include "npy.h"

namespace veles_native {

using Shape = std::vector<int64_t>;

inline int64_t NumElements(const Shape& shape) {
  int64_t n = 1;
  for (int64_t d : shape) n *= d;
  return n;
}

class Unit {
 public:
  virtual ~Unit() = default;

  // Consumes config + named arrays; computes and stores the output shape
  // for `input_shape` (batch included as dim 0). Called once per batch
  // geometry, before memory planning.
  virtual void Initialize(const Json& config,
                          std::map<std::string, NpyArray> arrays,
                          const Shape& input_shape) = 0;

  // Runs the forward computation `in` → `out` (dense f32, C-order,
  // shapes as negotiated in Initialize). `scratch` points at this unit's
  // arena slice of ScratchFloats() floats (nullptr when 0).
  virtual void Execute(const float* in, float* out, float* scratch,
                       Engine* engine) = 0;

  // Scratch floats needed per execution (packed by MemoryOptimizer).
  // `max_workers` = the engine's worker count: units that keep
  // per-thread scratch size it accordingly.
  virtual int64_t ScratchFloats(int max_workers) const {
    (void)max_workers;
    return 0;
  }

  const Shape& output_shape() const { return output_shape_; }
  const Shape& input_shape() const { return input_shape_; }
  const std::string& name() const { return name_; }
  void set_name(const std::string& name) { name_ = name; }

 protected:
  Shape input_shape_;
  Shape output_shape_;
  std::string name_;
};

// out[m,n] = x[m,k]·w[k,n] + b[n] (b may be null → zero init);
// row-major, 4-row-blocked with a zero-value skip (units.cc).  Exposed
// for the component tests, which pit the blocked / remainder /
// zero-skip paths against a naive reference loop.
void Gemm(const float* x, const float* w, const float* b, float* out,
          int64_t m, int64_t k, int64_t n, Engine* engine);

class UnitFactory {
 public:
  using Creator = std::function<std::unique_ptr<Unit>(const std::string&)>;

  static UnitFactory& Instance();

  void Register(const std::string& type, Creator creator);
  std::unique_ptr<Unit> Create(const std::string& type) const;
  std::vector<std::string> Types() const;

 private:
  std::map<std::string, Creator> creators_;
};

// Registers every built-in unit type (idempotent; called by Workflow).
void RegisterStandardUnits();

}  // namespace veles_native
