// Built-in forward units. Math mirrors veles_tpu/package.py's
// PackagedRunner (the golden model) exactly: znicz activations
// (tanh = 1.7159·tanh(0.6666x), relu = clipped softplus), im2col+sgemm
// convolution, window pooling (stochastic → test-time expectation),
// across-channel LRN, identity dropout, (x-mean)·disp normalization.
#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstring>
#include <stdexcept>

#include "unit.h"

namespace veles_native {

namespace {

// ---------------------------------------------------------------------
// activations (znicz semantics, see veles_tpu/znicz/fused.py _ACT)

enum class Act { kNone, kTanh, kSigmoid, kRelu, kStrictRelu };

Act ParseAct(const Json& config) {
  JsonPtr a = config.get("activation");
  if (!a || a->is_null()) return Act::kNone;
  const std::string& s = a->string_value();
  if (s == "tanh") return Act::kTanh;
  if (s == "sigmoid") return Act::kSigmoid;
  if (s == "relu") return Act::kRelu;
  if (s == "strict_relu") return Act::kStrictRelu;
  if (s == "linear") return Act::kNone;
  throw std::runtime_error("unknown activation " + s);
}

inline float ApplyAct(Act act, float z) {
  switch (act) {
    case Act::kNone: return z;
    case Act::kTanh: return 1.7159f * std::tanh(0.6666f * z);
    case Act::kSigmoid: return 1.0f / (1.0f + std::exp(-z));
    case Act::kRelu: return std::log1p(std::exp(std::min(z, 30.0f)));
    case Act::kStrictRelu: return std::max(z, 0.0f);
  }
  return z;
}

void ActRow(Act act, float* row, int64_t n) {
  if (act == Act::kNone) return;
  for (int64_t i = 0; i < n; ++i) row[i] = ApplyAct(act, row[i]);
}

}  // namespace

// out[m,n] = x[m,k]·w[k,n] + b[n]; row-major.  Four sample rows ride
// each streamed w row (4x less L2 traffic on w, four independent FMA
// chains for the vectorized j loop); per-element accumulation order
// is unchanged vs the single-row loop, so results are bitwise
// identical.  The zero skip (post-ReLU sparsity win) applies PER ROW
// even inside the 4-row block: a blocked `o += 0.0f * w` is NOT a
// skip when w holds NaN/Inf (0·NaN = NaN), so rows with a zero ride a
// per-row fallback while the common all-live case keeps the fused
// 4-chain loop.
// At namespace scope (declared in unit.h) so the component tests can
// pit the blocked/remainder/zero-skip paths against a naive loop.
void Gemm(const float* x, const float* w, const float* b, float* out,
          int64_t m, int64_t k, int64_t n, Engine* engine) {
  engine->ParallelFor(m, [&](int64_t begin, int64_t end) {
    auto init_row = [&](float* orow) {
      if (b) std::memcpy(orow, b, n * sizeof(float));
      else std::memset(orow, 0, n * sizeof(float));
    };
    int64_t i = begin;
    for (; i + 4 <= end; i += 4) {
      float* o0 = out + i * n;
      float* o1 = o0 + n;
      float* o2 = o1 + n;
      float* o3 = o2 + n;
      init_row(o0); init_row(o1); init_row(o2); init_row(o3);
      const float* x0 = x + i * k;
      const float* x1 = x0 + k;
      const float* x2 = x1 + k;
      const float* x3 = x2 + k;
      for (int64_t kk = 0; kk < k; ++kk) {
        float v0 = x0[kk], v1 = x1[kk], v2 = x2[kk], v3 = x3[kk];
        bool z0 = v0 == 0.0f, z1 = v1 == 0.0f, z2 = v2 == 0.0f,
             z3 = v3 == 0.0f;
        if (z0 && z1 && z2 && z3) continue;
        const float* wrow = w + kk * n;
        if (!z0 && !z1 && !z2 && !z3) {
          // all four rows live: the vectorized 4-chain loop
          for (int64_t j = 0; j < n; ++j) {
            float wv = wrow[j];
            o0[j] += v0 * wv;
            o1[j] += v1 * wv;
            o2[j] += v2 * wv;
            o3[j] += v3 * wv;
          }
          continue;
        }
        // mixed: skip exactly the zero rows (bitwise-identical to the
        // single-row loop even for NaN/Inf weights)
        if (!z0) for (int64_t j = 0; j < n; ++j) o0[j] += v0 * wrow[j];
        if (!z1) for (int64_t j = 0; j < n; ++j) o1[j] += v1 * wrow[j];
        if (!z2) for (int64_t j = 0; j < n; ++j) o2[j] += v2 * wrow[j];
        if (!z3) for (int64_t j = 0; j < n; ++j) o3[j] += v3 * wrow[j];
      }
    }
    for (; i < end; ++i) {
      float* orow = out + i * n;
      init_row(orow);
      const float* xrow = x + i * k;
      for (int64_t kk = 0; kk < k; ++kk) {
        float xv = xrow[kk];
        if (xv == 0.0f) continue;
        const float* wrow = w + kk * n;
        for (int64_t j = 0; j < n; ++j) orow[j] += xv * wrow[j];
      }
    }
  });
}

namespace {

Shape ShapeOf(const Json& config, const char* key) {
  Shape s;
  for (const auto& d : config.at(key)->array) s.push_back(d->integer());
  return s;
}

// ---------------------------------------------------------------------

class All2AllUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray> arrays,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    act_ = ParseAct(config);
    softmax_ = config.has("is_softmax") && config.at("is_softmax")->boolean;
    weights_ = std::move(arrays.at("weights"));
    if (arrays.count("bias")) {
      bias_ = std::move(arrays.at("bias"));
      has_bias_ = true;
    }
    k_ = weights_.shape.at(0);
    n_ = weights_.shape.at(1);
    int64_t flat = 1;
    for (size_t i = 1; i < input_shape.size(); ++i) flat *= input_shape[i];
    if (flat != k_)
      throw std::runtime_error(
          "all2all: input " + std::to_string(flat) + " != weights rows " +
          std::to_string(k_));
    output_shape_ = {input_shape[0]};
    for (const auto& d : config.at("output_sample_shape")->array)
      output_shape_.push_back(d->integer());
    // the output side is the memory-unsafe one: the arena slice is
    // sized from output_shape_ but Gemm writes n_ floats per row
    if (NumElements(output_shape_) != input_shape[0] * n_)
      throw std::runtime_error(
          "all2all: output_sample_shape product != weights cols " +
          std::to_string(n_));
  }

  void Execute(const float* in, float* out, float*, Engine* engine) override {
    int64_t m = input_shape_[0];
    Gemm(in, weights_.data.data(),
         has_bias_ ? bias_.data.data() : nullptr, out, m, k_, n_, engine);
    engine->ParallelFor(m, [&](int64_t begin, int64_t end) {
      for (int64_t i = begin; i < end; ++i) {
        float* row = out + i * n_;
        if (softmax_) {
          float mx = row[0];
          for (int64_t j = 1; j < n_; ++j) mx = std::max(mx, row[j]);
          float sum = 0.0f;
          for (int64_t j = 0; j < n_; ++j) {
            row[j] = std::exp(row[j] - mx);
            sum += row[j];
          }
          for (int64_t j = 0; j < n_; ++j) row[j] /= sum;
        } else {
          ActRow(act_, row, n_);
        }
      }
    });
  }

 private:
  NpyArray weights_, bias_;
  bool has_bias_ = false;
  bool softmax_ = false;
  Act act_ = Act::kNone;
  int64_t k_ = 0, n_ = 0;
};

// input (B,H,W,C) × HWIO weights (ky,kx,C/g,K); padding (l,r,t,b),
// sliding (sx,sy), optional grouping g (AlexNet's grouped conv:
// output block i reads input channel group i); im2col into scratch
// then one sgemm per batch chunk per group.
class ConvUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray> arrays,
                  const Shape& input_shape) override {
    if (input_shape.size() != 4)
      throw std::runtime_error("conv: input must be rank 4 (NHWC)");
    input_shape_ = input_shape;
    act_ = ParseAct(config);
    weights_ = std::move(arrays.at("weights"));
    if (arrays.count("bias")) {
      bias_ = std::move(arrays.at("bias"));
      has_bias_ = true;
    }
    ky_ = weights_.shape.at(0);
    kx_ = weights_.shape.at(1);
    cin_ = weights_.shape.at(2);    // per-group fan-in
    k_ = weights_.shape.at(3);
    Shape pad = ShapeOf(config, "padding");
    left_ = pad[0]; right_ = pad[1]; top_ = pad[2]; bottom_ = pad[3];
    Shape slide = ShapeOf(config, "sliding");
    sx_ = slide[0]; sy_ = slide[1];
    if (config.has("grouping")) g_ = config.at("grouping")->integer();
    if (g_ < 1 || k_ % g_)
      throw std::runtime_error("conv: grouping must divide n_kernels");
    if (input_shape[3] != cin_ * g_)
      throw std::runtime_error("conv: channel mismatch");
    int64_t h = input_shape[1] + top_ + bottom_;
    int64_t w = input_shape[2] + left_ + right_;
    oh_ = (h - ky_) / sy_ + 1;
    ow_ = (w - kx_) / sx_ + 1;
    output_shape_ = {input_shape[0], oh_, ow_, k_};
  }

  int64_t ScratchFloats(int max_workers) const override {
    // one im2col patch matrix (oh*ow, ky*kx*cin) per concurrent chunk;
    // ParallelFor creates at most `workers` chunks, so `max_workers`
    // slots can never be oversubscribed.
    return oh_ * ow_ * ky_ * kx_ * cin_ * max_workers;
  }

  void Execute(const float* in, float* out, float* scratch,
               Engine* engine) override {
    int64_t batch = input_shape_[0];
    int64_t h = input_shape_[1], w = input_shape_[2];
    int64_t c_total = cin_ * g_;
    int64_t patch = ky_ * kx_ * cin_;
    int64_t rows = oh_ * ow_;
    int64_t kpg = k_ / g_;              // kernels per group
    std::atomic<int> slot_counter{0};
    engine->ParallelFor(batch, [&](int64_t begin, int64_t end) {
      int slot = slot_counter.fetch_add(1);
      float* cols = scratch + slot * rows * patch;
      for (int64_t b = begin; b < end; ++b) {
        const float* img = in + b * h * w * c_total;
        float* dst = out + b * rows * k_;
        for (int64_t r = 0; r < rows; ++r) {
          float* orow = dst + r * k_;
          if (has_bias_)
            std::memcpy(orow, bias_.data.data(), k_ * sizeof(float));
          else
            std::memset(orow, 0, k_ * sizeof(float));
        }
        for (int64_t gi = 0; gi < g_; ++gi) {
          // im2col over this group's channel slice, implicit zero pad
          const float* gimg = img + gi * cin_;
          for (int64_t oy = 0; oy < oh_; ++oy) {
            for (int64_t ox = 0; ox < ow_; ++ox) {
              float* dstp = cols + (oy * ow_ + ox) * patch;
              for (int64_t iy = 0; iy < ky_; ++iy) {
                int64_t y = oy * sy_ + iy - top_;
                for (int64_t ix = 0; ix < kx_; ++ix) {
                  int64_t x = ox * sx_ + ix - left_;
                  float* cell = dstp + (iy * kx_ + ix) * cin_;
                  if (y < 0 || y >= h || x < 0 || x >= w) {
                    std::memset(cell, 0, cin_ * sizeof(float));
                  } else {
                    std::memcpy(cell, gimg + (y * w + x) * c_total,
                                cin_ * sizeof(float));
                  }
                }
              }
            }
          }
          // (rows, patch) × (patch, kpg) into output columns
          // [gi·kpg, (gi+1)·kpg) — weights HWIO are row-major
          // (ky·kx·cin, k) and output block gi owns that column slice
          for (int64_t r = 0; r < rows; ++r) {
            float* orow = dst + r * k_ + gi * kpg;
            const float* crow = cols + r * patch;
            for (int64_t p = 0; p < patch; ++p) {
              float v = crow[p];
              if (v == 0.0f) continue;
              const float* wrow =
                  weights_.data.data() + p * k_ + gi * kpg;
              for (int64_t j = 0; j < kpg; ++j) orow[j] += v * wrow[j];
            }
          }
        }
        for (int64_t r = 0; r < rows; ++r)
          ActRow(act_, dst + r * k_, k_);
      }
    });
  }

 private:
  NpyArray weights_, bias_;
  bool has_bias_ = false;
  Act act_ = Act::kNone;
  int64_t ky_ = 0, kx_ = 0, cin_ = 0, k_ = 0, g_ = 1;
  int64_t left_ = 0, right_ = 0, top_ = 0, bottom_ = 0;
  int64_t sx_ = 1, sy_ = 1;
  int64_t oh_ = 0, ow_ = 0;
};

class PoolingUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray>,
                  const Shape& input_shape) override {
    if (input_shape.size() != 4)
      throw std::runtime_error("pooling: input must be rank 4 (NHWC)");
    input_shape_ = input_shape;
    kind_ = config.at("kind")->string_value();
    kx_ = config.at("kx")->integer();
    ky_ = config.at("ky")->integer();
    Shape slide = ShapeOf(config, "sliding");
    sx_ = slide[0]; sy_ = slide[1];
    oh_ = (input_shape[1] - ky_) / sy_ + 1;
    ow_ = (input_shape[2] - kx_) / sx_ + 1;
    output_shape_ = {input_shape[0], oh_, ow_, input_shape[3]};
  }

  void Execute(const float* in, float* out, float*, Engine* engine) override {
    int64_t batch = input_shape_[0];
    int64_t h = input_shape_[1], w = input_shape_[2], c = input_shape_[3];
    engine->ParallelFor(batch, [&](int64_t begin, int64_t end) {
      std::vector<float> window(ky_ * kx_);
      for (int64_t b = begin; b < end; ++b) {
        const float* img = in + b * h * w * c;
        float* dst = out + b * oh_ * ow_ * c;
        for (int64_t oy = 0; oy < oh_; ++oy)
          for (int64_t ox = 0; ox < ow_; ++ox)
            for (int64_t ch = 0; ch < c; ++ch) {
              int nw = 0;
              for (int64_t iy = 0; iy < ky_; ++iy)
                for (int64_t ix = 0; ix < kx_; ++ix)
                  window[nw++] = img[((oy * sy_ + iy) * w +
                                      ox * sx_ + ix) * c + ch];
              dst[(oy * ow_ + ox) * c + ch] = Reduce(window);
            }
      }
    });
  }

 private:
  float Reduce(const std::vector<float>& window) const {
    if (kind_ == "max")
      return *std::max_element(window.begin(), window.end());
    if (kind_ == "avg") {
      float s = 0.0f;
      for (float v : window) s += v;
      return s / window.size();
    }
    if (kind_ == "maxabs") {
      float best = window[0];
      for (float v : window)
        if (std::fabs(v) > std::fabs(best)) best = v;
      return best;
    }
    // stochastic{,abs}: test-time expectation Σ pᵢ·xᵢ, pᵢ ∝ |xᵢ|
    float mag_sum = 0.0f;
    for (float v : window) mag_sum += std::fabs(v);
    mag_sum = std::max(mag_sum, 1e-12f);
    float acc = 0.0f;
    bool abs_out = kind_ == "stochasticabs";
    for (float v : window) {
      float p = std::fabs(v) / mag_sum;
      acc += p * (abs_out ? std::fabs(v) : v);
    }
    return acc;
  }

  std::string kind_;
  int64_t kx_ = 2, ky_ = 2, sx_ = 2, sy_ = 2;
  int64_t oh_ = 0, ow_ = 0;
};

// across-channel LRN: x / (k + α·Σ_{n-window} x²)^β  (last axis window)
class LrnUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray>,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    output_shape_ = input_shape;
    alpha_ = static_cast<float>(config.at("alpha")->num());
    beta_ = static_cast<float>(config.at("beta")->num());
    k_ = static_cast<float>(config.at("k")->num());
    n_ = config.at("n")->integer();
  }

  void Execute(const float* in, float* out, float*, Engine* engine) override {
    int64_t c = input_shape_.back();
    int64_t rows = NumElements(input_shape_) / c;
    int64_t half = n_ / 2;
    engine->ParallelFor(rows, [&](int64_t begin, int64_t end) {
      for (int64_t r = begin; r < end; ++r) {
        const float* x = in + r * c;
        float* y = out + r * c;
        for (int64_t j = 0; j < c; ++j) {
          float acc = 0.0f;
          // window [j-half, j-half+n) clipped to [0, c)
          for (int64_t d = 0; d < n_; ++d) {
            int64_t idx = j - half + d;
            if (idx >= 0 && idx < c) acc += x[idx] * x[idx];
          }
          y[j] = x[j] / std::pow(k_ + alpha_ * acc, beta_);
        }
      }
    });
  }

 private:
  float alpha_ = 1e-4f, beta_ = 0.75f, k_ = 2.0f;
  int64_t n_ = 5;
};

class ActivationUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray>,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    output_shape_ = input_shape;
    func_ = config.at("func")->string_value();
    if (config.has("k")) k_ = static_cast<float>(config.at("k")->num());
    static const char* known[] = {"tanh", "sigmoid", "relu",
                                  "strict_relu", "log", "tanhlog",
                                  "sincos", "mul"};
    bool ok = false;
    for (const char* f : known) ok |= (func_ == f);
    if (!ok)  // validate here: Execute runs on pool threads where a
              // throw would std::terminate
      throw std::runtime_error("unknown func " + func_);
  }

  void Execute(const float* in, float* out, float*, Engine* engine) override {
    int64_t total = NumElements(input_shape_);
    int64_t last = input_shape_.back();
    engine->ParallelFor(total, [&](int64_t begin, int64_t end) {
      for (int64_t i = begin; i < end; ++i) {
        float x = in[i];
        if (func_ == "tanh") {
          out[i] = 1.7159f * std::tanh(0.6666f * x);
        } else if (func_ == "sigmoid") {
          out[i] = 1.0f / (1.0f + std::exp(-x));
        } else if (func_ == "relu") {
          out[i] = std::log1p(std::exp(std::min(x, 30.0f)));
        } else if (func_ == "strict_relu") {
          out[i] = std::max(x, 0.0f);
        } else if (func_ == "log") {
          out[i] = std::log(x + std::sqrt(x * x + 1.0f));
        } else if (func_ == "tanhlog") {
          float t = 1.7159f * std::tanh(0.6666f * x);
          out[i] = std::fabs(t) <= 1.7159f * 0.6666f
              ? t
              : std::copysign(
                    std::log(std::fabs(x * 0.6666f * 1.7159f) + 1.0f), x);
        } else if (func_ == "sincos") {
          out[i] = (i % last) % 2 == 1 ? std::sin(x) : std::cos(x);
        } else {  // "mul" (validated in Initialize)
          out[i] = x * k_;
        }
      }
    });
  }

 private:
  std::string func_;
  float k_ = 1.0f;
};

class DropoutUnit : public Unit {  // inference = identity
 public:
  void Initialize(const Json&, std::map<std::string, NpyArray>,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    output_shape_ = input_shape;
  }
  void Execute(const float* in, float* out, float*, Engine*) override {
    std::memcpy(out, in, NumElements(input_shape_) * sizeof(float));
  }
};

class MeanDispUnit : public Unit {  // (x - mean) · disp
 public:
  void Initialize(const Json&, std::map<std::string, NpyArray> arrays,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    output_shape_ = input_shape;
    mean_ = std::move(arrays.at("mean"));
    disp_ = std::move(arrays.at("disp"));
    if (mean_.size() != disp_.size())
      throw std::runtime_error("mean_disp: mean/disp size mismatch");
    int64_t sample = NumElements(input_shape) / input_shape[0];
    if (mean_.size() != sample)
      throw std::runtime_error("mean_disp: sample size mismatch");
  }

  void Execute(const float* in, float* out, float*, Engine* engine) override {
    int64_t batch = input_shape_[0];
    int64_t sample = mean_.size();
    engine->ParallelFor(batch, [&](int64_t begin, int64_t end) {
      for (int64_t b = begin; b < end; ++b) {
        const float* x = in + b * sample;
        float* y = out + b * sample;
        for (int64_t i = 0; i < sample; ++i)
          y[i] = (x[i] - mean_.data[i]) * disp_.data[i];
      }
    });
  }

 private:
  NpyArray mean_, disp_;
};

// LSTM / simple RNN: scan over T, one fused-gate GEMM per step.
// Weight layout matches veles_tpu/znicz/rnn.py: (D+H, G·H) with gate
// order i,f,g,o (G=4) for LSTM, G=1 tanh cell for RNN.  Same math as
// the package.py numpy golden runner (accumulation order differs, so
// agreement is to f32 rounding, not bit-for-bit).
class LstmUnit : public Unit {
 public:
  explicit LstmUnit(bool lstm) : lstm_(lstm) {}

  void Initialize(const Json& config, std::map<std::string, NpyArray> arrays,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    if (input_shape.size() != 3)
      throw std::runtime_error("lstm/rnn: input must be (B, T, D)");
    h_ = config.at("hidden_units")->integer();
    if (h_ <= 0) throw std::runtime_error("lstm/rnn: hidden_units <= 0");
    last_only_ =
        config.has("last_only") && config.at("last_only")->boolean;
    weights_ = std::move(arrays.at("weights"));
    if (arrays.count("bias")) {
      bias_ = std::move(arrays.at("bias"));
      has_bias_ = true;
    }
    d_ = input_shape[2];
    const int64_t gates = NumGates();
    if (weights_.shape.size() != 2 || weights_.shape[0] != d_ + h_ ||
        weights_.shape[1] != gates * h_)
      throw std::runtime_error(
          "lstm/rnn: weights must be (D+H, G*H), got (" +
          std::to_string(weights_.shape.empty() ? 0 : weights_.shape[0]) +
          ", " +
          std::to_string(weights_.shape.size() < 2 ? 0
                                                   : weights_.shape[1]) +
          ")");
    if (has_bias_ && NumElements(bias_.shape) != gates * h_)
      throw std::runtime_error("lstm/rnn: bias must be (G*H,)");
    if (last_only_)
      output_shape_ = {input_shape[0], h_};
    else
      output_shape_ = {input_shape[0], input_shape[1], h_};
  }

  int64_t ScratchFloats(int) const override {
    const int64_t b = input_shape_[0];
    // concat (B, D+H) + gate pre-activations (B, G·H) + h + c (B, H)
    return b * (d_ + h_) + b * NumGates() * h_ + 2 * b * h_;
  }

  void Execute(const float* in, float* out, float* scratch,
               Engine* engine) override {
    const int64_t b = input_shape_[0];
    const int64_t t_len = input_shape_[1];
    const int64_t gates = NumGates();
    float* concat = scratch;                  // (B, D+H)
    float* z = concat + b * (d_ + h_);        // (B, G·H)
    float* h = z + b * gates * h_;            // (B, H)
    float* c = h + b * h_;                    // (B, H)
    std::memset(h, 0, static_cast<size_t>(b) * h_ * sizeof(float));
    std::memset(c, 0, static_cast<size_t>(b) * h_ * sizeof(float));
    for (int64_t t = 0; t < t_len; ++t) {
      engine->ParallelFor(b, [&](int64_t begin, int64_t end) {
        for (int64_t i = begin; i < end; ++i) {
          float* row = concat + i * (d_ + h_);
          std::memcpy(row, in + (i * t_len + t) * d_,
                      static_cast<size_t>(d_) * sizeof(float));
          std::memcpy(row + d_, h + i * h_,
                      static_cast<size_t>(h_) * sizeof(float));
        }
      });
      Gemm(concat, weights_.data.data(),
           has_bias_ ? bias_.data.data() : nullptr, z, b, d_ + h_,
           gates * h_, engine);
      engine->ParallelFor(b, [&](int64_t begin, int64_t end) {
        for (int64_t i = begin; i < end; ++i) {
          const float* zrow = z + i * gates * h_;
          float* hrow = h + i * h_;
          float* crow = c + i * h_;
          if (lstm_) {
            for (int64_t j = 0; j < h_; ++j) {
              const float ig = Sigmoid(zrow[j]);
              const float fg = Sigmoid(zrow[h_ + j]);
              const float gg = std::tanh(zrow[2 * h_ + j]);
              const float og = Sigmoid(zrow[3 * h_ + j]);
              crow[j] = fg * crow[j] + ig * gg;
              hrow[j] = og * std::tanh(crow[j]);
            }
          } else {
            for (int64_t j = 0; j < h_; ++j) hrow[j] = std::tanh(zrow[j]);
          }
          if (!last_only_)
            std::memcpy(out + (i * t_len + t) * h_, hrow,
                        static_cast<size_t>(h_) * sizeof(float));
        }
      });
    }
    if (last_only_)
      std::memcpy(out, h, static_cast<size_t>(b) * h_ * sizeof(float));
  }

 private:
  static float Sigmoid(float v) { return 1.0f / (1.0f + std::exp(-v)); }
  int64_t NumGates() const { return lstm_ ? 4 : 1; }

  NpyArray weights_, bias_;
  bool has_bias_ = false;
  bool lstm_ = true;
  bool last_only_ = false;
  int64_t d_ = 0, h_ = 0;
};

// Transposed convolution (deconv): dilate the input by the stride,
// pad with (k-1-p) per edge, correlate (no kernel flip) — the same
// math as package.py's _np_deconv and lax.conv_transpose/HWOI.
// Weights share the paired conv's (ky, kx, C, K) layout; no bias.
class DeconvUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray> arrays,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    if (input_shape.size() != 4)
      throw std::runtime_error("deconv: input must be (B, H, W, K)");
    act_ = ParseAct(config);
    weights_ = std::move(arrays.at("weights"));
    if (weights_.shape.size() != 4)
      throw std::runtime_error("deconv: weights must be (ky, kx, C, K)");
    ky_ = weights_.shape[0];
    kx_ = weights_.shape[1];
    c_out_ = weights_.shape[2];
    if (weights_.shape[3] != input_shape[3])
      throw std::runtime_error("deconv: weights K != input channels");
    Shape pad = ShapeOf(config, "padding");
    left_ = pad[0]; right_ = pad[1]; top_ = pad[2]; bottom_ = pad[3];
    Shape slide = ShapeOf(config, "sliding");
    sx_ = slide[0]; sy_ = slide[1];
    if (left_ < 0 || right_ < 0 || top_ < 0 || bottom_ < 0 ||
        left_ >= kx_ || right_ >= kx_ || top_ >= ky_ || bottom_ >= ky_)
      throw std::runtime_error(
          "deconv: forward padding must be within [0, kernel) — "
          "negative transpose pads (crops) are not supported");
    hp_ = (input_shape[1] - 1) * sy_ + 1 + (ky_ - 1 - top_) +
          (ky_ - 1 - bottom_);
    wp_ = (input_shape[2] - 1) * sx_ + 1 + (kx_ - 1 - left_) +
          (kx_ - 1 - right_);
    if (hp_ < ky_ || wp_ < kx_)
      throw std::runtime_error("deconv: padding exceeds kernel extent");
    output_shape_ = {input_shape[0], hp_ - ky_ + 1, wp_ - kx_ + 1,
                     c_out_};
  }

  int64_t ScratchFloats(int) const override {
    // dilated+padded input, one batch sample at a time per worker is
    // not needed: the buffer is shared, written disjointly per sample
    return input_shape_[0] * hp_ * wp_ * input_shape_[3];
  }

  void Execute(const float* in, float* out, float* scratch,
               Engine* engine) override {
    const int64_t b = input_shape_[0], h = input_shape_[1],
                  w = input_shape_[2], k = input_shape_[3];
    const int64_t out_h = output_shape_[1], out_w = output_shape_[2];
    const int64_t pt = ky_ - 1 - top_, pl = kx_ - 1 - left_;
    std::memset(scratch, 0,
                static_cast<size_t>(b) * hp_ * wp_ * k * sizeof(float));
    engine->ParallelFor(b, [&](int64_t begin, int64_t end) {
      for (int64_t i = begin; i < end; ++i)
        for (int64_t ih = 0; ih < h; ++ih)
          for (int64_t iw = 0; iw < w; ++iw)
            std::memcpy(scratch + ((i * hp_ + pt + ih * sy_) * wp_ +
                                   pl + iw * sx_) * k,
                        in + ((i * h + ih) * w + iw) * k,
                        static_cast<size_t>(k) * sizeof(float));
    });
    engine->ParallelFor(b * out_h, [&](int64_t begin, int64_t end) {
      for (int64_t row = begin; row < end; ++row) {
        const int64_t i = row / out_h, oh = row % out_h;
        for (int64_t ow = 0; ow < out_w; ++ow) {
          float* orow = out + ((i * out_h + oh) * out_w + ow) * c_out_;
          std::memset(orow, 0,
                      static_cast<size_t>(c_out_) * sizeof(float));
          for (int64_t dy = 0; dy < ky_; ++dy)
            for (int64_t dx = 0; dx < kx_; ++dx) {
              const float* xrow = scratch +
                  ((i * hp_ + oh + dy) * wp_ + ow + dx) * k;
              const float* wrow = weights_.data.data() +
                  (dy * kx_ + dx) * c_out_ * k;
              for (int64_t c = 0; c < c_out_; ++c) {
                float acc = 0.0f;
                const float* wc = wrow + c * k;
                for (int64_t kk = 0; kk < k; ++kk)
                  acc += xrow[kk] * wc[kk];
                orow[c] += acc;
              }
            }
          ActRow(act_, orow, c_out_);
        }
      }
    });
  }

 private:
  NpyArray weights_;
  Act act_ = Act::kNone;
  int64_t ky_ = 0, kx_ = 0, c_out_ = 0;
  int64_t left_ = 0, right_ = 0, top_ = 0, bottom_ = 0;
  int64_t sx_ = 1, sy_ = 1;
  int64_t hp_ = 0, wp_ = 0;
};

// Spatial crop (cutter): window (y, x, h, w).
class CutterUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray>,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    if (input_shape.size() != 4)
      throw std::runtime_error("cutter: input must be (B, H, W, C)");
    const auto& win = config.at("window")->array;
    y_ = win.at(0)->integer();
    x_ = win.at(1)->integer();
    h_ = win.at(2)->integer();
    w_ = win.at(3)->integer();
    if (y_ < 0 || x_ < 0 || y_ + h_ > input_shape[1] ||
        x_ + w_ > input_shape[2])
      throw std::runtime_error("cutter: window outside input");
    output_shape_ = {input_shape[0], h_, w_, input_shape[3]};
  }

  void Execute(const float* in, float* out, float*,
               Engine* engine) override {
    const int64_t b = input_shape_[0], ih = input_shape_[1],
                  iw = input_shape_[2], c = input_shape_[3];
    engine->ParallelFor(b, [&](int64_t begin, int64_t end) {
      for (int64_t i = begin; i < end; ++i)
        for (int64_t r = 0; r < h_; ++r)
          std::memcpy(out + ((i * h_ + r) * w_) * c,
                      in + ((i * ih + y_ + r) * iw + x_) * c,
                      static_cast<size_t>(w_) * c * sizeof(float));
    });
  }

 private:
  int64_t y_ = 0, x_ = 0, h_ = 0, w_ = 0;
};

// Contiguous channel slice (channel_splitter): start/count over the
// trailing axis of an NHWC tensor.
class ChannelSplitterUnit : public Unit {
 public:
  void Initialize(const Json& config, std::map<std::string, NpyArray>,
                  const Shape& input_shape) override {
    input_shape_ = input_shape;
    if (input_shape.empty())
      throw std::runtime_error("channel_splitter: scalar input");
    const int64_t channels = input_shape.back();
    start_ = config.at("start")->integer();
    count_ = config.has("count") && !config.at("count")->is_null()
                 ? config.at("count")->integer()
                 : channels - start_;
    if (start_ < 0 || count_ <= 0 || start_ + count_ > channels)
      throw std::runtime_error("channel_splitter: slice out of range");
    output_shape_ = input_shape;
    output_shape_.back() = count_;
  }

  void Execute(const float* in, float* out, float*,
               Engine* engine) override {
    const int64_t channels = input_shape_.back();
    const int64_t rows = NumElements(input_shape_) / channels;
    engine->ParallelFor(rows, [&](int64_t begin, int64_t end) {
      for (int64_t r = begin; r < end; ++r)
        std::memcpy(out + r * count_, in + r * channels + start_,
                    static_cast<size_t>(count_) * sizeof(float));
    });
  }

 private:
  int64_t start_ = 0, count_ = 0;
};

}  // namespace

UnitFactory& UnitFactory::Instance() {
  static UnitFactory factory;
  return factory;
}

void UnitFactory::Register(const std::string& type, Creator creator) {
  creators_[type] = std::move(creator);
}

std::unique_ptr<Unit> UnitFactory::Create(const std::string& type) const {
  auto it = creators_.find(type);
  if (it == creators_.end())
    throw std::runtime_error("no unit registered for type " + type);
  std::unique_ptr<Unit> unit = it->second(type);
  unit->set_name(type);
  return unit;
}

std::vector<std::string> UnitFactory::Types() const {
  std::vector<std::string> out;
  for (const auto& kv : creators_) out.push_back(kv.first);
  return out;
}

void RegisterStandardUnits() {
  UnitFactory& f = UnitFactory::Instance();
  auto reg = [&f](std::initializer_list<const char*> names, auto maker) {
    for (const char* n : names)
      f.Register(n, [maker](const std::string&) -> std::unique_ptr<Unit> {
        return maker();
      });
  };
  reg({"all2all", "all2all_tanh", "all2all_sigmoid", "all2all_relu",
       "all2all_strict_relu", "softmax"},
      [] { return std::make_unique<All2AllUnit>(); });
  reg({"conv", "conv_tanh", "conv_sigmoid", "conv_relu",
       "conv_strict_relu"},
      [] { return std::make_unique<ConvUnit>(); });
  reg({"max_pooling", "maxabs_pooling", "avg_pooling",
       "stochastic_pooling", "stochasticabs_pooling"},
      [] { return std::make_unique<PoolingUnit>(); });
  reg({"lrn"}, [] { return std::make_unique<LrnUnit>(); });
  reg({"activation_tanh", "activation_sigmoid", "activation_relu",
       "activation_strict_relu", "activation_log", "activation_tanhlog",
       "activation_sincos", "activation_mul"},
      [] { return std::make_unique<ActivationUnit>(); });
  reg({"dropout"}, [] { return std::make_unique<DropoutUnit>(); });
  reg({"mean_disp"}, [] { return std::make_unique<MeanDispUnit>(); });
  reg({"lstm"}, [] { return std::make_unique<LstmUnit>(true); });
  reg({"rnn"}, [] { return std::make_unique<LstmUnit>(false); });
  reg({"deconv"}, [] { return std::make_unique<DeconvUnit>(); });
  reg({"cutter"}, [] { return std::make_unique<CutterUnit>(); });
  reg({"channel_splitter"},
      [] { return std::make_unique<ChannelSplitterUnit>(); });
}

}  // namespace veles_native
