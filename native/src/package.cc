#include "package.h"

#include "logging.h"

#include <dirent.h>
#include <sys/stat.h>
#include <zlib.h>

#include <cstring>
#include <fstream>
#include <stdexcept>

namespace veles_native {

namespace {

std::vector<uint8_t> ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error("cannot open " + path);
  f.seekg(0, std::ios::end);
  std::vector<uint8_t> out(static_cast<size_t>(f.tellg()));
  f.seekg(0);
  f.read(reinterpret_cast<char*>(out.data()), out.size());
  return out;
}

uint32_t Le32(const uint8_t* p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}
uint16_t Le16(const uint8_t* p) { return p[0] | (p[1] << 8); }

// Per-entry allocation cap: entry sizes come from untrusted package
// headers; without a cap a crafted archive OOMs the runtime before any
// content validation runs.
constexpr size_t kMaxEntryBytes = size_t(1) << 30;  // 1 GiB

std::vector<uint8_t> InflateRaw(const uint8_t* src, size_t src_len,
                                size_t dst_len) {
  if (dst_len > kMaxEntryBytes)
    throw std::runtime_error("zip: entry exceeds allocation cap");
  std::vector<uint8_t> out(dst_len);
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK)  // raw deflate (no zlib header)
    throw std::runtime_error("zip: inflateInit2 failed");
  zs.next_in = const_cast<uint8_t*>(src);
  zs.avail_in = static_cast<uInt>(src_len);
  zs.next_out = out.data();
  zs.avail_out = static_cast<uInt>(dst_len);
  int rc = inflate(&zs, Z_FINISH);
  inflateEnd(&zs);
  if (rc != Z_STREAM_END)
    throw std::runtime_error("zip: inflate failed rc=" + std::to_string(rc));
  return out;
}

}  // namespace

FileMap ReadZip(const std::vector<uint8_t>& blob) {
  // locate End Of Central Directory (scan back for PK\5\6)
  if (blob.size() < 22) throw std::runtime_error("zip: too small");
  size_t eocd = std::string::npos;
  for (size_t i = blob.size() - 22; ; --i) {
    if (blob[i] == 0x50 && blob[i + 1] == 0x4B && blob[i + 2] == 0x05 &&
        blob[i + 3] == 0x06) {
      eocd = i;
      break;
    }
    if (i == 0 || blob.size() - i > 22 + 65536) break;
  }
  if (eocd == std::string::npos)
    throw std::runtime_error("zip: no end-of-central-directory");
  uint16_t entries = Le16(&blob[eocd + 10]);
  uint32_t cd_at = Le32(&blob[eocd + 16]);

  FileMap files;
  size_t p = cd_at;
  for (uint16_t e = 0; e < entries; ++e) {
    if (p + 46 > blob.size() || Le32(&blob[p]) != 0x02014B50)
      throw std::runtime_error("zip: bad central directory entry");
    uint16_t method = Le16(&blob[p + 10]);
    uint32_t csize = Le32(&blob[p + 20]);
    uint32_t usize = Le32(&blob[p + 24]);
    uint16_t name_len = Le16(&blob[p + 28]);
    uint16_t extra_len = Le16(&blob[p + 30]);
    uint16_t comment_len = Le16(&blob[p + 32]);
    uint32_t local_at = Le32(&blob[p + 42]);
    std::string name(reinterpret_cast<const char*>(&blob[p + 46]), name_len);
    p += 46 + name_len + extra_len + comment_len;

    // local header: its own name/extra lengths may differ from CD's
    if (local_at + 30 > blob.size() || Le32(&blob[local_at]) != 0x04034B50)
      throw std::runtime_error("zip: bad local header for " + name);
    uint16_t lname = Le16(&blob[local_at + 26]);
    uint16_t lextra = Le16(&blob[local_at + 28]);
    size_t data_at = local_at + 30 + lname + lextra;
    if (data_at + csize > blob.size())
      throw std::runtime_error("zip: truncated data for " + name);
    if (name.empty() || name.back() == '/') continue;  // directory entry
    if (method == 0) {
      files[name].assign(blob.begin() + data_at,
                         blob.begin() + data_at + csize);
    } else if (method == 8) {
      files[name] = InflateRaw(&blob[data_at], csize, usize);
    } else {
      throw std::runtime_error("zip: unsupported method " +
                               std::to_string(method) + " for " + name);
    }
  }
  return files;
}

FileMap ReadTarGz(const std::string& path) {
  gzFile gz = gzopen(path.c_str(), "rb");
  if (!gz) throw std::runtime_error("cannot open " + path);
  FileMap files;
  uint8_t block[512];
  while (true) {
    int n = gzread(gz, block, 512);
    if (n == 0) break;  // clean EOF
    if (n != 512) { gzclose(gz); throw std::runtime_error("tar: short read"); }
    bool all_zero = true;
    for (int i = 0; i < 512; ++i) all_zero &= (block[i] == 0);
    if (all_zero) continue;  // end-of-archive padding
    char name[257] = {0};
    std::memcpy(name, block, 100);
    char prefix[156] = {0};
    std::memcpy(prefix, block + 345, 155);
    std::string full = prefix[0]
        ? std::string(prefix) + "/" + name : std::string(name);
    char size_field[13] = {0};
    std::memcpy(size_field, block + 124, 12);
    size_t size = std::strtoull(size_field, nullptr, 8);
    if (size > kMaxEntryBytes) {
      gzclose(gz);
      throw std::runtime_error("tar: entry exceeds allocation cap");
    }
    char type = block[156];
    std::vector<uint8_t> data(size);
    size_t got = 0;
    while (got < size) {
      int r = gzread(gz, data.data() + got,
                     static_cast<unsigned>(size - got));
      if (r <= 0) { gzclose(gz); throw std::runtime_error("tar: truncated"); }
      got += r;
    }
    size_t pad = (512 - size % 512) % 512;
    if (pad) {
      uint8_t skip[512];
      if (gzread(gz, skip, static_cast<unsigned>(pad)) !=
          static_cast<int>(pad)) {
        gzclose(gz);
        throw std::runtime_error("tar: bad padding");
      }
    }
    if (type == '0' || type == 0) files[full] = std::move(data);
  }
  gzclose(gz);
  return files;
}

FileMap LoadPackage(const std::string& path) {
  VN_DEBUG("package", "loading %s", path.c_str());
  struct stat st;
  if (stat(path.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) {
    FileMap files;
    DIR* dir = opendir(path.c_str());
    if (!dir) throw std::runtime_error("cannot open dir " + path);
    while (dirent* ent = readdir(dir)) {
      std::string name = ent->d_name;
      if (name == "." || name == "..") continue;
      std::string full = path + "/" + name;
      if (stat(full.c_str(), &st) == 0 && S_ISREG(st.st_mode))
        files[name] = ReadFile(full);
    }
    closedir(dir);
    return files;
  }
  auto ends_with = [&](const char* suffix) {
    size_t n = strlen(suffix);
    return path.size() >= n &&
           path.compare(path.size() - n, n, suffix) == 0;
  };
  if (ends_with(".zip")) return ReadZip(ReadFile(path));
  if (ends_with(".tar.gz") || ends_with(".tgz")) return ReadTarGz(path);
  throw std::runtime_error("unknown package format: " + path);
}

}  // namespace veles_native
