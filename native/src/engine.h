// Engine: thread-pool scheduler for unit execution.
// Role parity: libVeles Engine (inc/veles/engine.h:43-60 — Schedule()
// abstraction + finish callbacks) and its thread pool (src/thread_pool.h).
// Adds ParallelFor, the primitive the compute units use to split batch
// rows across workers.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace veles_native {

class Engine {
 public:
  explicit Engine(int workers = 0);
  ~Engine();

  // Asynchronously runs `fn` on a worker (libVeles Engine::Schedule).
  void Schedule(std::function<void()> fn);

  // Blocks until every scheduled task has finished.
  void Wait();

  // Splits [0, count) into contiguous chunks across workers and blocks
  // until all are done. Falls back to inline execution when the pool has
  // a single worker or the range is tiny.
  void ParallelFor(int64_t count,
                   const std::function<void(int64_t, int64_t)>& body);

  int workers() const { return static_cast<int>(threads_.size()); }

 private:
  void WorkerLoop();

  std::vector<std::thread> threads_;
  std::queue<std::function<void()>> queue_;
  std::mutex mutex_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  int active_ = 0;
  bool stop_ = false;
};

}  // namespace veles_native
