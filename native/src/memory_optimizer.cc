#include "memory_optimizer.h"

#include <algorithm>

namespace veles_native {

int64_t MemoryOptimizer::Optimize(std::vector<MemoryNode>* nodes) {
  std::vector<size_t> order(nodes->size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = i;
  std::sort(order.begin(), order.end(), [&](size_t a, size_t b) {
    return (*nodes)[a].size > (*nodes)[b].size;
  });

  int64_t total = 0;
  for (size_t idx : order) {
    MemoryNode& node = (*nodes)[idx];
    // collect space intervals already taken by time-overlapping nodes
    std::vector<std::pair<int64_t, int64_t>> taken;
    for (const MemoryNode& other : *nodes) {
      if (&other == &node || other.offset < 0) continue;
      bool time_overlap = !(other.time_end < node.time_start ||
                            node.time_end < other.time_start);
      if (time_overlap)
        taken.emplace_back(other.offset, other.offset + other.size);
    }
    std::sort(taken.begin(), taken.end());
    // first-fit: earliest gap large enough
    int64_t at = 0;
    for (const auto& iv : taken) {
      if (at + node.size <= iv.first) break;
      at = std::max(at, iv.second);
    }
    node.offset = at;
    total = std::max(total, at + node.size);
  }
  return total;
}

}  // namespace veles_native
