#include "workflow.h"

#include <cstring>
#include <stdexcept>

#include "json.h"
#include "logging.h"
#include "memory_optimizer.h"
#include "npy.h"

namespace veles_native {

namespace {
constexpr const char* kContents = "contents.json";
}

Workflow::Workflow(const std::string& path) : engine_(0) {
  RegisterStandardUnits();
  files_ = LoadPackage(path);
  auto it = files_.find(kContents);
  if (it == files_.end())
    throw std::runtime_error("package has no contents.json");
  contents_ = JsonParser::Parse(
      std::string(it->second.begin(), it->second.end()));
  int64_t fmt = contents_->at("format_version")->integer();
  // v2 = int8 quantized packages (this loader dequantizes at load)
  if (fmt != 1 && fmt != 2)
    throw std::runtime_error("unsupported package format_version");
  name_ = contents_->has("name")
      ? contents_->at("name")->string_value() : "model";
  JsonPtr ishape = contents_->get("input_shape");
  if (ishape && !ishape->is_null())
    for (const auto& d : ishape->array)
      package_input_shape_.push_back(d->integer());
  VN_INFO("workflow", "loaded package %s: model '%s', %zu files",
          path.c_str(), name_.c_str(), files_.size());
}

void Workflow::Initialize(int64_t batch) {
  if (package_input_shape_.empty())
    throw std::runtime_error("package has no input_shape");
  input_shape_ = package_input_shape_;
  input_shape_[0] = batch;

  units_.clear();
  Shape shape = input_shape_;
  std::vector<MemoryNode> nodes;
  // node 0: the input buffer, live from step 0 (copy-in) through step 1
  // (the first unit's read); unit i's output node is produced at step
  // i+1 and read at step i+2; unit scratch is live only during its step.
  nodes.push_back({NumElements(shape), 0, 1, -1});
  std::vector<size_t> out_node_of, scratch_node_of;

  const auto& unit_list = contents_->at("units")->array;
  int step = 1;
  for (const auto& entry : unit_list) {
    const std::string& type = entry->at("type")->string_value();
    std::unique_ptr<Unit> unit = UnitFactory::Instance().Create(type);
    if (entry->has("name"))
      unit->set_name(entry->at("name")->string_value());
    std::map<std::string, NpyArray> arrays;
    for (const auto& kv : entry->at("arrays")->object) {
      auto file = files_.find(kv.second->string_value());
      if (file == files_.end())
        throw std::runtime_error("missing array file " +
                                 kv.second->string_value());
      arrays[kv.first] = LoadNpy(file->second.data(), file->second.size());
    }
    // int8 quantized packages (precision=8): a "<name>.scale"
    // companion holds per-output-channel (last axis) float scales;
    // dequantize at load so the units always see float weights —
    // the exact rule of package.py's dequantize_arrays
    for (auto it2 = arrays.begin(); it2 != arrays.end();) {
      const std::string& key = it2->first;
      static const std::string kSuffix = ".scale";
      if (key.size() <= kSuffix.size() ||
          key.compare(key.size() - kSuffix.size(), kSuffix.size(),
                      kSuffix) != 0) {
        ++it2;
        continue;
      }
      std::string base = key.substr(0, key.size() - kSuffix.size());
      auto tgt = arrays.find(base);
      if (tgt != arrays.end()) {
        const std::vector<float>& scale = it2->second.data;
        std::vector<float>& data = tgt->second.data;
        if (scale.empty() || tgt->second.shape.empty() ||
            static_cast<size_t>(tgt->second.shape.back()) !=
                scale.size() ||
            data.size() % scale.size() != 0)
          throw std::runtime_error("bad quantization scales for " +
                                   base);
        size_t c = scale.size();
        for (size_t i = 0; i < data.size(); ++i)
          data[i] *= scale[i % c];
      }
      it2 = arrays.erase(it2);
    }
    unit->Initialize(*entry->at("config"), std::move(arrays), shape);
    shape = unit->output_shape();

    // output: written at `step`, read at `step+1` (next unit, or the
    // final copy-out for the last unit)
    out_node_of.push_back(nodes.size());
    nodes.push_back({NumElements(shape), step, step + 1, -1});
    int64_t scratch = unit->ScratchFloats(engine_.workers());
    scratch_node_of.push_back(scratch ? nodes.size() : SIZE_MAX);
    if (scratch) nodes.push_back({scratch, step, step, -1});
    units_.push_back(std::move(unit));
    ++step;
  }

  int64_t total = MemoryOptimizer::Optimize(&nodes);
  VN_INFO("workflow",
          "initialized %zu units, batch %lld; arena %lld floats "
          "(%.1f MB after liveness packing)",
          units_.size(), static_cast<long long>(batch),
          static_cast<long long>(total),
          total * sizeof(float) / 1048576.0);
  arena_.assign(static_cast<size_t>(total), 0.0f);
  input_buf_ = arena_.data() + nodes[0].offset;
  unit_out_.clear();
  unit_scratch_.clear();
  for (size_t i = 0; i < units_.size(); ++i) {
    unit_out_.push_back(arena_.data() + nodes[out_node_of[i]].offset);
    unit_scratch_.push_back(
        scratch_node_of[i] == SIZE_MAX
            ? nullptr
            : arena_.data() + nodes[scratch_node_of[i]].offset);
  }
}

const Shape& Workflow::output_shape() const {
  if (units_.empty())
    throw std::runtime_error("workflow not initialized");
  return units_.back()->output_shape();
}

void Workflow::Run(const float* input, float* output) {
  if (units_.empty())
    throw std::runtime_error("workflow not initialized");
  std::memcpy(input_buf_, input,
              NumElements(input_shape_) * sizeof(float));
  const float* cur = input_buf_;
  for (size_t i = 0; i < units_.size(); ++i) {
    units_[i]->Execute(cur, unit_out_[i], unit_scratch_[i], &engine_);
    cur = unit_out_[i];
  }
  std::memcpy(output, cur,
              NumElements(output_shape()) * sizeof(float));
}

}  // namespace veles_native
