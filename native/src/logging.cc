#include "logging.h"

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>

namespace veles_native {

namespace {

LogLevel LevelFromEnv() {
  const char* env = std::getenv("VELES_NATIVE_LOG");
  if (!env) return kLogWarning;  // quiet by default, like a library
  if (!std::strcmp(env, "debug")) return kLogDebug;
  if (!std::strcmp(env, "info")) return kLogInfo;
  if (!std::strcmp(env, "warning")) return kLogWarning;
  if (!std::strcmp(env, "error")) return kLogError;
  if (!std::strcmp(env, "off")) return kLogOff;
  return kLogWarning;
}

LogLevel g_level = LevelFromEnv();
LogCallback g_callback = nullptr;
std::mutex g_mutex;

const char* LevelName(LogLevel level) {
  switch (level) {
    case kLogDebug: return "D";
    case kLogInfo: return "I";
    case kLogWarning: return "W";
    case kLogError: return "E";
    default: return "?";
  }
}

}  // namespace

void SetLogLevel(LogLevel level) {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_level = level;
}

LogLevel GetLogLevel() {
  std::lock_guard<std::mutex> lock(g_mutex);
  return g_level;
}

void SetLogCallback(LogCallback cb) {
  std::lock_guard<std::mutex> lock(g_mutex);
  g_callback = cb;
}

void LogMessage(LogLevel level, const char* component, const char* fmt,
                ...) {
  LogCallback cb;
  {
    std::lock_guard<std::mutex> lock(g_mutex);
    if (level < g_level) return;
    cb = g_callback;
  }
  char message[1024];
  va_list args;
  va_start(args, fmt);
  std::vsnprintf(message, sizeof(message), fmt, args);
  va_end(args);
  if (cb) {
    cb(static_cast<int>(level), component, message);
    return;
  }
  std::time_t now = std::time(nullptr);
  std::tm tm_buf;
  localtime_r(&now, &tm_buf);
  char stamp[32];
  std::strftime(stamp, sizeof(stamp), "%H:%M:%S", &tm_buf);
  std::lock_guard<std::mutex> lock(g_mutex);
  std::fprintf(stderr, "%s %s %s: %s\n", stamp, LevelName(level),
               component, message);
}

}  // namespace veles_native
