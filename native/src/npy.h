// .npy reader: header parse (v1.0/v2.0), little-endian f4/f2, C order.
// Role parity: libVeles NumpyArrayLoader
// (inc/veles/numpy_array_loader.h, src/numpy_array_loader.cc) — dtype and
// endianness checks, transposition rejection, aligned allocation.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace veles_native {

struct NpyArray {
  std::vector<int64_t> shape;
  std::vector<float> data;  // always widened to f32

  int64_t size() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
};

// Parses a .npy blob. Accepts dtypes <f4, <f2 (fp16 packages), |u1, <i4,
// <i8; everything is converted to float. Throws std::runtime_error on
// fortran_order=True or foreign endianness.
NpyArray LoadNpy(const uint8_t* bytes, size_t len);

}  // namespace veles_native
