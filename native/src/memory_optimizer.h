// Liveness-interval buffer packing: assigns every intermediate buffer an
// offset in one shared arena so that time-overlapping buffers never
// overlap in space, minimizing the arena size.
// Role parity: libVeles MemoryOptimizer (src/memory_optimizer.h:43-55,
// src/memory_node.h) — interval-graph packing of unit scratch buffers;
// Optimize() returns the total arena size.
#pragma once

#include <cstdint>
#include <vector>

namespace veles_native {

struct MemoryNode {
  int64_t size = 0;       // bytes (or any unit; offsets share it)
  int time_start = 0;     // first step the buffer is live (inclusive)
  int time_end = 0;       // last step the buffer is live (inclusive)
  int64_t offset = -1;    // output: assigned arena offset
};

class MemoryOptimizer {
 public:
  // Assigns node offsets; returns total arena size. Greedy first-fit on
  // size-descending order — optimal for chains, near-optimal for DAGs.
  static int64_t Optimize(std::vector<MemoryNode>* nodes);
};

}  // namespace veles_native
