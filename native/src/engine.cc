#include "engine.h"

#include <algorithm>

namespace veles_native {

Engine::Engine(int workers) {
  if (workers <= 0) {
    workers = static_cast<int>(std::thread::hardware_concurrency());
    if (workers <= 0) workers = 1;
  }
  threads_.reserve(workers);
  for (int i = 0; i < workers; ++i)
    threads_.emplace_back([this] { WorkerLoop(); });
}

Engine::~Engine() {
  {
    std::unique_lock<std::mutex> lock(mutex_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void Engine::WorkerLoop() {
  while (true) {
    std::function<void()> fn;
    {
      std::unique_lock<std::mutex> lock(mutex_);
      cv_.wait(lock, [this] { return stop_ || !queue_.empty(); });
      if (stop_ && queue_.empty()) return;
      fn = std::move(queue_.front());
      queue_.pop();
      ++active_;
    }
    fn();
    {
      std::unique_lock<std::mutex> lock(mutex_);
      --active_;
      if (queue_.empty() && active_ == 0) done_cv_.notify_all();
    }
  }
}

void Engine::Schedule(std::function<void()> fn) {
  {
    std::unique_lock<std::mutex> lock(mutex_);
    queue_.push(std::move(fn));
  }
  cv_.notify_one();
}

void Engine::Wait() {
  std::unique_lock<std::mutex> lock(mutex_);
  done_cv_.wait(lock, [this] { return queue_.empty() && active_ == 0; });
}

void Engine::ParallelFor(
    int64_t count, const std::function<void(int64_t, int64_t)>& body) {
  int n = workers();
  if (n <= 1 || count < 2) {
    body(0, count);
    return;
  }
  int64_t chunk = (count + n - 1) / n;
  for (int64_t begin = 0; begin < count; begin += chunk) {
    int64_t end = std::min(begin + chunk, count);
    Schedule([&body, begin, end] { body(begin, end); });
  }
  Wait();
}

}  // namespace veles_native
