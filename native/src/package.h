// Package reader: .zip (stored/deflate), .tar.gz and plain-directory
// layouts into an in-memory file map.
// Role parity: libVeles WorkflowArchive (src/workflow_archive.cc) which
// wraps libarchive; here ZIP central-directory + tar formats are decoded
// directly (deflate/gzip via zlib).
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

namespace veles_native {

using FileMap = std::map<std::string, std::vector<uint8_t>>;

// Loads a package by path; dispatches on suffix (.zip → ZIP, .tar.gz/.tgz
// → gzipped tar, anything that stats as a directory → per-file read).
FileMap LoadPackage(const std::string& path);

FileMap ReadZip(const std::vector<uint8_t>& blob);
FileMap ReadTarGz(const std::string& path);

}  // namespace veles_native
