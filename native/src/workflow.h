// Workflow: load a package, plan memory, run inference.
// Role parity: libVeles WorkflowLoader (src/workflow_loader.cc:41-133 —
// archive → unit creation → property assignment) + Workflow facade
// (inc/veles/workflow.h:72-116 — Initialize(input)/Run()) with the
// MemoryOptimizer arena pass from src/memory_optimizer.h:43-55.
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "engine.h"
#include "package.h"
#include "unit.h"

namespace veles_native {

class Workflow {
 public:
  // Loads + validates the package (contents.json checksum included).
  explicit Workflow(const std::string& path);

  // Builds units for a concrete batch size and packs the buffer arena.
  // Must be called before Run; re-call to change the batch geometry.
  void Initialize(int64_t batch);

  // input: NumElements(input_shape()) floats; output buffer must hold
  // NumElements(output_shape()) floats.
  void Run(const float* input, float* output);

  const Shape& input_shape() const { return input_shape_; }
  const Shape& output_shape() const;
  int64_t arena_floats() const { return arena_.size(); }
  const std::string& name() const { return name_; }
  size_t unit_count() const { return units_.size(); }

 private:
  FileMap files_;
  JsonPtr contents_;
  std::string name_;
  Shape package_input_shape_;   // as exported (batch included)
  Shape input_shape_;           // with the Initialize()-time batch
  std::vector<std::unique_ptr<Unit>> units_;
  std::vector<float*> unit_out_;      // arena pointer per unit output
  std::vector<float*> unit_scratch_;  // arena pointer per unit scratch
  float* input_buf_ = nullptr;
  std::vector<float> arena_;
  Engine engine_;
};

}  // namespace veles_native
