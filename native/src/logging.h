// Leveled logging for the native inference runtime.
//
// Parity target: libVeles' eina-log macro layer
// (/root/reference/libVeles/inc/veles/logger.h, src/logger.cc) —
// re-designed as a ~100-line dependency-free logger: level from the
// VELES_NATIVE_LOG env var (debug|info|warning|error|off), default
// stderr sink with timestamp + component tag, and an installable
// callback so the Python host (veles_tpu/native.py) can route messages
// into its own Logger stack.
#pragma once

namespace veles_native {

enum LogLevel {
  kLogDebug = 0,
  kLogInfo = 1,
  kLogWarning = 2,
  kLogError = 3,
  kLogOff = 4,
};

// callback receives (level, component, formatted message)
using LogCallback = void (*)(int level, const char* component,
                             const char* message);

void SetLogLevel(LogLevel level);
LogLevel GetLogLevel();
void SetLogCallback(LogCallback cb);  // nullptr restores stderr sink

void LogMessage(LogLevel level, const char* component, const char* fmt,
                ...) __attribute__((format(printf, 3, 4)));

}  // namespace veles_native

#define VN_DEBUG(comp, ...) \
  ::veles_native::LogMessage(::veles_native::kLogDebug, comp, \
                             __VA_ARGS__)
#define VN_INFO(comp, ...) \
  ::veles_native::LogMessage(::veles_native::kLogInfo, comp, \
                             __VA_ARGS__)
#define VN_WARNING(comp, ...) \
  ::veles_native::LogMessage(::veles_native::kLogWarning, comp, \
                             __VA_ARGS__)
#define VN_ERROR(comp, ...) \
  ::veles_native::LogMessage(::veles_native::kLogError, comp, \
                             __VA_ARGS__)
