// C API for the native inference runtime (consumed from Python via
// ctypes — pybind11 is not in this image; see veles_tpu/native.py).
#include <cstring>
#include <string>

#include "logging.h"
#include "workflow.h"

using veles_native::NumElements;
using veles_native::Workflow;

namespace {

void SetError(char* err, int errlen, const std::string& what) {
  if (err && errlen > 0) {
    std::strncpy(err, what.c_str(), errlen - 1);
    err[errlen - 1] = '\0';
  }
}

}  // namespace

extern "C" {

// 0=debug 1=info 2=warning 3=error 4=off (ref eina-log domains; the
// Python host mirrors veles_tpu.logger levels onto these)
void veles_native_set_log_level(int level) {
  if (level < 0 || level > 4) return;
  veles_native::SetLogLevel(static_cast<veles_native::LogLevel>(level));
}

// cb(level, component, message); nullptr restores the stderr sink.
void veles_native_set_log_callback(
    void (*cb)(int, const char*, const char*)) {
  veles_native::SetLogCallback(cb);
}

// Returns an opaque handle or nullptr (error text in err).
void* veles_native_load(const char* path, char* err, int errlen) {
  try {
    return new Workflow(path);
  } catch (const std::exception& e) {
    SetError(err, errlen, e.what());
    return nullptr;
  }
}

int veles_native_initialize(void* handle, long long batch, char* err,
                            int errlen) {
  try {
    static_cast<Workflow*>(handle)->Initialize(batch);
    return 0;
  } catch (const std::exception& e) {
    SetError(err, errlen, e.what());
    return -1;
  }
}

// Writes the output shape into dims (capacity `cap`); returns rank
// (or -1 on error / not initialized).
int veles_native_output_shape(void* handle, long long* dims, int cap) {
  try {
    const auto& shape = static_cast<Workflow*>(handle)->output_shape();
    if (static_cast<int>(shape.size()) > cap) return -1;
    for (size_t i = 0; i < shape.size(); ++i) dims[i] = shape[i];
    return static_cast<int>(shape.size());
  } catch (...) {
    return -1;
  }
}

int veles_native_input_shape(void* handle, long long* dims, int cap) {
  try {
    const auto& shape = static_cast<Workflow*>(handle)->input_shape();
    if (static_cast<int>(shape.size()) > cap) return -1;
    for (size_t i = 0; i < shape.size(); ++i) dims[i] = shape[i];
    return static_cast<int>(shape.size());
  } catch (...) {
    // exceptions must not cross the C ABI into a ctypes caller
    return -1;
  }
}

long long veles_native_arena_floats(void* handle) {
  return static_cast<Workflow*>(handle)->arena_floats();
}

int veles_native_run(void* handle, const float* input, float* output,
                     char* err, int errlen) {
  try {
    static_cast<Workflow*>(handle)->Run(input, output);
    return 0;
  } catch (const std::exception& e) {
    SetError(err, errlen, e.what());
    return -1;
  }
}

void veles_native_destroy(void* handle) {
  delete static_cast<Workflow*>(handle);
}

}  // extern "C"
