// Minimal recursive-descent JSON parser for package manifests.
// Role parity: the reference's rapidjson consumer in libVeles
// (src/main_file_loader.cc reads contents.json via rapidjson); vendoring
// is avoided — the subset needed by contents.json is ~200 lines.
#pragma once

#include <cctype>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace veles_native {

class Json;
using JsonPtr = std::shared_ptr<Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Type type = Type::Null;
  bool boolean = false;
  double number = 0.0;
  std::string str;
  std::vector<JsonPtr> array;
  std::map<std::string, JsonPtr> object;

  bool is_null() const { return type == Type::Null; }
  double num() const {
    if (type != Type::Number) throw std::runtime_error("json: not a number");
    return number;
  }
  int64_t integer() const { return static_cast<int64_t>(num()); }
  const std::string& string_value() const {
    if (type != Type::String) throw std::runtime_error("json: not a string");
    return str;
  }
  const JsonPtr& at(const std::string& key) const {
    auto it = object.find(key);
    if (it == object.end())
      throw std::runtime_error("json: missing key " + key);
    return it->second;
  }
  JsonPtr get(const std::string& key) const {
    auto it = object.find(key);
    return it == object.end() ? nullptr : it->second;
  }
  bool has(const std::string& key) const {
    return object.count(key) != 0;
  }
};

class JsonParser {
 public:
  static JsonPtr Parse(const std::string& text) {
    JsonParser p(text);
    JsonPtr v = p.ParseValue();
    p.SkipWs();
    if (p.pos_ != text.size())
      throw std::runtime_error("json: trailing garbage");
    return v;
  }

 private:
  explicit JsonParser(const std::string& text) : text_(text) {}

  const std::string& text_;
  size_t pos_ = 0;

  [[noreturn]] void Fail(const std::string& what) {
    throw std::runtime_error("json: " + what + " at offset " +
                             std::to_string(pos_));
  }
  char Peek() {
    if (pos_ >= text_.size()) Fail("unexpected end");
    return text_[pos_];
  }
  char Next() { char c = Peek(); ++pos_; return c; }
  void SkipWs() {
    while (pos_ < text_.size() &&
           (text_[pos_] == ' ' || text_[pos_] == '\t' ||
            text_[pos_] == '\n' || text_[pos_] == '\r'))
      ++pos_;
  }
  void Expect(char c) {
    if (Next() != c) { --pos_; Fail(std::string("expected '") + c + "'"); }
  }
  bool Consume(const char* lit) {
    size_t n = strlen(lit);
    if (text_.compare(pos_, n, lit) == 0) { pos_ += n; return true; }
    return false;
  }

  JsonPtr ParseValue() {
    SkipWs();
    auto v = std::make_shared<Json>();
    char c = Peek();
    if (c == '{') {
      v->type = Json::Type::Object;
      Next(); SkipWs();
      if (Peek() == '}') { Next(); return v; }
      while (true) {
        SkipWs();
        std::string key = ParseString();
        SkipWs(); Expect(':');
        v->object[key] = ParseValue();
        SkipWs();
        char d = Next();
        if (d == '}') break;
        if (d != ',') { --pos_; Fail("expected ',' or '}'"); }
      }
    } else if (c == '[') {
      v->type = Json::Type::Array;
      Next(); SkipWs();
      if (Peek() == ']') { Next(); return v; }
      while (true) {
        v->array.push_back(ParseValue());
        SkipWs();
        char d = Next();
        if (d == ']') break;
        if (d != ',') { --pos_; Fail("expected ',' or ']'"); }
      }
    } else if (c == '"') {
      v->type = Json::Type::String;
      v->str = ParseString();
    } else if (Consume("true")) {
      v->type = Json::Type::Bool; v->boolean = true;
    } else if (Consume("false")) {
      v->type = Json::Type::Bool; v->boolean = false;
    } else if (Consume("null")) {
      v->type = Json::Type::Null;
    } else {
      v->type = Json::Type::Number;
      size_t end = pos_;
      while (end < text_.size() &&
             (isdigit(static_cast<unsigned char>(text_[end])) ||
              text_[end] == '-' || text_[end] == '+' || text_[end] == '.' ||
              text_[end] == 'e' || text_[end] == 'E'))
        ++end;
      if (end == pos_) Fail("invalid value");
      v->number = std::stod(text_.substr(pos_, end - pos_));
      pos_ = end;
    }
    return v;
  }

  std::string ParseString() {
    Expect('"');
    std::string out;
    while (true) {
      char c = Next();
      if (c == '"') break;
      if (c == '\\') {
        char e = Next();
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            // contents.json is ASCII-safe; decode BMP codepoints to UTF-8
            unsigned code = 0;
            for (int i = 0; i < 4; ++i) {
              char h = Next();
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else Fail("bad \\u escape");
            }
            if (code < 0x80) {
              out += static_cast<char>(code);
            } else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: Fail("bad escape");
        }
      } else {
        out += c;
      }
    }
    return out;
  }
};

}  // namespace veles_native
