#include "npy.h"

#include <cstring>
#include <stdexcept>

namespace veles_native {

namespace {

float HalfToFloat(uint16_t h) {
  uint32_t sign = (h >> 15) & 1, exp = (h >> 10) & 0x1F, frac = h & 0x3FF;
  uint32_t out;
  if (exp == 0) {
    if (frac == 0) {
      out = sign << 31;
    } else {  // subnormal: normalize
      exp = 1;
      while (!(frac & 0x400)) { frac <<= 1; --exp; }
      frac &= 0x3FF;
      out = (sign << 31) | ((exp + 112) << 23) | (frac << 13);
    }
  } else if (exp == 0x1F) {
    out = (sign << 31) | 0x7F800000 | (frac << 13);
  } else {
    out = (sign << 31) | ((exp + 112) << 23) | (frac << 13);
  }
  float f;
  std::memcpy(&f, &out, 4);
  return f;
}

std::string HeaderField(const std::string& header, const std::string& key) {
  size_t at = header.find("'" + key + "'");
  if (at == std::string::npos)
    throw std::runtime_error("npy: header missing " + key);
  at = header.find(':', at);
  size_t end = at + 1;
  int depth = 0;
  while (end < header.size()) {
    char c = header[end];
    if (c == '(' || c == '[') ++depth;
    if (c == ')' || c == ']') --depth;
    if ((c == ',' || c == '}') && depth <= 0) break;
    ++end;
  }
  std::string value = header.substr(at + 1, end - at - 1);
  // trim
  while (!value.empty() && (value.front() == ' ' || value.front() == '\''))
    value.erase(value.begin());
  while (!value.empty() &&
         (value.back() == ' ' || value.back() == '\'' || value.back() == ','))
    value.pop_back();
  return value;
}

}  // namespace

NpyArray LoadNpy(const uint8_t* bytes, size_t len) {
  if (len < 10 || std::memcmp(bytes, "\x93NUMPY", 6) != 0)
    throw std::runtime_error("npy: bad magic");
  uint8_t major = bytes[6];
  size_t header_len, header_at;
  if (major == 1) {
    header_len = bytes[8] | (bytes[9] << 8);
    header_at = 10;
  } else {
    if (len < 12) throw std::runtime_error("npy: truncated");
    header_len = static_cast<size_t>(bytes[8]) | (bytes[9] << 8) |
                 (static_cast<size_t>(bytes[10]) << 16) |
                 (static_cast<size_t>(bytes[11]) << 24);
    header_at = 12;
  }
  if (header_at + header_len > len)
    throw std::runtime_error("npy: truncated header");
  std::string header(reinterpret_cast<const char*>(bytes + header_at),
                     header_len);
  std::string descr = HeaderField(header, "descr");
  std::string fortran = HeaderField(header, "fortran_order");
  std::string shape_s = HeaderField(header, "shape");
  if (fortran.find("True") != std::string::npos)
    throw std::runtime_error("npy: fortran_order not supported");

  NpyArray arr;
  size_t p = shape_s.find('(');
  size_t q = shape_s.find(')');
  std::string dims = (p == std::string::npos)
      ? shape_s : shape_s.substr(p + 1, q - p - 1);
  size_t start = 0;
  while (start < dims.size()) {
    size_t comma = dims.find(',', start);
    std::string tok = dims.substr(
        start, comma == std::string::npos ? std::string::npos
                                          : comma - start);
    bool any_digit = false;
    for (char c : tok) any_digit |= (c >= '0' && c <= '9');
    if (any_digit) {
      int64_t dim = 0;
      try {
        dim = std::stoll(tok);
      } catch (const std::exception&) {
        throw std::runtime_error("npy: unparseable shape dim");
      }
      if (dim < 0) throw std::runtime_error("npy: negative shape dim");
      arr.shape.push_back(dim);
    }
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  // Overflow-checked element count with an allocation cap: shape dims
  // come from the (untrusted) package, and an overflowed product would
  // be UB before resize() could even object.
  constexpr int64_t kMaxElements = int64_t(1) << 31;  // 8 GiB of f32
  int64_t count = 1;
  for (int64_t d : arr.shape) {
    if (d != 0 && count > kMaxElements / d)
      throw std::runtime_error("npy: element count overflows cap");
    count *= d;
  }
  const uint8_t* payload = bytes + header_at + header_len;
  size_t avail = len - header_at - header_len;
  arr.data.resize(static_cast<size_t>(count));

  auto need = [&](size_t itemsize) {
    if (avail < static_cast<size_t>(count) * itemsize)
      throw std::runtime_error("npy: truncated payload");
  };
  if (descr == "<f4") {
    need(4);
    std::memcpy(arr.data.data(), payload, count * 4);
  } else if (descr == "<f2") {
    need(2);
    const uint16_t* h = reinterpret_cast<const uint16_t*>(payload);
    for (int64_t i = 0; i < count; ++i) arr.data[i] = HalfToFloat(h[i]);
  } else if (descr == "<f8") {
    need(8);
    const double* d = reinterpret_cast<const double*>(payload);
    for (int64_t i = 0; i < count; ++i)
      arr.data[i] = static_cast<float>(d[i]);
  } else if (descr == "|u1") {
    need(1);
    for (int64_t i = 0; i < count; ++i) arr.data[i] = payload[i];
  } else if (descr == "|i1") {
    // int8 quantized packages (precision=8): raw codes here; the
    // workflow loader applies the per-channel ".scale" companions
    need(1);
    const int8_t* d = reinterpret_cast<const int8_t*>(payload);
    for (int64_t i = 0; i < count; ++i)
      arr.data[i] = static_cast<float>(d[i]);
  } else if (descr == "<i4") {
    need(4);
    const int32_t* d = reinterpret_cast<const int32_t*>(payload);
    for (int64_t i = 0; i < count; ++i)
      arr.data[i] = static_cast<float>(d[i]);
  } else if (descr == "<i8") {
    need(8);
    const int64_t* d = reinterpret_cast<const int64_t*>(payload);
    for (int64_t i = 0; i < count; ++i)
      arr.data[i] = static_cast<float>(d[i]);
  } else {
    throw std::runtime_error("npy: unsupported dtype " + descr);
  }
  return arr;
}

}  // namespace veles_native
