// Component-level C++ tests for the native runtime — the libVeles
// test discipline (googletest suites per component under
// libVeles/tests/) without the gtest dependency: a plain CHECK macro,
// one section per component, nonzero exit on any failure.
//
//     make -C native test
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <limits>
#include <string>
#include <vector>

#include "../src/engine.h"
#include "../src/json.h"
#include "../src/memory_optimizer.h"
#include "../src/npy.h"
#include "../src/unit.h"

namespace {

int failures = 0;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      ++failures;                                                      \
    }                                                                  \
  } while (0)

#define CHECK_THROWS(expr)                                             \
  do {                                                                 \
    bool threw = false;                                                \
    try {                                                              \
      (void)(expr);                                                    \
    } catch (const std::exception&) {                                  \
      threw = true;                                                    \
    }                                                                  \
    CHECK(threw);                                                      \
  } while (0)

using veles_native::Engine;
using veles_native::Gemm;
using veles_native::JsonParser;
using veles_native::LoadNpy;
using veles_native::MemoryNode;
using veles_native::MemoryOptimizer;

std::vector<uint8_t> MakeNpy(const std::string& descr,
                             const std::string& shape,
                             const void* payload, size_t payload_len,
                             bool fortran = false) {
  std::string header = "{'descr': '" + descr +
                       "', 'fortran_order': " +
                       (fortran ? "True" : "False") +
                       ", 'shape': " + shape + ", }";
  // pad so magic(6)+ver(2)+len(2)+header is a multiple of 16
  size_t base = 6 + 2 + 2;
  size_t total = base + header.size() + 1;
  size_t padded = (total + 15) / 16 * 16;
  header.append(padded - base - header.size() - 1, ' ');
  header.push_back('\n');
  std::vector<uint8_t> out;
  const uint8_t magic[6] = {0x93, 'N', 'U', 'M', 'P', 'Y'};
  out.insert(out.end(), magic, magic + 6);
  out.push_back(1);
  out.push_back(0);
  uint16_t hlen = static_cast<uint16_t>(header.size());
  out.push_back(hlen & 0xff);
  out.push_back(hlen >> 8);
  out.insert(out.end(), header.begin(), header.end());
  const uint8_t* p = static_cast<const uint8_t*>(payload);
  out.insert(out.end(), p, p + payload_len);
  return out;
}

void TestNpy() {
  float data[6] = {1.5f, -2.0f, 0.0f, 3.25f, 4.0f, -5.5f};
  auto blob = MakeNpy("<f4", "(2, 3)", data, sizeof(data));
  auto arr = LoadNpy(blob.data(), blob.size());
  CHECK(arr.shape == std::vector<int64_t>({2, 3}));
  CHECK(arr.size() == 6);
  for (int i = 0; i < 6; ++i) CHECK(arr.data[i] == data[i]);

  // fp16 widens to f32 (the precision=16 package path)
  uint16_t half[2] = {0x3C00, 0xC000};  // 1.0, -2.0
  auto blob16 = MakeNpy("<f2", "(2,)", half, sizeof(half));
  auto arr16 = LoadNpy(blob16.data(), blob16.size());
  CHECK(arr16.data[0] == 1.0f && arr16.data[1] == -2.0f);

  // int and byte dtypes convert
  int32_t ints[3] = {-7, 0, 42};
  auto blobi = MakeNpy("<i4", "(3,)", ints, sizeof(ints));
  auto arri = LoadNpy(blobi.data(), blobi.size());
  CHECK(arri.data[0] == -7.0f && arri.data[2] == 42.0f);

  // int8 quantized codes widen signed (the precision=8 package path)
  int8_t codes[4] = {-127, -1, 0, 127};
  auto blob8 = MakeNpy("|i1", "(4,)", codes, sizeof(codes));
  auto arr8 = LoadNpy(blob8.data(), blob8.size());
  CHECK(arr8.data[0] == -127.0f && arr8.data[1] == -1.0f &&
        arr8.data[3] == 127.0f);

  // fortran order and foreign endianness are rejected loudly
  auto fblob = MakeNpy("<f4", "(2, 3)", data, sizeof(data), true);
  CHECK_THROWS(LoadNpy(fblob.data(), fblob.size()));
  auto bblob = MakeNpy(">f4", "(2, 3)", data, sizeof(data));
  CHECK_THROWS(LoadNpy(bblob.data(), bblob.size()));
  // truncated payload
  auto tblob = MakeNpy("<f4", "(2, 3)", data, sizeof(data) - 4);
  CHECK_THROWS(LoadNpy(tblob.data(), tblob.size()));
}

void TestJson() {
  auto v = JsonParser::Parse(
      "{\"name\": \"mnist\", \"n\": -3.5, \"ok\": true, "
      "\"null\": null, \"shape\": [1, 2, 3], "
      "\"nested\": {\"k\": \"v\\n\"}}");
  CHECK(v->at("name")->string_value() == "mnist");
  CHECK(v->at("n")->number == -3.5);
  CHECK(v->at("ok")->boolean);
  CHECK(v->at("shape")->array.size() == 3);
  CHECK(v->at("shape")->array[2]->integer() == 3);
  CHECK(v->at("nested")->at("k")->string_value() == "v\n");
  CHECK(v->has("name") && !v->has("absent"));
  CHECK_THROWS(JsonParser::Parse("{\"unterminated\": "));
}

void TestMemoryOptimizer() {
  // chain: A overlaps B, B overlaps C, A and C are disjoint in time —
  // A and C may share space, B must not overlap either
  std::vector<MemoryNode> nodes(3);
  nodes[0] = {100, 0, 1, -1};
  nodes[1] = {200, 1, 2, -1};
  nodes[2] = {150, 2, 3, -1};
  int64_t total = MemoryOptimizer::Optimize(&nodes);
  CHECK(total <= 350);  // naive sum would be 450
  for (size_t i = 0; i < nodes.size(); ++i) {
    CHECK(nodes[i].offset >= 0);
    CHECK(nodes[i].offset + nodes[i].size <= total);
    for (size_t j = i + 1; j < nodes.size(); ++j) {
      bool time_overlap = nodes[i].time_start <= nodes[j].time_end &&
                          nodes[j].time_start <= nodes[i].time_end;
      bool space_overlap =
          nodes[i].offset < nodes[j].offset + nodes[j].size &&
          nodes[j].offset < nodes[i].offset + nodes[i].size;
      if (time_overlap) CHECK(!space_overlap);
    }
  }

  // all-live-at-once degenerates to sum
  std::vector<MemoryNode> dense(4);
  for (int i = 0; i < 4; ++i) dense[i] = {64, 0, 9, -1};
  CHECK(MemoryOptimizer::Optimize(&dense) == 256);
}

void TestGemm() {
  // The 4-row-blocked kernel vs a naive loop: m in 1..9 sweeps every
  // blocked/remainder split (0..3 leftover rows), with and without
  // bias, and with all-zero rows/entries to cover the zero-skip path.
  // The kernel's per-element accumulation order matches the naive
  // loop (documented in units.cc), so results must be exactly equal.
  Engine engine(3);
  const int64_t k = 7, n = 5;
  uint32_t state = 0x2545f491u;
  auto next = [&state]() {  // xorshift; values in roughly [-4, 4)
    state ^= state << 13;
    state ^= state >> 17;
    state ^= state << 5;
    return static_cast<float>(static_cast<int32_t>(state % 1024) - 512)
           / 128.0f;
  };
  for (int64_t m = 1; m <= 9; ++m) {
    for (int with_bias = 0; with_bias <= 1; ++with_bias) {
      std::vector<float> x(m * k), w(k * n), b(n);
      for (auto& v : x) v = next();
      for (auto& v : w) v = next();
      for (auto& v : b) v = next();
      // all-zero rows exercise the skip in both the blocked path
      // (rows 0..3) and the remainder path (last row)
      for (int64_t kk = 0; kk < k; ++kk) x[0 * k + kk] = 0.0f;
      if (m > 4)
        for (int64_t kk = 0; kk < k; ++kk) x[(m - 1) * k + kk] = 0.0f;
      if (m > 1) x[1 * k + 2] = 0.0f;  // scattered zero, live row
      const float* bias = with_bias ? b.data() : nullptr;

      std::vector<float> ref(m * n);
      for (int64_t i = 0; i < m; ++i)
        for (int64_t j = 0; j < n; ++j) {
          float acc = bias ? bias[j] : 0.0f;
          for (int64_t kk = 0; kk < k; ++kk)
            acc += x[i * k + kk] * w[kk * n + j];
          ref[i * n + j] = acc;
        }

      std::vector<float> out(m * n, -777.0f);
      Gemm(x.data(), w.data(), bias, out.data(), m, k, n, &engine);
      for (int64_t i = 0; i < m * n; ++i) CHECK(out[i] == ref[i]);
    }
  }

  // NaN/Inf weights: the per-row zero skip must hold INSIDE the 4-row
  // block too — a blocked `o += 0.0f * NaN` would poison the row the
  // single-row loop leaves clean.  Row 1 of the block is all-zero
  // (skipped), rows 0/2/3 are live but zero exactly at the NaN/Inf
  // weight rows, so every output must stay finite and bitwise equal
  // to the naive per-row-skip loop.
  {
    const int64_t mm = 6;  // 4-row block + 2 remainder rows
    std::vector<float> xn(mm * k), wn(k * n), bn(n);
    for (auto& v : xn) v = next();
    for (auto& v : wn) v = next();
    for (auto& v : bn) v = next();
    for (int64_t kk = 0; kk < k; ++kk) xn[1 * k + kk] = 0.0f;
    for (int64_t i = 0; i < mm; ++i) {
      xn[i * k + 2] = 0.0f;  // every row zero at the NaN weight row
      xn[i * k + 4] = 0.0f;  // ...and at the Inf weight row
    }
    const float nan = std::nanf("");
    const float inf = std::numeric_limits<float>::infinity();
    for (int64_t j = 0; j < n; ++j) {
      wn[2 * n + j] = nan;
      wn[4 * n + j] = inf;
    }
    std::vector<float> refn(mm * n);
    for (int64_t i = 0; i < mm; ++i)
      for (int64_t j = 0; j < n; ++j) {
        float acc = bn[j];
        for (int64_t kk = 0; kk < k; ++kk) {
          float xv = xn[i * k + kk];
          if (xv == 0.0f) continue;  // the single-row skip rule
          acc += xv * wn[kk * n + j];
        }
        refn[i * n + j] = acc;
      }
    std::vector<float> outn(mm * n, -777.0f);
    Gemm(xn.data(), wn.data(), bn.data(), outn.data(), mm, k, n,
         &engine);
    for (int64_t i = 0; i < mm * n; ++i) {
      CHECK(std::isfinite(outn[i]));
      CHECK(outn[i] == refn[i]);
    }
  }

  // all-zero input: output is exactly the bias (or zeros) everywhere
  std::vector<float> xz(6 * k, 0.0f), w(k * n), b(n), out(6 * n, 1.0f);
  for (auto& v : w) v = next();
  for (auto& v : b) v = next();
  Gemm(xz.data(), w.data(), b.data(), out.data(), 6, k, n, &engine);
  for (int64_t i = 0; i < 6; ++i)
    for (int64_t j = 0; j < n; ++j) CHECK(out[i * n + j] == b[j]);
  Gemm(xz.data(), w.data(), nullptr, out.data(), 6, k, n, &engine);
  for (int64_t i = 0; i < 6 * n; ++i) CHECK(out[i] == 0.0f);
}

void TestEngine() {
  Engine engine(4);
  CHECK(engine.workers() >= 1);
  std::vector<int> hits(1000, 0);
  engine.ParallelFor(1000, [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) hits[i] += 1;
  });
  int64_t sum = 0;
  for (int h : hits) sum += h;
  CHECK(sum == 1000);  // every index exactly once

  // Schedule + Wait: all tasks complete before Wait returns
  std::vector<int> done(32, 0);
  for (int i = 0; i < 32; ++i) {
    engine.Schedule([&done, i] { done[i] = 1; });
  }
  engine.Wait();
  for (int i = 0; i < 32; ++i) CHECK(done[i] == 1);
}

}  // namespace

int main() {
  TestNpy();
  TestJson();
  TestMemoryOptimizer();
  TestGemm();
  TestEngine();
  if (failures) {
    std::fprintf(stderr, "%d native test check(s) FAILED\n", failures);
    return 1;
  }
  std::printf("native tests OK\n");
  return 0;
}
