"""veles_tpu.pod — one-pod-one-program training.

The reference survey's explicit north star (PAPER.md §0: "ICI ``psum``
replacing ZeroMQ gradient aggregation on-pod"), landed: slave jobs
sharing a mesh become shards of ONE pjit'd stitched program
(:class:`~veles_tpu.pod.runtime.PodRuntime`), and ZeroMQ is demoted to
the cross-host control plane — pod leases, heartbeats, per-epoch
Decision sync, checkpoint triggers and elastic membership
(:mod:`~veles_tpu.pod.membership`).  Steady-state training moves ZERO
gradient bytes over the wire; the chaos controller's wire-site frame
counters are the proof (``python -m veles_tpu.pod --smoke``).

See ``docs/distributed_training.md`` § Pod runtime.
"""

from veles_tpu.pod.membership import (  # noqa: F401
    DeviceLossDetector, PodMaster, PodWorker, capture_params,
    eval_metrics, install_params, is_device_loss, train_epochs)
from veles_tpu.pod.pods import (  # noqa: F401
    MultiHostPod, MultiHostPodWorker)
from veles_tpu.pod.runtime import PodError, PodRuntime  # noqa: F401
