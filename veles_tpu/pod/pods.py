"""Pod-of-pods: ONE lease spanning every host of a multi-host pod.

:class:`veles_tpu.pod.runtime.PodRuntime` composes with
:mod:`veles_tpu.parallel.multihost` here: :func:`multihost.initialize`
joins the processes into one JAX runtime, ``jax.devices()`` becomes the
GLOBAL device list, and the same :func:`veles_tpu.parallel.mesh
.mesh_from_topology` call every single-host pod makes now spans hosts —
the collectives XLA inserts for the mesh ride ICI within a slice and
DCN across slices (ROADMAP item 2's pod-of-pods direction, PAPERS.md's
multi-slice scaling).  Three consequences fall out without new
machinery:

* **datasets load host-locally**: each process reads only its
  :func:`~veles_tpu.parallel.multihost.host_shard_range` rows and
  :meth:`MultiHostPod.assemble` turns them into one addressable-shard-
  backed global array (:func:`~veles_tpu.parallel.multihost
  .from_host_local`) — no host ever materializes the full batch;
* **the epoch-scan window spans the slice**: the window program is
  compiled once over the global mesh, so a whole class pass is still
  ONE dispatch — now a multi-host dispatch — and the wire gate
  (exactly one ``update`` frame per lease) holds unchanged because the
  control plane never learned to carry gradients in the first place;
* **a single-process run is byte-identical to a plain PodRuntime**:
  with no coordinator configured :func:`multihost.initialize` no-ops,
  the mesh is the same local mesh, and :class:`MultiHostPod` is a
  transparent delegate — same programs, same bytes, same trace.

Control plane: exactly ONE rank (the coordinator,
:func:`multihost.is_coordinator`) speaks ZMQ.
:class:`MultiHostPodWorker` runs the full
:class:`~veles_tpu.pod.membership.PodWorker` session there — lease
grant, per-epoch ``pod_epoch`` syncs, the single final ``update`` —
while every other rank runs in **follower mode**: it executes the same
SPMD dispatches in lockstep (that is what a global mesh means) but
opens no socket and sends ZERO frames.  The chaos wire-site counters
therefore read identically however many hosts the pod spans.

Device loss: each rank :meth:`~MultiHostPod.beat`s on every epoch
boundary; the coordinator's
:class:`~veles_tpu.pod.membership.DeviceLossDetector` declares a
silent host lost (``jobs:heartbeat_stall`` instant), reshards the
runtime (generation bump) and the master's reaper/requeue machinery
re-grants the lease — the same elastic path a chaos ``chip_kill``
exercises on one host.
"""

from veles_tpu.logger import Logger
from veles_tpu.parallel import multihost
from veles_tpu.parallel.mesh import mesh_from_topology
from veles_tpu.pod.membership import (DeviceLossDetector, PodWorker,
                                      eval_metrics, train_epochs)
from veles_tpu.pod.runtime import PodRuntime


class MultiHostPod(Logger):
    """One lease over every host's devices.

    ``coordinator`` / ``num_processes`` / ``process_id`` forward to
    :func:`multihost.initialize` (all None + single process → no-op:
    the transparent single-host path).  ``mesh`` overrides the
    knob/``topology`` mesh; either way the mesh is built AFTER
    initialize, so it grids the global device list.
    ``heartbeat_timeout`` configures the device-loss detector
    (seconds of host silence before its chips are declared lost).
    """

    def __init__(self, workflow, mesh=None, topology=None,
                 param_rules=None, data_axis="data", coordinator=None,
                 num_processes=None, process_id=None,
                 heartbeat_timeout=30.0, **kwargs):
        super(MultiHostPod, self).__init__(**kwargs)
        if coordinator or num_processes or process_id is not None \
                or multihost.configured():
            multihost.initialize(coordinator=coordinator,
                                 num_processes=num_processes,
                                 process_id=process_id)
        self.workflow = workflow
        if mesh is None:
            mesh = mesh_from_topology(topology, require=(data_axis,))
        #: the delegate — a single-process MultiHostPod IS this
        #: runtime (byte-identical programs and placements)
        self.runtime = PodRuntime(workflow, mesh=mesh,
                                  param_rules=param_rules,
                                  data_axis=data_axis)
        devices_per_host = max(
            1, len(self.runtime.devices) // self.process_count)
        self.detector = DeviceLossDetector(
            self.runtime, timeout=heartbeat_timeout,
            devices_per_host=devices_per_host)

    # -- process topology ----------------------------------------------------
    @property
    def process_index(self):
        return multihost.process_index()

    @property
    def process_count(self):
        return multihost.process_count()

    @property
    def is_coordinator(self):
        return multihost.is_coordinator()

    # -- runtime delegation --------------------------------------------------
    def install(self):
        if not self.runtime.installed:
            self.runtime.install()
        return self

    def uninstall(self):
        self.runtime.uninstall()
        return self

    def describe(self):
        out = self.runtime.describe()
        out["processes"] = self.process_count
        out["process_index"] = self.process_index
        out["coordinator"] = self.is_coordinator
        return out

    # -- the host->device data boundary --------------------------------------
    def host_range(self, n_samples, allow_uneven=False):
        """[start, stop) of THIS host's rows of an ``n_samples``-row
        dataset (:func:`multihost.host_shard_range`) — what a loader
        reads instead of the full set."""
        return multihost.host_shard_range(n_samples,
                                          allow_uneven=allow_uneven)

    def assemble(self, local_batch, global_shape=None):
        """This host's rows → one global jax.Array batch-sharded over
        the pod mesh (:func:`multihost.from_host_local`; identity
        placement on a single process).  The returned array feeds any
        program this runtime compiled without a gather."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        ndim = getattr(local_batch, "ndim", 1)
        sharding = NamedSharding(
            self.runtime.mesh,
            P(self.runtime.data_axis, *([None] * (ndim - 1))))
        return multihost.from_host_local(local_batch, sharding,
                                         global_shape=global_shape)

    # -- liveness ------------------------------------------------------------
    def beat(self, host=None, now=None):
        """Record a liveness beat (default: this process) — workers
        call this on every epoch boundary; the coordinator's
        :meth:`poll` turns silence into a reshard."""
        self.detector.beat(self.process_index if host is None
                           else host, now=now)

    def poll(self, now=None):
        """Coordinator-side: declare silent hosts lost (reshard +
        ``jobs:heartbeat_stall``).  No-op on followers — exactly one
        rank may drive elastic membership."""
        if not self.is_coordinator:
            return []
        return self.detector.poll(now=now)


class MultiHostPodWorker(Logger):
    """The multi-host worker: the coordinator rank runs a full
    :class:`~veles_tpu.pod.membership.PodWorker` ZMQ session over the
    shared :class:`MultiHostPod` runtime; every other rank runs in
    follower mode — same epochs, same SPMD dispatches, ZERO frames.

    ``epochs`` is the follower's local epoch budget (the coordinator's
    comes inside the lease); default: the workflow Decision's
    ``max_epochs`` — the same number the master defaults to, which is
    what keeps lockstep ranks in lockstep.
    """

    def __init__(self, workflow, endpoint, pod=None, epochs=None,
                 sid=None, **kwargs):
        super(MultiHostPodWorker, self).__init__(**kwargs)
        self.workflow = workflow
        self.pod = pod if pod is not None else MultiHostPod(workflow)
        self.epochs = int(epochs
                          or getattr(workflow.decision, "max_epochs",
                                     1))
        self.worker = None
        if self.pod.is_coordinator:
            self.worker = PodWorker(
                workflow, endpoint, mesh=self.pod.runtime.mesh,
                param_rules=self.pod.runtime.param_rules, sid=sid)
            # share the pod's runtime: _ensure_runtime sees it
            # installed and never builds a second one
            self.worker.runtime = self.pod.runtime

    def run(self):
        """Install (idempotent) and serve: the coordinator's JobClient
        session, or the follower's frameless local epochs.  Returns
        the coordinator verdict / True for a completed follower."""
        self.pod.install()
        self.pod.beat()
        if self.worker is not None:
            return self.worker.run()
        return self._run_follower()

    def _run_follower(self):
        self.info(
            "rank %d/%d: follower mode — training %d epoch(s) in "
            "lockstep, no control-plane socket", self.pod.process_index,
            self.pod.process_count, self.epochs)
        for _epoch in train_epochs(self.workflow, self.epochs):
            self.pod.beat()
        return True

    def metrics(self):
        return eval_metrics(self.workflow)

    def close(self):
        if self.worker is not None:
            self.worker.close()
