"""PodRuntime: one pod, one program.

Turns an initialized, *stitched* workflow (:mod:`veles_tpu.stitch`)
into shards of a single pjit'd program per segment over a
:func:`veles_tpu.parallel.mesh.mesh_from_topology` mesh:

* the device-resident FullBatch dataset, the pre-mapped labels and the
  shuffled-index buffer shard row-wise over the ``data`` axis (each
  chip holds ``1/shards`` of the dataset), and the PR 4 traced
  ``(offset, size)`` gather partitions with them — GSPMD lowers the
  global ``jnp.take`` into per-shard index arithmetic with one
  combine, so minibatch selection never funnels through a host;
* parameters (and their momentum/solver state — every donated Vector)
  stay replicated (or TP-shard via ``param_rules``, the
  :func:`veles_tpu.parallel.dp.tp_rules` /
  :func:`~veles_tpu.parallel.dp.fsdp_rules` recipes), so the gradient
  contractions XLA partitions over the batch end in an in-program
  ``psum`` — the ICI all-reduce that replaces per-step ZMQ gradient
  frames — and the optimizer step runs sharded-in-program on donated
  HBM buffers;
* metric scalars come out replicated (already globally reduced), so
  Decision's host accounting is byte-compatible with the
  single-device run.

Nothing about the workflow's control graph changes: the loader prelude
still advances the serving cursor, Decision still closes epochs, the
segments just dispatch mesh-wide programs.  ``install()`` is therefore
reversible (:meth:`uninstall`) and must be re-run after any
``rebuild_stitching()``.

Elastic membership: :meth:`pre_dispatch` (called by every bound
segment before it gathers arguments) consults the chaos controller's
``pod_chip`` site — a scheduled ``chip_kill`` drops one chip from the
mesh, :meth:`reshard` shrinks the ``data`` axis to the largest size
the global batch still divides over the survivors, re-places every
resident buffer (params sync device→host→new-mesh: the run resumes
from the last in-HBM-consistent step), BUMPS the generation (the PR 7
staleness token the membership layer reports upstream) and recompiles
each segment once.  The reshard lands in the trace as a
``pod:reshard`` instant next to the chaos injection that provoked it.
"""

import numpy

from veles_tpu import chaos, prof, trace
from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.memory import Vector
from veles_tpu.parallel.mesh import MeshTopologyError, mesh_from_topology
from veles_tpu.prof.ledger import _fmt_bytes


class PodError(RuntimeError):
    """The workflow cannot run as a pod program (not stitched, no
    divisible batch, no mesh) — raised by :meth:`PodRuntime.install`
    with the remedy in the message."""


def spec_for_vector(vec, batch, shards, data_axis="data",
                    param_rules=None, donated=False):
    """THE per-Vector pod sharding rule — shared by
    :meth:`PodRuntime._spec_for` and the analyzer's V-P02 preflight
    (:func:`veles_tpu.analyze.shapes.check_pod`), so the residency
    estimate and the installed plan can never drift:

    * parameters — the ``params`` category, or ANY donated slot —
      replicate, unless ``param_rules`` returns a spec for the leaf
      (a raising rule raises here, identically at preflight and at
      install);
    * resident dataset rows and minibatch-sized staging tensors shard
      their leading dim over ``data_axis`` — but only when the row
      count divides the shard count: an uneven dataset replicates
      transparently instead of crashing ``jax.device_put`` (the
      preflight warns, so the lost sharding is not silent);
    * everything else replicates.
    """
    from jax.sharding import PartitionSpec as P
    shape = vec.shape or ()
    if donated or getattr(vec, "category", None) == "params":
        if param_rules is not None and shape:
            spec = param_rules(numpy.empty(shape, dtype=numpy.int8))
            if spec is not None:
                return spec
        return P()
    leading = shape[0] if shape else 0
    if leading and (getattr(vec, "category", None) == "dataset"
                    or leading == batch):
        if leading % max(1, shards) == 0:
            return P(data_axis, *([None] * (len(shape) - 1)))
        return P()
    return P()


class PodRuntime(Logger):
    """Compiles a stitched workflow's segments for a device mesh with
    in-program gradient aggregation.

    ``mesh``: a ``jax.sharding.Mesh`` with a ``data`` axis; default
    :func:`mesh_from_topology` (the ``root.common.engine.pod.topology``
    knob).  ``param_rules``: optional callable ``leaf_shape_array ->
    PartitionSpec | None`` applied to parameter/donated buffers (TP /
    FSDP sharding); ``None`` → fully replicated; ``"auto"`` → the
    static planner (:func:`veles_tpu.analyze.plan.auto_param_rules`)
    picks replicated/fsdp/tp for this mesh at ``install()`` (also
    spellable as the ``root.common.engine.pod.param_rules`` knob).
    ``data_axis`` names the batch axis ("data").

    ``preflight``: ``off`` | ``warn`` | ``fail`` — run the analyzer's
    V-P02 pod preflight at install (default: the
    ``root.common.engine.pod.preflight`` knob, else ``warn``).
    """

    def __init__(self, workflow, mesh=None, param_rules=None,
                 data_axis="data", preflight=None, **kwargs):
        super(PodRuntime, self).__init__(**kwargs)
        self.workflow = workflow
        self.data_axis = data_axis
        self.param_rules = param_rules
        if param_rules is None:
            node = root.common.engine.get("pod")
            knob = node.get("param_rules") if node else None
            if knob:
                # a knob can only spell a mode ("auto"); callables
                # come through the constructor
                self.param_rules = str(knob)
        #: the planner's winning candidate (dict) when param_rules
        #: resolved via "auto" at install()
        self.auto_plan = None
        self.mesh = mesh if mesh is not None else mesh_from_topology(
            require=(data_axis,))
        if data_axis not in self.mesh.shape:
            raise MeshTopologyError(
                "pod mesh %r has no %r axis" % (dict(self.mesh.shape),
                                                data_axis))
        if preflight is None:
            node = root.common.engine.get("pod")
            preflight = str((node.get("preflight") if node else None)
                            or "warn").lower()
        self.preflight = preflight
        self.generation = 1
        self.installed = False
        #: chips lost to chip_kill faults so far (reshard count)
        self.reshards = 0
        #: id(segment) -> analytic per-dispatch psum bytes (the ring
        #: all-reduce estimate over the segment's donated buffers)
        self._psum_bytes = {}
        #: id(segment) -> analytic per-dispatch expert all_to_all
        #: bytes (non-zero only under an ``expert`` mesh axis)
        self._a2a_bytes = {}
        #: pipeline microbatches per step (the
        #: ``root.common.engine.pod.microbatches`` knob; default =
        #: the planner's PP_MICRO_PER_STAGE × stage count)
        node = root.common.engine.get("pod")
        self.microbatches = int((node.get("microbatches") if node
                                 else 0) or 0) or None
        self._segments = []
        self._sharded_vecs = []
        #: membership hook: called as on_reshard(runtime) after an
        #: elastic reshard so the control plane can report the bumped
        #: generation on its next epoch sync
        self.on_reshard = None

    # -- properties ---------------------------------------------------------
    @property
    def shards(self):
        """Lockstep shards on the data axis."""
        return int(self.mesh.shape[self.data_axis])

    @property
    def devices(self):
        return [d for d in self.mesh.devices.flat]

    @property
    def pipe_stages(self):
        """Pipeline stages on the ``pipe`` axis (1 = no pipelining)."""
        return int(dict(self.mesh.shape).get("pipe", 1))

    @property
    def expert_shards(self):
        """Expert shards on the ``expert`` axis (1 = dense)."""
        return int(dict(self.mesh.shape).get("expert", 1))

    def _microbatches(self):
        if self.microbatches:
            return int(self.microbatches)
        from veles_tpu.analyze.plan import PP_MICRO_PER_STAGE
        return PP_MICRO_PER_STAGE * self.pipe_stages

    def describe(self):
        from veles_tpu.analyze.pricing import pipeline_bubble
        return {
            "shards": self.shards,
            "axes": dict(self.mesh.shape),
            "generation": self.generation,
            "reshards": self.reshards,
            "segments": [
                "+".join(s.names) for s in self._segments],
            "psum_bytes_per_step": sum(self._psum_bytes.values()),
            "all_to_all_bytes_per_step": sum(self._a2a_bytes.values()),
            "bubble_fraction": pipeline_bubble(self.pipe_stages,
                                               self._microbatches())
            if self.pipe_stages > 1 else 0.0,
            "auto_plan": (self.auto_plan or {}).get("name"),
        }

    # -- install ------------------------------------------------------------
    def install(self):
        """Shard the resident state and swap every stitched segment's
        program for its mesh-wide twin.  Idempotent; re-run after
        ``rebuild_stitching()``."""
        wf = self.workflow
        segments = list(getattr(wf, "_stitch_segments_", ()))
        if not segments:
            raise PodError(
                "workflow has no stitched segments — pod training "
                "rides the stitched fast path (initialize with "
                "root.common.engine.stitch=on on a jit device; "
                "interpret/NumpyDevice workflows cannot shard)")
        batch = int(wf.loader.max_minibatch_size)
        if batch % self.shards:
            raise PodError(
                "global batch %d does not divide over %d data shards "
                "— pick a batch a multiple of the data axis (or a "
                "smaller topology)" % (batch, self.shards))
        self._resolve_param_rules()
        self._run_preflight()
        self._segments = segments
        self._apply_shardings()
        self.installed = True
        self.info(
            "pod installed: %d segment(s) compiled for %d shard(s) "
            "%r, ~%s psum/step",
            len(segments), self.shards, dict(self.mesh.shape),
            _fmt_bytes(sum(self._psum_bytes.values())))
        return self

    def uninstall(self):
        """Back to single-device segments (clears vector shardings)."""
        for segment in self._segments:
            segment.clear_shardings()
            segment.prof_entry.shards = 1
        for vec in self._sharded_vecs:
            vec.set_sharding(None)
        self._sharded_vecs = []
        self._segments = []
        self._psum_bytes = {}
        self._a2a_bytes = {}
        self.installed = False
        self._invalidate_scan()
        return self

    def _invalidate_scan(self):
        """Drop the workflow's compiled epoch-scan window programs (a
        placement change is a new program by definition — the next
        window recompiles once, counted warmup, never flagged)."""
        runner = getattr(self.workflow, "_epoch_runner_", None)
        if runner is not None:
            runner.invalidate_programs()

    def _resolve_param_rules(self):
        """A string ``param_rules`` is a mode: ``auto`` hands the
        choice to the static planner (replicated / fsdp / tp over
        THIS mesh, priced with the shared pricing core); the winner's
        callable (or None) replaces the string before preflight and
        sharding, so everything downstream sees an explicit rule —
        same programs, same parity, zero extra recompiles."""
        if not isinstance(self.param_rules, str):
            return
        mode = self.param_rules.strip().lower()
        if mode in ("", "none", "off"):
            self.param_rules = None
            return
        if mode != "auto":
            raise PodError(
                "unknown param_rules mode %r (None | callable | "
                "'auto')" % (self.param_rules,))
        from veles_tpu.analyze.plan import auto_param_rules
        rules, name, row = auto_param_rules(
            self.workflow, self.mesh, data_axis=self.data_axis)
        self.param_rules = rules
        self.auto_plan = row
        self.info(
            "pod auto plan: %s (%s) — predicted %s/shard, %s "
            "psum/step",
            name, row.get("rule", "?"),
            _fmt_bytes(int(row.get("per_shard_bytes", 0))),
            _fmt_bytes(int(row.get("psum_bytes_per_step", 0))))

    def _run_preflight(self):
        if self.preflight == "off":
            return
        from veles_tpu.analyze import PreflightError
        from veles_tpu.analyze.shapes import check_pod
        report = check_pod(self.workflow, self.mesh,
                           data_axis=self.data_axis,
                           param_rules=self.param_rules)
        if report.has_errors and self.preflight == "fail":
            raise PreflightError(report)
        for finding in report:
            (self.warning if finding.severity == "error"
             else self.info)("pod preflight %s: %s", finding.rule,
                             finding.message)

    # -- sharding plan ------------------------------------------------------
    def _named(self, spec):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, spec)

    def _spec_for(self, vec, donated=False):
        """The shared per-Vector rule (:func:`spec_for_vector`) bound
        to this runtime's mesh/batch/rules."""
        return spec_for_vector(
            vec, int(self.workflow.loader.max_minibatch_size),
            self.shards, data_axis=self.data_axis,
            param_rules=self.param_rules, donated=donated)

    def _segment_shardings(self, segment):
        from jax.sharding import PartitionSpec as P
        don_ids = set(id(v) for v in segment._don_vecs)

        def spec(vec):
            return self._spec_for(vec, donated=id(vec) in don_ids)

        in_s = (tuple(self._named(spec(v))
                      for v in segment._input_vecs),
                tuple(self._named(spec(v)) for v in segment._ro_vecs),
                tuple(self._named(spec(v)) for v in segment._don_vecs),
                None)                      # traced python scalars
        out_s = ([self._named(spec(v)) for v in segment._output_vecs],
                 [self._named(spec(v)) for v in segment._don_vecs],
                 # metrics are globally-reduced device scalars
                 self._named(P()))
        return in_s, out_s

    def _segment_psum_estimate(self, segment):
        """Analytic per-dispatch ICI traffic: every donated buffer that
        replicates while the segment consumes batch-sharded tensors is
        all-reduced in-program — a ring moves ``2·(n−1)/n`` of the
        reduced bytes (XLA's cost model does not expose collective
        traffic, so the ledger carries this estimate, clearly labeled
        next to the measured ``h2d_bytes``).  The formula lives in the
        shared pricing core (:func:`veles_tpu.analyze.pricing
        .segment_psum_bytes`), so the static planner's prediction and
        this ledger entry cannot drift."""
        from veles_tpu.analyze.pricing import segment_psum_bytes
        return segment_psum_bytes(
            segment, int(self.workflow.loader.max_minibatch_size),
            self.shards, data_axis=self.data_axis,
            param_rules=self.param_rules)

    def _segment_a2a_estimate(self, segment):
        """Analytic per-dispatch expert-dispatch traffic (zero without
        an ``expert`` mesh axis) — the shared pricing-core formula
        (:func:`veles_tpu.analyze.pricing.segment_all_to_all_bytes`),
        carried in the ledger's ``all_to_all_bytes`` column next to
        (never mixed into) the ring-reduce ``psum_bytes``."""
        from veles_tpu.analyze.pricing import segment_all_to_all_bytes
        return segment_all_to_all_bytes(
            segment, int(self.workflow.loader.max_minibatch_size),
            self.expert_shards)

    def _apply_shardings(self):
        """Pin every plan Vector's placement and swap every segment's
        jit wrapper — placements land eagerly so the first dispatch
        lowers against mesh-resident arguments (and the AOT
        executables can then enforce them)."""
        # fresh estimates: a re-install after rebuild_stitching (or a
        # reshard) must not accumulate entries keyed by dead segments
        self._psum_bytes = {}
        self._a2a_bytes = {}
        seen = set()
        sharded = []
        for segment in self._segments:
            in_s, out_s = self._segment_shardings(segment)
            segment.set_shardings(in_s, out_s)
            segment.pod = self
            # the ledger's axis dimension: this entry's program now
            # runs N-wide (updated again on reshard)
            segment.prof_entry.shards = self.shards
            self._psum_bytes[id(segment)] = \
                self._segment_psum_estimate(segment)
            self._a2a_bytes[id(segment)] = \
                self._segment_a2a_estimate(segment)
            don_ids = set(id(v) for v in segment._don_vecs)
            # output Vectors are pinned too: per-step programs only
            # WRITE them (already mesh-placed), but an epoch-scan
            # window passes them back in as carry placeholders — a
            # single-device host re-upload would then reject against
            # the window program's explicit shardings
            for vec in (segment._input_vecs + segment._ro_vecs
                        + segment._don_vecs + segment._output_vecs):
                if id(vec) in seen or not isinstance(vec, Vector):
                    continue
                seen.add(id(vec))
                vec.set_sharding(self._named(self._spec_for(
                    vec, donated=id(vec) in don_ids)))
                sharded.append(vec)
        # resident loader buffers outside any current plan (targets of
        # a future segment rebuild) re-place with the dataset rule too
        for vec in self.workflow.loader.resident_vectors():
            if isinstance(vec, Vector) and vec and id(vec) not in seen:
                seen.add(id(vec))
                vec.set_sharding(self._named(self._spec_for(vec)))
                sharded.append(vec)
        self._sharded_vecs = sharded
        # eager re-place: devmem under the new sharding NOW, so the
        # first dispatch (and its AOT lower) sees mesh-resident args
        for vec in sharded:
            if vec and vec.device is not None \
                    and not vec.device.is_interpret:
                vec.devmem
        # epoch-scan window programs compiled for the OLD placement
        # (or none) must rebuild against this mesh
        self._invalidate_scan()

    def segment_psum_bytes(self, segment):
        """Per-dispatch collective bytes for ``segment`` (the ledger
        hook the stitched dispatch path calls).  Per STEP: an
        epoch-scan window multiplies by its K (every scanned step runs
        the same in-program psum on the data axis)."""
        return self._psum_bytes.get(id(segment), 0)

    def segment_all_to_all_bytes(self, segment):
        """Per-dispatch expert all_to_all bytes for ``segment`` — the
        ledger hook twin of :meth:`segment_psum_bytes`; an epoch-scan
        window multiplies by its K the same way."""
        return self._a2a_bytes.get(id(segment), 0)

    def scan_shardings(self, plan, with_verdict=False, n_pred=0):
        """Explicit mesh shardings for an epoch-scan window program
        over ``plan`` (:class:`veles_tpu.epoch_scan.ScanPlan`) — the
        SAME per-Vector rule as the per-step segment programs
        (:func:`spec_for_vector`), so a window compiled over the pod
        is the per-step pod program with the step loop folded in:
        carry params/momentum replicate (or TP/FSDP-shard via
        ``param_rules``), batch-shaped outputs and the resident
        dataset shard the data axis, stacked per-step scalars / the
        metric accumulator / the verdict replicate."""
        from jax.sharding import PartitionSpec as P
        rep = self._named(P())

        def spec(vec, donated=False):
            return self._named(self._spec_for(vec, donated=donated))

        in_s = (tuple(spec(v, True) for v in plan.don_vecs),
                tuple(spec(v) for v in plan.out_vecs),
                tuple(spec(v) for v in plan.ext_vecs),
                tuple(rep for _ in range(plan.n_scalars)),
                rep,
                tuple(rep for _ in range(n_pred)))
        out_s = (tuple(spec(v, True) for v in plan.don_vecs),
                 tuple(spec(v) for v in plan.out_vecs),
                 tuple(rep for _ in plan.metric_spec),
                 rep,
                 {"improved": rep, "stop": rep} if with_verdict
                 else ())
        return in_s, out_s

    # -- elastic membership -------------------------------------------------
    def pre_dispatch(self, segment):
        """The chaos ``pod_chip`` site, consulted before every sharded
        dispatch: a scheduled ``chip_kill`` loses one chip and
        triggers the elastic reshard.  Unarmed chaos costs one
        attribute check."""
        if not chaos.controller.armed:
            return
        fault = chaos.controller.process("pod_chip", role="pod")
        if fault is not None and fault.action == "chip_kill":
            self.warning("chaos: chip killed under a %d-shard pod",
                         self.shards)
            self.reshard(lost=1)

    def reshard(self, lost=1, devices=None):
        """Shrink the mesh after losing ``lost`` chips (or rebuild
        over an explicit ``devices`` list) and resume from the last
        in-HBM-consistent step.

        The surviving ``data`` axis is the largest size that (a) fits
        the survivors and (b) still divides the global batch — with
        power-of-two batches this halves the axis, the documented
        shrink policy.  Every resident buffer re-places (params sync
        device→host first, so the exact post-last-step values carry
        over), every segment recompiles once against the new mesh, and
        the generation bumps so the control plane can tell pre-reshard
        state from post."""
        import jax

        survivors = list(devices) if devices is not None \
            else self.devices[:max(1, len(self.devices) - int(lost))]
        batch = int(self.workflow.loader.max_minibatch_size)
        other = 1
        for name, size in self.mesh.shape.items():
            if name != self.data_axis:
                other *= int(size)
        if len(survivors) < other:
            # only the data axis is elastic: model/pipeline shards
            # hold DIFFERENT parameter slices, so a pod cannot lose
            # below its non-data extent — fail with the remedy, not a
            # reshape traceback mid-dispatch
            raise PodError(
                "cannot reshard: %d surviving chip(s) cannot carry "
                "the mesh's non-data axes (product %d) — a TP/PP-"
                "sharded pod cannot shrink below its model extent; "
                "restore the chips or redeploy with a smaller "
                "topology" % (len(survivors), other))
        # non-data axes keep their extent; data absorbs what remains
        new_n = max(1, len(survivors) // other)
        while new_n > 1 and batch % new_n:
            new_n -= 1
        axes = {name: (new_n if name == self.data_axis else int(size))
                for name, size in self.mesh.shape.items()}
        names = tuple(axes)
        shape = tuple(axes[n] for n in names)
        count = int(numpy.prod(shape))
        grid = numpy.array(survivors[:count]).reshape(shape)
        old_shards = self.shards
        self.mesh = jax.sharding.Mesh(grid, names)
        self.generation += 1
        self.reshards += 1
        self._psum_bytes = {}
        self._apply_shardings()
        trace.instant("pod", "reshard",
                      {"generation": self.generation,
                       "shards": self.shards,
                       "was": old_shards}, role="pod")
        from veles_tpu import watch
        if watch.enabled():
            watch.publish("reshard", generation=self.generation,
                          shards=self.shards, was=old_shards,
                          reshards=self.reshards)
        self.warning(
            "pod resharded %d -> %d shard(s) (generation %d): "
            "dataset + params re-placed, %d segment program(s) "
            "recompiling, training resumes from the last "
            "in-HBM-consistent step", old_shards, self.shards,
            self.generation, len(self._segments))
        hook = self.on_reshard
        if hook is not None:
            try:
                hook(self)
            except Exception:
                self.exception("on_reshard hook failed")
        return self
