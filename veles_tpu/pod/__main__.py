"""``python -m veles_tpu.pod`` — the one-pod-one-program CLI.

``--smoke`` (the ``scripts/lint.sh`` CI gate; run under
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` for the 8-shard
CPU session) trains a seeded sample THREE ways and gates the pod path
on all of them:

1. a single-device stitched reference run (the parity oracle);
2. a full pod membership session over real localhost ZMQ — lease out,
   per-epoch ``pod_epoch`` syncs, one final update — with the chaos
   controller armed (empty schedule) so its wire-site frame counters
   PROVE zero per-step gradient/update frames crossed the wire and the
   control plane stayed O(heartbeats + epochs);
3. a chaos session replaying the PR 7 style schedule on the pod path:
   a chip kill mid-epoch (mesh shrink + reshard + generation bump), a
   duplicated final-update frame (dedup'd) and a dropped lease frame
   (lost-frame requeue) — completing with eval parity.

Also asserted: zero steady-state recompiles (the reshard's recompile
is a legitimate topology change, counted as warmup), the V-P02
preflight clean, and a mesh-sharded
:class:`veles_tpu.serve.engine.InferenceEngine` byte-identical to the
single-device forward over the trained weights.

Pod-of-pods legs (one per new parallelism axis):

5. **pp** — stacked stages pipelined over ``dp×pp``
   (:func:`~veles_tpu.parallel.pp.pipeline_apply`), each epoch ONE
   jitted scan, forward bitwise vs the sequential dp twin and trained
   weights within 5e-5;
6. **ep** — the switch-MoE sample routed by ``all_to_all`` over
   ``dp×ep``, token parity vs the dense reference at drop-free
   capacity;
7. **multihost** — a simulated 2-process session (the ``multihost``
   test double): coordinator lease + frameless follower, exactly ONE
   update frame per lease across hosts, single-process
   :class:`~veles_tpu.pod.pods.MultiHostPod` byte-identical to
   :class:`~veles_tpu.pod.runtime.PodRuntime`;
8. **device loss** — a heartbeat-silent host mid-epoch reshards
   (``jobs:heartbeat_stall`` + ``pod:reshard`` in the trace) and
   training completes with eval parity.
"""

import argparse
import json
import sys

import numpy

SMOKE_SEED = 20260804
SMOKE_EPOCHS = 3
SMOKE_BATCH = 64

#: the seeded 5-cluster task every distributed gate in this repo
#: trains (mirrors tests/test_chaos.py): 384 train + 128 validation
#: 16-feature points around 5 class centers — converges in 2 epochs,
#: compiles in seconds on the virtual CPU mesh
SMOKE_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 12},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 5},
     "<-": {"learning_rate": 0.05}},
]


def make_workflow(max_epochs=SMOKE_EPOCHS, batch=SMOKE_BATCH,
                  device=None, seed=21, is_master=False,
                  is_slave=False):
    """The smoke's stitched workflow over the seeded 5-cluster task.
    Default standalone (pod workers train full epochs locally, so NO
    slave-mode graph surgery); the launcher flags build the ZMQ
    per-minibatch twins the parity tests compare against."""
    from veles_tpu import prng
    from veles_tpu.backends import AutoDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class PodSmokeLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(5)
            n = 512
            labels = (numpy.arange(n) % 5).astype(int)
            centers = rng.standard_normal((5, 16)) * 3
            self.original_data.mem = (
                centers[labels]
                + rng.standard_normal((n, 16)) * 0.5
            ).astype(numpy.float32)
            self.original_labels = [int(v) for v in labels]
            self.class_lengths[:] = [0, 128, 384]

    prng.seed_all(seed)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: PodSmokeLoader(w,
                                                minibatch_size=batch),
        layers=[{**spec} for spec in SMOKE_LAYERS],
        decision_config={"max_epochs": max_epochs})
    wf.launcher = DummyLauncher(is_master=is_master,
                                is_slave=is_slave)
    wf.initialize(device=device or AutoDevice())
    return wf


def _reference_run(epochs):
    """The single-device parity oracle, driven by the SAME epoch
    stepper the pod worker uses (membership.train_epochs) so the two
    trajectories compare like for like."""
    from veles_tpu.pod import train_epochs
    wf = make_workflow(max_epochs=epochs)
    for _ in train_epochs(wf, epochs):
        pass
    return wf


def _pod_session(epochs, schedule=None, seed=SMOKE_SEED, mesh=None):
    """One full membership session over localhost ZMQ with chaos armed
    (``schedule`` may be empty = counters only).  Returns
    ``(master, server, worker, chaos_snapshot, survived)``."""
    from veles_tpu import chaos
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.parallel.jobs import JobServer
    from veles_tpu.pod import PodMaster, PodWorker

    chaos.controller.arm(list(schedule or []), seed=seed)
    # the master never dispatches kernels: NumpyDevice keeps its copy
    # of the dataset off the mesh (per-host device config does not
    # enter the checksum)
    master_wf = make_workflow(max_epochs=epochs, device=NumpyDevice())
    master = PodMaster(master_wf, pods=1, epochs=epochs)
    server = JobServer(master, heartbeat_interval=0.4).start()
    worker_wf = make_workflow(max_epochs=epochs)
    worker = PodWorker(worker_wf, server.endpoint, mesh=mesh,
                       rpc_timeout_ms=4000, reconnect_max_wait=10.0)
    try:
        survived = worker.run()
    finally:
        worker.close()
        server.stop()
        snap = chaos.controller.snapshot()
        chaos.controller.disarm()
    return master, server, worker, snap, survived


def _metrics_close(a, b, tol=2.0):
    """Eval parity: integer/flag fields equal, error-point fields
    within ``tol`` (the in-program psum reorders float reductions, so
    bitwise weight equality is not the contract — docs/
    distributed_training.md § Numerics)."""
    for key in set(a) & set(b):
        va, vb = a[key], b[key]
        if key == "complete":
            if bool(va) != bool(vb):
                return False
        elif abs(float(va) - float(vb)) > tol:
            return False
    return True


def _check_sharded_serving(wf, problems):
    """Satellite gate: the request/response InferenceEngine accepts
    the pod mesh and its pjit'd buckets answer byte-identically to
    the single-device engine over the SAME trained weights."""
    import jax

    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.serve.engine import InferenceEngine

    if len(jax.devices()) < 2:
        return
    mesh = mesh_from_topology({"data": -1}, require=("data",))
    batch = numpy.random.default_rng(7).standard_normal(
        (8, 16)).astype(numpy.float32)
    plain = InferenceEngine.from_workflow(wf, max_batch_size=8).warmup()
    sharded = InferenceEngine.from_workflow(
        wf, max_batch_size=8, mesh=mesh).warmup()
    a = plain.infer(batch)
    b = sharded.infer(batch)
    if a.shape != b.shape or not numpy.array_equal(a, b):
        problems.append(
            "mesh-sharded InferenceEngine diverged from the "
            "single-device forward (max |d|=%s)"
            % (numpy.max(numpy.abs(a - b)) if a.shape == b.shape
               else "shape"))


def _epoch_scan_gate(epochs, reference, problems):
    """The one-dispatch-per-epoch assertion: a PodRuntime-sharded
    workflow under ``engine.epoch_scan=auto`` must train each epoch
    in at most one scanned dispatch per non-empty class span (the
    K-step window covers the whole pass), with zero steady-state
    recompiles and eval parity with the single-device reference."""
    from veles_tpu import prof, trace
    from veles_tpu.config import root
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod import PodRuntime, eval_metrics, train_epochs

    saved_scan = root.common.engine.get("epoch_scan", "off")
    saved_trace = root.common.engine.get("trace", "off")
    saved_every = root.common.engine.get("metrics_every", 0)
    root.common.engine.epoch_scan = "auto"
    root.common.engine.trace = "on"
    # an ambient metrics_every bounds K and would split each class
    # pass into several windows — pin it off: this gate asserts the
    # headline one-dispatch-per-pass bound, not a flush cadence
    root.common.engine.metrics_every = 0
    try:
        wf = make_workflow(max_epochs=epochs)
        runtime = PodRuntime(wf, mesh=mesh_from_topology(
            {"data": -1}, require=("data",)))
        runtime.install()
        dispatches0 = trace.recorder.count("segment", "dispatch")
        recompiles0 = prof.ledger.recompiles
        for _ in train_epochs(wf, epochs):
            pass
        dispatches = trace.recorder.count("segment", "dispatch") \
            - dispatches0
        runner = getattr(wf, "_epoch_runner_", None)
        spans = sum(1 for n in wf.loader.class_lengths if n)
        budget = epochs * spans
        if runner is None or not runner.windows:
            problems.append(
                "epoch-scan gate: windows never engaged on the pod "
                "path (%r)" % (runner and runner.describe()))
        if dispatches > budget:
            problems.append(
                "epoch-scan gate: %d dispatches for %d epochs x %d "
                "class span(s) — an epoch is NOT one dispatch per "
                "pass" % (dispatches, epochs, spans))
        if prof.ledger.recompiles - recompiles0:
            problems.append(
                "epoch-scan gate: %d steady-state recompile(s) under "
                "scan windows"
                % (prof.ledger.recompiles - recompiles0))
        if not _metrics_close(reference, eval_metrics(wf)):
            problems.append(
                "epoch-scan gate: windowed pod metrics %r diverged "
                "from reference %r" % (eval_metrics(wf), reference))
        return dispatches, runner.windows if runner else 0
    finally:
        root.common.engine.epoch_scan = saved_scan
        root.common.engine.trace = saved_trace
        root.common.engine.metrics_every = saved_every
        trace.configure()


def _pp_gate(problems):
    """Pipeline leg: a homogeneous stacked-stage model trained via
    :func:`veles_tpu.parallel.pp.pipeline_apply` over a dp×pp mesh,
    each epoch folded into ONE jitted scan over minibatches (one
    dispatch per class pass), against a dp-only twin running the same
    stages as a sequential ``lax.scan`` on the same data order:
    forward bitwise-identical, trained weights within 5e-5 (microbatch
    summation reorders gradient adds at float epsilon), and ZERO
    steady-state recompiles (one compile per epoch program)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veles_tpu.analyze.pricing import pipeline_bubble
    from veles_tpu.parallel.mesh import make_mesh, replicated
    from veles_tpu.parallel.pp import pipeline_apply

    if len(jax.devices()) < 8:
        return None
    stages, dim, batch, n_micro, steps_per_epoch = 4, 16, 64, 8, 8
    mesh = make_mesh({"data": 2, "pipe": stages})
    rng = numpy.random.default_rng(11)
    params = {
        "w": jnp.asarray(rng.standard_normal(
            (stages, dim, dim)).astype(numpy.float32) * 0.3),
        "b": jnp.zeros((stages, dim), numpy.float32),
    }
    # the pp_rules placement: stacked stages shard their leading dim
    # over ``pipe`` (each device holds its stage's weights); the dp
    # twin replicates — pinning in/out shardings keeps every epoch
    # call on ONE compiled program (zero steady-state recompiles)
    pp_shard = {"w": NamedSharding(mesh, P("pipe", None, None)),
                "b": NamedSharding(mesh, P("pipe", None))}
    dp_shard = {"w": replicated(mesh), "b": replicated(mesh)}
    data = jnp.asarray(rng.standard_normal(
        (steps_per_epoch, batch, dim)).astype(numpy.float32))
    target = jnp.asarray(rng.standard_normal(
        (steps_per_epoch, batch, dim)).astype(numpy.float32))

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def seq_forward(p, x):
        def body(h, leaf):
            return stage_fn(leaf, h), None
        h, _ = jax.lax.scan(
            body, x, jax.tree.map(lambda l: l, p))
        return h

    def pp_forward(p, x):
        return pipeline_apply(stage_fn, p, x, mesh, n_micro=n_micro,
                              batch_axis="data")

    # forward parity FIRST: same stages, same values, bit for bit
    ref = jax.jit(seq_forward)(params, data[0])
    pp = jax.jit(pp_forward)(params, data[0])
    if not numpy.array_equal(numpy.asarray(ref), numpy.asarray(pp)):
        problems.append(
            "pp gate: pipeline_apply forward diverged bitwise from "
            "the sequential stage scan (max |d|=%s)"
            % numpy.abs(numpy.asarray(ref) - numpy.asarray(pp)).max())

    def epoch_fn(forward, shard):
        def loss_fn(p, x, y):
            return ((forward(p, x) - y) ** 2).mean()

        def step(p, xs):
            x, y = xs
            grads = jax.grad(loss_fn)(p, x, y)
            return jax.tree.map(lambda a, g: a - 0.1 * g, p,
                                grads), None

        def epoch(p):
            p, _ = jax.lax.scan(step, p, (data, target))
            return p
        return jax.jit(epoch, in_shardings=(shard,),
                       out_shardings=shard)

    seq_epoch = epoch_fn(seq_forward, dp_shard)
    pp_epoch = epoch_fn(pp_forward, pp_shard)
    p_seq = jax.device_put(params, dp_shard)
    p_pp = jax.device_put(params, pp_shard)
    for _ in range(SMOKE_EPOCHS):
        p_seq = seq_epoch(p_seq)         # one dispatch per class pass
        p_pp = pp_epoch(p_pp)
    for key in params:
        diff = numpy.abs(numpy.asarray(p_seq[key])
                         - numpy.asarray(p_pp[key])).max()
        if diff > 5e-5:
            problems.append(
                "pp gate: trained %r diverged %.2e (> 5e-5) from the "
                "dp oracle on the same data order" % (key, diff))
    for name, fn in (("dp", seq_epoch), ("pp", pp_epoch)):
        if fn._cache_size() != 1:
            problems.append(
                "pp gate: %s epoch program compiled %d time(s) over "
                "%d epochs — exactly one compile, zero steady-state "
                "recompiles" % (name, fn._cache_size(), SMOKE_EPOCHS))
    return {"stages": stages, "microbatches": n_micro,
            "bubble_fraction": pipeline_bubble(stages, n_micro),
            "epoch_dispatches": 1}


def _ep_gate(problems):
    """Expert leg: the switch-MoE sample routed by ``all_to_all`` over
    a dp×ep mesh vs its dense reference — at the drop-free capacity
    (``capacity_factor = n_experts``) top-1 routing loses no token, so
    logits must match token-for-token; a few sharded train steps must
    also run (and descend) without recompiling."""
    import jax

    from veles_tpu.parallel.mesh import make_mesh
    from veles_tpu.samples import moe

    if len(jax.devices()) < 8:
        return None
    cfg = dict(moe.TINY)
    mesh = make_mesh({"data": 2, "expert": 4})
    params = moe.init_params(cfg, seed=1)
    tokens = moe.synthetic_tokens(cfg, 16, seed=2)
    dense = numpy.asarray(moe.apply_fn(params, tokens, cfg, mesh=None))
    routed = numpy.asarray(moe.apply_fn(params, tokens, cfg,
                                        mesh=mesh))
    diff = numpy.abs(dense - routed).max()
    if diff > 1e-5:
        problems.append(
            "ep gate: routed MoE diverged %.2e from the dense "
            "reference at drop-free capacity (want token parity)"
            % diff)
    from jax.sharding import NamedSharding, PartitionSpec as P
    p, v, step = moe.build_train(cfg, mesh=mesh, seed=1)
    shard = {name: NamedSharding(mesh, spec)
             for name, spec in moe.param_specs(p).items()}
    p = jax.device_put(p, shard)
    v = jax.device_put(v, shard)
    toks = jax.device_put(tokens,
                          NamedSharding(mesh, P("data", "expert")))
    losses = []
    for _ in range(4):
        p, v, metrics = step(p, v, toks)
        losses.append(float(metrics["loss"]))
    if not losses[-1] < losses[0]:
        problems.append("ep gate: sharded MoE loss did not descend "
                        "(%r)" % losses)
    if step._cache_size() != 1:
        problems.append(
            "ep gate: %d compile(s) across %d identical steps — "
            "exactly one compile, zero steady-state recompiles"
            % (step._cache_size(), len(losses)))
    return {"experts": cfg["experts"], "expert_shards": 4,
            "max_token_diff": float(diff)}


def _multihost_gate(epochs, problems):
    """Multi-host leg, on one real process via the ``multihost``
    test double:

    * a single-process :class:`~veles_tpu.pod.pods.MultiHostPod` (no
      coordinator) must train bitwise-identically to a plain
      :class:`PodRuntime` — the transparent-delegation contract;
    * a simulated 2-process session — rank 0 a full coordinator
      :class:`~veles_tpu.pod.pods.MultiHostPodWorker` ZMQ lease, rank
      1 a follower — must put exactly ONE update frame on the wire
      (the follower owns no socket) and leave both ranks with
      identical trained weights (lockstep SPMD, sequentially
      simulated);
    * :meth:`MultiHostPod.assemble` must rebuild the global batch from
      per-rank host-local shards.
    """
    from veles_tpu import chaos
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.parallel import multihost
    from veles_tpu.parallel.jobs import JobServer
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod import (MultiHostPod, MultiHostPodWorker,
                               PodMaster, PodRuntime, capture_params,
                               train_epochs)

    # (a) single-process transparency: byte-identical to PodRuntime
    wf_plain = make_workflow(max_epochs=epochs)
    PodRuntime(wf_plain, mesh=mesh_from_topology(
        {"data": -1}, require=("data",))).install()
    for _ in train_epochs(wf_plain, epochs):
        pass
    wf_multi = make_workflow(max_epochs=epochs)
    MultiHostPod(wf_multi).install()
    for _ in train_epochs(wf_multi, epochs):
        pass
    for i, (a, b) in enumerate(zip(capture_params(wf_plain),
                                   capture_params(wf_multi))):
        for key in a:
            if not numpy.array_equal(a[key], b[key]):
                problems.append(
                    "multihost gate: single-process MultiHostPod "
                    "unit %d %r diverged from plain PodRuntime "
                    "(must be byte-identical)" % (i, key))

    # (b) 2-process session: coordinator lease + frameless follower
    update_frames = -1
    with multihost.process_double(2) as dbl:
        chaos.controller.arm([], seed=SMOKE_SEED)
        server = w0 = w1 = None
        try:
            master_wf = make_workflow(max_epochs=epochs,
                                      device=NumpyDevice())
            master = PodMaster(master_wf, pods=1, epochs=epochs)
            server = JobServer(master,
                               heartbeat_interval=0.4).start()
            with dbl.rank(0):
                wf0 = make_workflow(max_epochs=epochs)
                w0 = MultiHostPodWorker(wf0, server.endpoint)
                if not w0.pod.is_coordinator or w0.worker is None:
                    problems.append("multihost gate: rank 0 did not "
                                    "become the coordinator")
                if not w0.run():
                    problems.append("multihost gate: coordinator "
                                    "session did not survive")
            with dbl.rank(1):
                wf1 = make_workflow(max_epochs=epochs)
                w1 = MultiHostPodWorker(wf1, server.endpoint)
                if w1.worker is not None:
                    problems.append("multihost gate: rank 1 opened a "
                                    "control-plane socket")
                w1.run()
            if not master.done:
                problems.append("multihost gate: lease never "
                                "finished")
        finally:
            for w in (w0, w1):
                if w is not None:
                    w.close()
            if server is not None:
                server.stop()
            snap = chaos.controller.snapshot()
            chaos.controller.disarm()
        frames = snap.get("wire_frames", {})
        update_frames = sum(n for key, n in frames.items()
                            if key == "master_recv:update")
        if update_frames != 1:
            problems.append(
                "multihost gate: %d update frame(s) across 2 "
                "simulated hosts (want exactly 1 — the coordinator's "
                "final lease update)" % update_frames)
        for i, (a, b) in enumerate(zip(capture_params(wf0),
                                       capture_params(wf1))):
            for key in a:
                if not numpy.array_equal(a[key], b[key]):
                    problems.append(
                        "multihost gate: rank weights diverged (unit "
                        "%d %r) — lockstep ranks must train "
                        "identically" % (i, key))

        # (c) host-local shards -> one global array
        full = numpy.arange(64, dtype=numpy.float32).reshape(16, 4)
        start, stop = 0, 0
        with dbl.rank(0):
            lo, hi = w1.pod.host_range(len(full))
            w1.pod.assemble(full[lo:hi])
            start = lo
        with dbl.rank(1):
            lo, hi = w1.pod.host_range(len(full))
            assembled = w1.pod.assemble(full[lo:hi])
            stop = hi
        if (start, stop) != (0, 16) or not numpy.array_equal(
                numpy.asarray(assembled), full):
            problems.append(
                "multihost gate: host-local shards did not assemble "
                "into the global batch (range %r)" % ((start, stop),))
    return {"processes": 2, "update_frames": update_frames}


def _device_loss_gate(epochs, reference, problems):
    """Device-loss leg: a heartbeat-silent host declared lost MID-epoch
    must reshard the runtime (generation bump, ``pod:reshard`` next to
    ``jobs:heartbeat_stall`` in the trace) and training must still
    complete with eval parity; the typed-error classifier must accept
    device-loss spellings and reject program bugs."""
    import jax

    from veles_tpu import trace
    from veles_tpu.config import root
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod import (DeviceLossDetector, PodRuntime,
                               eval_metrics, is_device_loss,
                               train_epochs)

    if len(jax.devices()) < 2:
        return None
    saved_trace = root.common.engine.get("trace", "off")
    root.common.engine.trace = "on"
    trace.configure()
    try:
        wf = make_workflow(max_epochs=epochs)
        runtime = PodRuntime(wf, mesh=mesh_from_topology(
            {"data": -1}, require=("data",)))
        runtime.install()
        clock = {"now": 0.0}
        # the virtual 8-chip mesh as 2 hosts x 4 chips
        detector = DeviceLossDetector(
            runtime, timeout=5.0,
            devices_per_host=max(1, len(runtime.devices) // 2),
            clock=lambda: clock["now"])
        detector.beat("host-1")
        stalls0 = trace.recorder.count("jobs", "heartbeat_stall")
        reshards0 = trace.recorder.count("pod", "reshard")
        shards_before = runtime.shards
        for epoch in train_epochs(wf, epochs):
            if epoch == 1:
                clock["now"] += 10.0       # host-1 goes silent …
                detector.beat("host-0")    # … the survivor still beats
                if detector.poll() != ["host-1"]:
                    problems.append("device-loss gate: the silent "
                                    "host was not declared lost")
        if runtime.reshards != 1 or runtime.generation != 2:
            problems.append(
                "device-loss gate: heartbeat loss did not reshard "
                "(reshards=%d generation=%d)"
                % (runtime.reshards, runtime.generation))
        if runtime.shards >= shards_before:
            problems.append(
                "device-loss gate: mesh did not shrink (%d -> %d)"
                % (shards_before, runtime.shards))
        if trace.recorder.count("jobs", "heartbeat_stall") \
                - stalls0 != 1:
            problems.append("device-loss gate: jobs:heartbeat_stall "
                            "instant missing from the trace")
        if trace.recorder.count("pod", "reshard") - reshards0 != 1:
            problems.append("device-loss gate: pod:reshard instant "
                            "missing from the trace")
        if not _metrics_close(reference, eval_metrics(wf)):
            problems.append(
                "device-loss gate: post-loss metrics %r diverged "
                "from reference %r" % (eval_metrics(wf), reference))
        for exc, want in (
                (RuntimeError("UNAVAILABLE: socket closed"), True),
                (RuntimeError("device lost: slice health"), True),
                (RuntimeError("Invalid argument: dot shape"), False),
                (ValueError("batch mismatch"), False)):
            if is_device_loss(exc) is not want:
                problems.append(
                    "device-loss gate: %r misclassified (want "
                    "device_loss=%s)" % (exc, want))
        return {"shards_before": shards_before,
                "shards_after": runtime.shards,
                "generation": runtime.generation}
    finally:
        root.common.engine.trace = saved_trace
        trace.configure()


def run_smoke(as_json=False, epochs=SMOKE_EPOCHS):
    import jax

    from veles_tpu import prof
    from veles_tpu.pod import eval_metrics

    problems = []
    n_devices = len(jax.devices())
    if n_devices < 2:
        print("pod smoke: WARNING — %d device(s); run under "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "for the 8-shard gate (continuing on the 1-device "
              "fallback)" % n_devices, file=sys.stderr)

    # 1) single-device parity oracle
    reference_wf = _reference_run(epochs)
    reference = eval_metrics(reference_wf)
    if not reference["complete"]:
        problems.append("reference run did not complete")

    # 2) clean pod session, chaos armed with an EMPTY schedule so the
    #    wire-site counters record every frame without injecting
    recompiles_before = prof.ledger.recompiles
    master, server, worker, snap, survived = _pod_session(epochs)
    shards = worker.runtime.shards if worker.runtime else 0
    pod_metrics = (master.done.get("pod-0") or {}).get("metrics") or {}
    frames = snap.get("wire_frames", {})

    def count(op):
        return sum(n for key, n in frames.items()
                   if key == "master_recv:%s" % op)

    update_frames = count("update")
    job_frames = sum(n for key, n in frames.items()
                     if key == "master_send:job")
    epoch_frames = count("pod_epoch")
    if not survived:
        problems.append("clean pod session did not survive")
    if not master.done:
        problems.append("lease never finished")
    # THE wire gate: one final update per lease, zero per-step
    # gradient/update frames — steady state trained (epochs ×
    # minibatches) steps but the wire saw O(heartbeats + epochs)
    minibatches = epochs * (512 // SMOKE_BATCH)
    if update_frames != 1:
        problems.append(
            "wire gate: %d update frame(s) on the wire (want exactly "
            "1 — the final lease update)" % update_frames)
    if epoch_frames > epochs + 1:
        problems.append(
            "wire gate: %d pod_epoch frames for %d epochs — the "
            "control plane is not O(epochs)" % (epoch_frames, epochs))
    if update_frames + job_frames >= minibatches:
        problems.append(
            "wire gate: %d data-plane frames vs %d minibatches — "
            "per-step traffic survived"
            % (update_frames + job_frames, minibatches))
    if prof.ledger.recompiles - recompiles_before:
        problems.append(
            "%d steady-state recompile(s) during the clean pod "
            "session" % (prof.ledger.recompiles - recompiles_before))
    if shards != n_devices:
        problems.append("pod ran %d shard(s) on %d devices"
                        % (shards, n_devices))
    if not _metrics_close(reference, pod_metrics):
        problems.append(
            "parity gate: pod metrics %r vs single-device %r"
            % (pod_metrics, reference))

    # 2b) one-dispatch-per-epoch: the SAME pod path under
    #     engine.epoch_scan=auto — a whole class pass must fold into
    #     ONE scanned dispatch (so an epoch is one dispatch per
    #     non-empty class), with zero steady-state recompiles and
    #     eval parity against the reference
    scan_dispatches, scan_windows = _epoch_scan_gate(
        epochs, reference, problems)

    # 3) chaos session on the pod path: chip kill mid-epoch + dup'd
    #    final update + dropped lease frame
    chaos_schedule = [
        {"site": "pod_chip", "action": "chip_kill", "nth": 3},
        {"site": "slave_send", "action": "dup", "op": "update",
         "nth": 1},
        {"site": "master_send", "action": "drop", "op": "job",
         "nth": 1},
    ]
    cmaster, cserver, cworker, csnap, csurvived = _pod_session(
        epochs, schedule=chaos_schedule)
    cmetrics = (cmaster.done.get("pod-0") or {}).get("metrics") or {}
    if not csurvived or not cmaster.done:
        problems.append("chaos pod session did not complete")
    injected = csnap.get("injected", {})
    if n_devices >= 2 and injected.get("chip_kill", 0) != 1:
        problems.append("the scheduled chip kill never fired: %r"
                        % injected)
    if n_devices >= 2 and cworker.runtime \
            and cworker.runtime.reshards != 1:
        problems.append("chip kill did not reshard (reshards=%r)"
                        % (cworker.runtime
                           and cworker.runtime.reshards))
    if n_devices >= 2 and cworker.runtime \
            and cworker.runtime.generation != 2:
        problems.append("reshard did not bump the generation")
    if injected.get("drop", 0) and not (cserver.lost_requeued
                                        or csurvived):
        problems.append("dropped lease frame was never requeued")
    if cserver.dedup_dropped < injected.get("dup", 0):
        problems.append(
            "dup'd final update slipped past dedup (%d < %d)"
            % (cserver.dedup_dropped, injected.get("dup", 0)))
    if not _metrics_close(reference, cmetrics):
        problems.append(
            "chaos parity gate: %r vs reference %r"
            % (cmetrics, reference))

    # 4) the mesh-sharded serving satellite, over the trained weights
    try:
        _check_sharded_serving(reference_wf, problems)
    except Exception as exc:
        problems.append("sharded InferenceEngine check raised: %s: %s"
                        % (type(exc).__name__, exc))

    # 5) pipeline parallelism: stage-sharded stages, one dispatch per
    #    class pass, bitwise forward parity vs the dp twin
    try:
        pp_stats = _pp_gate(problems)
    except Exception as exc:
        problems.append("pp gate raised: %s: %s"
                        % (type(exc).__name__, exc))
        pp_stats = None

    # 6) expert parallelism: all_to_all-routed MoE, token parity vs
    #    the dense reference at drop-free capacity
    try:
        ep_stats = _ep_gate(problems)
    except Exception as exc:
        problems.append("ep gate raised: %s: %s"
                        % (type(exc).__name__, exc))
        ep_stats = None

    # 7) multi-host pod (simulated 2-process session): one update
    #    frame per lease across hosts, single-process byte-identity
    try:
        mh_stats = _multihost_gate(epochs, problems)
    except Exception as exc:
        problems.append("multihost gate raised: %s: %s"
                        % (type(exc).__name__, exc))
        mh_stats = None

    # 8) real device-loss detection: heartbeat stall -> reshard ->
    #    completed training with eval parity
    try:
        loss_stats = _device_loss_gate(epochs, reference, problems)
    except Exception as exc:
        problems.append("device-loss gate raised: %s: %s"
                        % (type(exc).__name__, exc))
        loss_stats = None

    pod_stats = (master.done.get("pod-0") or {}).get("pod") or {}
    summary = {
        "ok": not problems,
        "devices": n_devices,
        "shards": shards,
        "epochs": epochs,
        "update_frames": update_frames,
        "pod_epoch_frames": epoch_frames,
        "minibatches_trained": minibatches,
        "psum_bytes_per_step": pod_stats.get("psum_bytes_per_step"),
        "epoch_scan_dispatches": scan_dispatches,
        "epoch_scan_windows": scan_windows,
        "reshards_under_chaos": cworker.runtime.reshards
        if cworker.runtime else None,
        "chaos_injected": injected,
        "pp": pp_stats,
        "ep": ep_stats,
        "multihost": mh_stats,
        "device_loss": loss_stats,
        "reference_metrics": reference,
        "pod_metrics": pod_metrics,
        "problems": problems,
    }
    if as_json:
        print(json.dumps(summary, indent=2, default=float))
    else:
        print("pod smoke: %d shard(s)/%d device(s), %d epoch(s), "
              "%d update frame(s) on the wire for %d minibatches "
              "trained, %s psum/step, epoch-scan %d dispatch(es)/"
              "%d window(s), chaos reshard gen=%s"
              % (shards, n_devices, epochs, update_frames,
                 minibatches, pod_stats.get("psum_bytes_per_step"),
                 scan_dispatches, scan_windows,
                 cworker.runtime.generation if cworker.runtime
                 else "-"))
        print("pod smoke legs: pp=%r ep=%r multihost=%r "
              "device_loss=%r" % (pp_stats, ep_stats, mh_stats,
                                  loss_stats))
        for problem in problems:
            print("PROBLEM: %s" % problem)
    return 0 if not problems else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.pod",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI one-pod-one-program gate")
    parser.add_argument("--epochs", type=int, default=SMOKE_EPOCHS)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary")
    args = parser.parse_args(argv)
    if args.smoke:
        # in-code watchdog on top of the caller's `timeout` wrapper —
        # a hang IS a failure mode here (lease wait loops), never a
        # silent stall
        import signal

        def _hang(signum, frame):
            print("PROBLEM: pod smoke hung (watchdog)",
                  file=sys.stderr)
            import os
            os._exit(3)
        signal.signal(signal.SIGALRM, _hang)
        signal.alarm(480)
        try:
            return run_smoke(as_json=args.json, epochs=args.epochs)
        finally:
            signal.alarm(0)
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
