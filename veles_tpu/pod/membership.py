"""Pod membership: the control-plane split.

With :class:`veles_tpu.pod.runtime.PodRuntime` aggregating gradients
in-program, the ZMQ job layer stops carrying minibatches.  What
remains — and what this module implements over the unchanged
:class:`veles_tpu.parallel.jobs.JobServer` / ``JobClient`` machinery —
is membership:

* the master assigns **pod leases** instead of per-minibatch jobs: one
  ``job`` frame carries a whole training assignment (epoch budget,
  mesh topology, lease id); heartbeats keep the worker alive in the
  master's reaper exactly as before;
* a worker syncs once per EPOCH (op ``pod_epoch``): lease progress,
  eval metrics, and its runtime's generation (bumped by any elastic
  chip-kill reshard) go up; the Decision verdict (``stop``) comes
  back; the master's checkpoint cadence triggers off the same frame;
* ONE final ``update`` per lease ships the trained parameters + eval
  metrics — deduplicated, generation-checked and requeue-safe by the
  PR 7 exactly-once machinery, because it IS an ordinary job update;
* elastic membership is the existing requeue path: a reaped or
  re-handshaking worker's lease goes back on the queue
  (``drop_slave``), and a worker re-granted a lease it already
  progressed CONTINUES from its local epoch counter — its training
  state never left its own HBM, so a master restart costs the pod
  nothing but a re-handshake (the master-kill-and-resume story).

Steady-state wire traffic is therefore O(heartbeats + epochs) — the
chaos controller's wire-site frame counters are the proof the pod
smoke and the acceptance tests assert on.
"""

import time

import numpy

from veles_tpu import trace
from veles_tpu.logger import Logger
from veles_tpu.obs import context as obs_context
from veles_tpu.parallel.mesh import mesh_from_topology
from veles_tpu.pod.runtime import PodRuntime


def capture_params(workflow):
    """Host copies of the trained forward parameters, one dict per
    forward unit — the final-update payload (and the master-side
    install's input)."""
    out = []
    for unit in workflow.forwards:
        entry = {}
        if getattr(unit, "weights", None) and unit.weights:
            unit.weights.map_read()
            entry["weights"] = numpy.array(unit.weights.mem)
        if getattr(unit, "bias", None) and unit.bias:
            unit.bias.map_read()
            entry["bias"] = numpy.array(unit.bias.mem)
        out.append(entry)
    return out


def install_params(workflow, payload):
    """Install a :func:`capture_params` payload into a workflow's
    forward units (whole-buffer reset, the PR 4 fast install)."""
    for unit, entry in zip(workflow.forwards, payload):
        if "weights" in entry:
            unit.weights.reset(entry["weights"])
        if "bias" in entry and getattr(unit, "bias", None) is not None:
            unit.bias.reset(entry["bias"])


def eval_metrics(workflow):
    """Decision-side eval summary (JSON-able) — the per-epoch sync
    payload and the parity gate's comparison record."""
    decision = workflow.decision
    out = {"epochs": int(workflow.loader.epoch_number),
           "complete": bool(decision.complete)}
    for attr in ("best_n_err_pt", "best_epoch", "best_mse",
                 "min_validation_n_err"):
        value = getattr(decision, attr, None)
        if value is not None:
            out[attr] = float(value)
    return out


def train_epochs(workflow, epochs, already=0):
    """Drive a standalone workflow epoch-by-epoch (generator yielding
    the completed epoch number after each) — the ONE driver both the
    pod worker and the parity references use, so "epoch boundary"
    means the same thing on every side of a comparison.  ``already``
    skips epochs a re-granted lease completed before a master
    restart."""
    decision = workflow.decision
    for epoch in range(int(already), int(epochs)):
        if int(workflow.loader.epoch_number) >= epoch + 1:
            # this epoch already ran (resumed lease) — report only
            yield epoch + 1
            continue
        decision.complete <<= False
        decision.max_epochs = epoch + 1
        workflow.run()
        yield epoch + 1


#: message fragments of the typed XLA dispatch errors that mean a
#: participating device/host is GONE (vs. a programming error, which
#: must propagate): the gRPC status spellings the PJRT runtime uses
#: for coordinator/peer loss plus the explicit device-loss wordings
DEVICE_LOSS_MARKERS = ("unavailable", "device lost", "data loss",
                       "deadline exceeded", "failed to connect",
                       "connection reset", "socket closed",
                       "halted", "slice health")


def is_device_loss(exc):
    """Classify an exception from a sharded dispatch: ``True`` when it
    is a runtime/XLA error whose message names a lost device or peer
    (an :data:`DEVICE_LOSS_MARKERS` fragment), ``False`` for anything
    that looks like a program bug — those must propagate, not trigger
    an elastic reshard that would silently mask them."""
    if not isinstance(exc, Exception):
        return False
    name = type(exc).__name__
    if not (isinstance(exc, RuntimeError)
            or "XlaRuntimeError" in name or "JaxRuntimeError" in name):
        return False
    text = ("%s: %s" % (name, exc)).lower()
    return any(marker in text for marker in DEVICE_LOSS_MARKERS)


class DeviceLossDetector(Logger):
    """Real device-loss detection feeding :meth:`PodRuntime.reshard`
    — the production twin of the chaos ``pod_chip`` site.

    Two independent signals, both landing on the SAME elastic path
    (mesh shrink, generation bump — the membership layer's epoch sync
    then reports the new generation upstream and the master's reaper/
    requeue machinery re-grants the lease):

    * **heartbeats** — co-hosts of a multi-host pod :meth:`beat` this
      detector (the launcher's ssh keepalive, or the worker loop on
      each epoch boundary); :meth:`poll` declares any host silent for
      ``timeout`` seconds lost, emits the ``jobs:heartbeat_stall``
      instant (the exact counter the scheduler's reaper publishes, so
      one Perfetto query finds both) and resharding drops its
      ``devices_per_host`` chips;
    * **dispatch failures** — :meth:`dispatch_failure` classifies an
      exception raised by a sharded dispatch through
      :func:`is_device_loss`; a typed device-loss reshards and
      returns True (caller retries the step), anything else returns
      False (caller re-raises).

    ``clock`` is injectable for tests (default ``time.monotonic``).
    """

    def __init__(self, runtime, timeout=5.0, devices_per_host=1,
                 clock=None, **kwargs):
        super(DeviceLossDetector, self).__init__(**kwargs)
        self.runtime = runtime
        self.timeout = float(timeout)
        self.devices_per_host = max(1, int(devices_per_host))
        self._clock = clock if clock is not None else time.monotonic
        self._beats = {}          # host -> last beat timestamp
        self.stalls = 0           # heartbeat losses declared
        self.dispatch_losses = 0  # typed dispatch-failure losses

    def beat(self, host, now=None):
        """Record a liveness beat from ``host`` (any hashable id)."""
        self._beats[host] = self._clock() if now is None else now

    def hosts(self):
        return sorted(self._beats)

    def poll(self, now=None):
        """Declare hosts silent for > ``timeout`` lost; reshard once
        for all of them.  Returns the list of lost host ids."""
        now = self._clock() if now is None else now
        lost = [host for host, beat in self._beats.items()
                if now - beat > self.timeout]
        for host in lost:
            gap = now - self._beats.pop(host)
            self.stalls += 1
            # the scheduler reaper's exact instant spelling
            # (parallel/jobs.py), so the merged timeline shows the
            # pod's host loss in the same lane family as slave stalls
            trace.instant("jobs", "heartbeat_stall",
                          {"slave": host, "gap_ms": round(gap * 1e3,
                                                          1)},
                          role="pod")
            self.warning(
                "pod host %r silent for %.1fs (timeout %.1fs) — "
                "declaring its %d chip(s) lost", host, gap,
                self.timeout, self.devices_per_host)
        if lost:
            self.runtime.reshard(
                lost=self.devices_per_host * len(lost))
        return lost

    def dispatch_failure(self, exc):
        """True = ``exc`` was a typed device loss and the pod
        resharded (retry the dispatch); False = not ours, re-raise."""
        if not is_device_loss(exc):
            return False
        self.dispatch_losses += 1
        self.warning("sharded dispatch failed with a device-loss "
                     "error (%s) — resharding", exc)
        self.runtime.reshard(lost=self.devices_per_host)
        return True


class PodMaster(Logger):
    """The master-side workflow adapter a :class:`veles_tpu.parallel
    .jobs.JobServer` serves: lease assignment, per-epoch Decision
    sync, final-update installation, elastic requeue.

    ``workflow``: the master's own (never-running) workflow — the
    checksum anchor, the weight-install target, and the delegate for
    the server's checkpoint protocol.  ``pods``: number of leases
    (independent pod assignments) to hand out.  ``epochs``: the per-
    lease epoch budget (default: the workflow Decision's
    ``max_epochs``).  ``topology``: mesh topology shipped inside the
    lease (None → each worker reads its own knob)."""

    def __init__(self, workflow, pods=1, epochs=None, topology=None,
                 **kwargs):
        super(PodMaster, self).__init__(**kwargs)
        self.workflow = workflow
        self.epochs = int(epochs
                          or getattr(workflow.decision, "max_epochs",
                                     1))
        self.topology = topology
        self._queue = ["pod-%d" % i for i in range(int(pods))]
        self._assigned = {}         # sid -> lease id
        self.done = {}              # lease id -> final update payload
        self.progress = {}          # lease id -> last pod_epoch msg
        #: operator stop switch: the next epoch sync of every lease
        #: answers stop=1 (Decision-level early stop across the pod)
        self.stop_requested = False
        self.total = int(pods)

    # -- the JobServer workflow contract ------------------------------------
    def checksum(self):
        return self.workflow.checksum()

    def generate_data_for_slave(self, slave):
        from veles_tpu.workflow import NoJobYet
        if self._queue:
            lease_id = self._queue.pop(0)
            self._assigned[slave.id] = lease_id
            self.info("granting lease %s to %s (%d epoch(s))",
                      lease_id, slave.id, self.epochs)
            return {"pod_lease": {
                "lease": lease_id, "epochs": self.epochs,
                "topology": self.topology}}
        if len(self.done) < self.total:
            # every lease is out with a live worker: more work may
            # still requeue (a reaped pod) — workers wait, not quit
            raise NoJobYet
        return None

    def apply_data_from_slave(self, data, slave):
        lease_id = data.get("lease")
        self._assigned.pop(slave.id, None)
        if data.get("params"):
            install_params(self.workflow, data["params"])
        self.done[lease_id] = data
        self.info("lease %s finished: %r", lease_id,
                  data.get("metrics"))

    def drop_slave(self, slave):
        """Elastic requeue: a dead/re-handshaking worker's unfinished
        lease goes back on the queue for the next worker."""
        lease_id = self._assigned.pop(slave.id, None)
        if lease_id is not None and lease_id not in self.done:
            self._queue.append(lease_id)
            self.info("requeued lease %s from dropped worker %s",
                      lease_id, slave.id)

    def on_pod_epoch(self, msg, slave):
        """The per-epoch Decision sync (see
        :meth:`veles_tpu.parallel.jobs.JobServer._on_pod_epoch`)."""
        lease_id = msg.get("lease")
        self.progress[lease_id] = {
            "epoch": int(msg.get("epoch", 0)),
            "generation": int(msg.get("generation", 1)),
            "shards": int(msg.get("shards", 1)),
            "metrics": msg.get("metrics") or {},
            "worker": slave.id,
        }
        from veles_tpu import watch
        if watch.enabled():
            watch.publish("pod_epoch", lease=lease_id,
                          leases_queued=len(self._queue),
                          leases_done=len(self.done),
                          **self.progress[lease_id])
        stop = self.stop_requested \
            or int(msg.get("epoch", 0)) >= self.epochs
        return {"stop": int(bool(stop))}

    # -- checkpoint protocol passthrough (master crash-recovery) ------------
    def capture_train_state(self):
        return self.workflow.capture_train_state()

    def restore_train_state(self, train, meta):
        return self.workflow.restore_train_state(train, meta)

    # -- scrape surface (appended to the hosting JobServer's
    # -- metrics_text by its workflow passthrough) ---------------------------
    def metrics_text(self):
        """The lease table as Prometheus gauges — the pod master's
        slice of the master scrape endpoint."""
        lines = [
            "# HELP veles_pod_leases_queued pod leases waiting for a "
            "worker",
            "# TYPE veles_pod_leases_queued gauge",
            "veles_pod_leases_queued %d" % len(self._queue),
            "# TYPE veles_pod_leases_assigned gauge",
            "veles_pod_leases_assigned %d" % len(self._assigned),
            "# TYPE veles_pod_leases_done gauge",
            "veles_pod_leases_done %d" % len(self.done),
            "# TYPE veles_pod_leases_total gauge",
            "veles_pod_leases_total %d" % self.total,
            "# HELP veles_pod_lease_epoch last reported epoch per "
            "lease",
            "# TYPE veles_pod_lease_epoch gauge",
        ]
        for lease_id in sorted(self.progress):
            lines.append('veles_pod_lease_epoch{lease="%s"} %d'
                         % (lease_id,
                            self.progress[lease_id].get("epoch", 0)))
        return "\n".join(lines) + "\n"


class PodWorker(Logger):
    """The slave-side driver: ONE :class:`veles_tpu.parallel.jobs
    .JobClient` whose single "job" is a pod lease.

    The client's existing machinery supplies everything around the
    lease: the heartbeat thread keeps the master's reaper quiet while
    epochs run inside ``do_job``, ``_send_update_with_retry`` makes
    the final update exactly-once, and ``_reconnect`` survives master
    restarts — after which the re-granted lease resumes from this
    worker's local epoch counter (the trained params never left its
    HBM).

    ``mesh`` overrides the lease/knob topology; ``param_rules``
    forwards to :class:`PodRuntime` (TP/FSDP parameter sharding)."""

    def __init__(self, workflow, endpoint, mesh=None, param_rules=None,
                 sid=None, rpc_timeout_ms=5000, reconnect_max_wait=30.0,
                 heartbeat_interval=None, **kwargs):
        super(PodWorker, self).__init__(**kwargs)
        from veles_tpu.parallel.jobs import (HEARTBEAT_INTERVAL,
                                             JobClient)
        self.workflow = workflow
        self.mesh = mesh
        self.param_rules = param_rules
        self.runtime = None
        #: lease id -> epochs completed locally (resume-on-regrant)
        self._progress = {}
        self.client = JobClient(
            self, endpoint, sid=sid, rpc_timeout_ms=rpc_timeout_ms,
            reconnect_max_wait=reconnect_max_wait,
            heartbeat_interval=heartbeat_interval
            if heartbeat_interval is not None else HEARTBEAT_INTERVAL)

    # -- the JobClient workflow contract ------------------------------------
    def checksum(self):
        return self.workflow.checksum()

    def do_job(self, data, callback):
        """One job = one lease: install the runtime, train the epoch
        budget with per-epoch syncs, answer with the final params +
        metrics.  A lease this worker already progressed (master
        restart → requeue → re-grant) resumes at its local counter."""
        lease = data.get("pod_lease") or {}
        lease_id = lease.get("lease", "pod-0")
        epochs = int(lease.get("epochs") or 1)
        self._ensure_runtime(lease)
        already = self._progress.get(lease_id, 0)
        if already:
            self.info("lease %s re-granted at epoch %d/%d — resuming "
                      "from the in-HBM state", lease_id, already,
                      epochs)
        for epoch in train_epochs(self.workflow, epochs,
                                  already=already):
            self._progress[lease_id] = epoch
            if self._sync_epoch(lease_id, epoch):
                self.info("master stopped lease %s at epoch %d",
                          lease_id, epoch)
                break
        callback({
            "lease": lease_id,
            "params": capture_params(self.workflow),
            "metrics": eval_metrics(self.workflow),
            "pod": self.runtime.describe(),
        })

    def _ensure_runtime(self, lease):
        if self.runtime is not None and self.runtime.installed:
            return
        mesh = self.mesh
        if mesh is None and lease.get("topology") is not None:
            mesh = mesh_from_topology(lease["topology"],
                                      require=("data",))
        self.runtime = PodRuntime(self.workflow, mesh=mesh,
                                  param_rules=self.param_rules)
        self.runtime.install()

    def _sync_epoch(self, lease_id, epoch):
        """One control-plane frame per epoch; a silent master is
        re-handshaked ONCE and the sync retried — a master that stays
        gone does not stall training (the pod is autonomous; the
        final update's own retry/reconnect settles the books)."""
        # the sync rides the lease's trace context (activated by the
        # JobClient around do_job), so the master's pod_epoch instant
        # lands in the same request waterfall
        msg = obs_context.wire_inject(
            {"op": "pod_epoch", "lease": lease_id, "epoch": epoch,
             "generation": self.runtime.generation,
             "shards": self.runtime.shards,
             "metrics": eval_metrics(self.workflow)})
        for attempt in (1, 2):
            try:
                reply = self.client.control(dict(msg))
            except TimeoutError:
                if attempt == 2 \
                        or not self.client._reconnect("pod_epoch"):
                    self.warning(
                        "epoch %d sync unanswered — training on "
                        "(the final update will reconcile)", epoch)
                    return False
                continue
            return bool(reply.get("stop"))
        return False

    # -- scrape surface ------------------------------------------------------
    def metrics_text(self):
        """The pod worker's slice of its scrape endpoint: runtime
        shape and lease progress next to the JobClient's job gauges."""
        runtime = self.runtime
        lines = [
            "# TYPE veles_pod_worker_shards gauge",
            "veles_pod_worker_shards %d"
            % (runtime.shards if runtime is not None else 0),
            "# TYPE veles_pod_worker_generation gauge",
            "veles_pod_worker_generation %d"
            % (runtime.generation if runtime is not None else 0),
            "# HELP veles_pod_worker_lease_epoch epochs completed "
            "locally per lease",
            "# TYPE veles_pod_worker_lease_epoch gauge",
        ]
        for lease_id in sorted(self._progress):
            lines.append(
                'veles_pod_worker_lease_epoch{lease="%s"} %d'
                % (lease_id, self._progress[lease_id]))
        return "\n".join(lines) + "\n"

    def start_scrape(self, host="127.0.0.1", port=0):
        """Mount this pod worker's ``/metrics`` endpoint: the
        JobClient job gauges + the pod runtime shape + the shared
        process-wide base — the worker role's scrape surface.  One
        mount per process: if the client endpoint is already up, the
        delegate warns and the pod gauges are NOT added."""
        return self.client.start_scrape(
            host=host, port=port, extra_sources=(self.metrics_text,),
            role="pod-worker-%s" % self.client.sid)

    # -- lifecycle ----------------------------------------------------------
    def run(self):
        """Handshake and serve leases until ``no_more_jobs``; returns
        the client's verdict (False = gave up / chaos-killed)."""
        self.client.handshake()
        return self.client.run()

    def close(self):
        self.client.close()
