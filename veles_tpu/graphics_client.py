"""GraphicsClient: the detached viewer process.

Parity target: reference ``veles/graphics_client.py:84`` — subscribes to
the server's PUB socket, unpickles plotter units and renders them with
matplotlib.  This image is headless, so the default backend is Agg
rendering into PNG files under ``root.common.dirs.results`` (the
reference's WebAgg browser option maps to the web-status server instead).

Run detached:  ``python -m veles_tpu.graphics_client tcp://127.0.0.1:PORT``
"""

import os
import pickle
import sys
import threading

from veles_tpu.config import root
from veles_tpu.logger import Logger


class GraphicsClient(Logger):
    def __init__(self, endpoint, output_dir=None, pdf=False):
        super(GraphicsClient, self).__init__()
        self.endpoint = endpoint
        self.output_dir = output_dir or root.common.dirs.get("results")
        #: PDF mode (ref graphics doc: SIGUSR2 toggles it at runtime)
        self.pdf_mode = bool(pdf)
        if endpoint.startswith("udp://"):
            # lab-wide multicast viewer (the reference's epgm
            # subscriber role) — stdlib transport, no broker
            from veles_tpu.multicast import McastReceiver
            self._mcast = McastReceiver(endpoint)
            self._socket = None
        else:
            import zmq
            self._mcast = None
            self._context = zmq.Context.instance()
            self._socket = self._context.socket(zmq.SUB)
            self._socket.connect(endpoint)
            self._socket.setsockopt(zmq.SUBSCRIBE, b"")
        self._stop = threading.Event()
        self.rendered = 0

    def toggle_pdf(self, *_signal_args):
        """Flip PNG↔PDF output (the reference's ``killall -SIGUSR2``
        feature, ``manualrst_veles_graphics.rst:36-40``)."""
        self.pdf_mode = not self.pdf_mode
        self.info("plot output switched to %s",
                  "PDF" if self.pdf_mode else "PNG")

    def process_one(self, timeout_ms=1000):
        """Receive + render one plotter; returns True if one arrived."""
        if self._mcast is not None:
            blob = self._mcast.recv_frame(timeout=timeout_ms / 1000.0)
            if blob is None:
                return False
        else:
            if not self._socket.poll(timeout_ms):
                return False
            blob = self._socket.recv()
        try:
            plotter = pickle.loads(blob)
        except Exception:
            self.exception("undecodable plot message")
            return True
        self.render(plotter)
        return True

    def render(self, plotter):
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        fig, axes = plt.subplots(figsize=(6, 4))
        try:
            plotter.redraw(axes)
            os.makedirs(self.output_dir, exist_ok=True)
            ext = "pdf" if self.pdf_mode else "png"
            path = os.path.join(
                self.output_dir,
                "%s.%s" % (plotter.name.replace(" ", "_"), ext))
            fig.savefig(path, dpi=80)
            self.rendered += 1
            self.debug("rendered %s", path)
        except Exception:
            self.exception("failed to render %r", plotter)
        finally:
            plt.close(fig)

    def run(self):
        while not self._stop.is_set():
            self.process_one(200)

    def stop(self):
        self._stop.set()
        if self._mcast is not None:
            self._mcast.close()
        else:
            self._socket.close(linger=0)


def main(argv=None):
    argv = argv or sys.argv[1:]
    if not argv:
        print("usage: python -m veles_tpu.graphics_client "
              "tcp://host:port [output_dir]")
        return 1
    client = GraphicsClient(argv[0],
                            output_dir=argv[1] if len(argv) > 1
                            else None)
    import signal
    # the reference's runtime PDF toggle: killall -SIGUSR2
    signal.signal(signal.SIGUSR2, client.toggle_pdf)
    client.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
