"""The live telemetry bus: a drop-tolerant ZMQ PUB fan-out.

The reference platform's signature operator surface was live plotting
over ZeroMQ pub/sub (PAPER.md §0): training publishes, any number of
viewers attach and detach at will, and a dead viewer never slows the
run.  This module is that contract for the TPU port's telemetry —
JSON snapshot events (health stats, epoch metrics, perf ledger
digests, pod membership, reshard/chaos events, serving gauges)
instead of pickled matplotlib units:

* **PUB semantics** — ZeroMQ PUB never blocks on send: with no
  subscriber the frame is dropped at the socket, and a slow
  subscriber's queue is bounded by ``SNDHWM`` (overflow drops the
  newest frames for that peer).  Publishing is additionally
  ``NOBLOCK`` so even a pathological transport state cannot stall a
  train step or a decode step — the publisher-side guarantee the
  drop-tolerance tests assert with a wall-clock bound.
* **Host-side conflation** — the bus keeps the newest event per kind
  (``latest``) plus a bounded ``history`` ring, so a late-joining
  dashboard, a ``web_status`` push or an ``obs.blackbox`` post-mortem
  can read the current state without having subscribed in time.  The
  optional ``conflate=True`` additionally sets ``ZMQ_CONFLATE`` on
  the socket (keep-only-last wire semantics — collapses *across*
  kinds, so it is off by default).
* **Wire format** — one single-frame UTF-8 JSON object per event:
  ``{"kind", "ts", "seq", "role", ...payload}``.  Single-frame so
  conflating subscribers stay legal; ``seq`` lets a reader count its
  own gaps.

Readers (:class:`TelemetryReader`) are plain SUB sockets;
``python -m veles_tpu.watch <endpoint>`` wraps one in a live terminal
dashboard with ``--record file.ndjson`` persistence.
"""

import collections
import json
import math
import threading
import time

from veles_tpu.logger import Logger


def _json_safe(value):
    """Recursively replace non-finite floats with their repr strings
    ("inf"/"-inf"/"nan"): the wire contract is strict RFC-8259 JSON,
    and a bare ``Infinity`` token (python's ``allow_nan`` extension)
    would break every non-python subscriber and ``jq`` over a
    recorded session."""
    if isinstance(value, float) and not math.isfinite(value):
        return repr(value)
    if isinstance(value, dict):
        return {key: _json_safe(val) for key, val in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(val) for val in value]
    return value


class TelemetryBus(Logger):
    """One PUB endpoint; create via :func:`veles_tpu.watch.start` (or
    the ``root.common.watch.endpoint`` knob at
    ``Workflow.initialize``)."""

    def __init__(self, endpoint="tcp://127.0.0.1:0", hwm=64,
                 history=256, conflate=False, **kwargs):
        super(TelemetryBus, self).__init__(**kwargs)
        import zmq
        self._zmq = zmq
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.PUB)
        # bounded send queue per subscriber + zero linger: a dead or
        # slow peer costs at most `hwm` buffered frames and teardown
        # never waits on undelivered telemetry
        self._socket.setsockopt(zmq.SNDHWM, int(hwm))
        self._socket.setsockopt(zmq.LINGER, 0)
        if conflate:
            self._socket.setsockopt(zmq.CONFLATE, 1)
        # the config knob documents shorthand forms (":0" for a
        # random local port, a bare port number) — normalize them to
        # a full tcp endpoint instead of handing libzmq an empty host
        if "://" not in endpoint:
            endpoint = "tcp://127.0.0.1" + (
                endpoint if endpoint.startswith(":")
                else ":" + endpoint)
        if endpoint.endswith(":0"):
            port = self._socket.bind_to_random_port(
                endpoint.rsplit(":", 1)[0])
            self.endpoint = "%s:%d" % (endpoint.rsplit(":", 1)[0],
                                       port)
        else:
            self._socket.bind(endpoint)
            self.endpoint = endpoint
        self.hwm = int(hwm)
        self.published = 0
        #: NOBLOCK sends the transport refused (EAGAIN) — the frame
        #: was dropped instead of stalling the caller.  Socket-level
        #: HWM drops are invisible by PUB design and not counted here.
        self.dropped = 0
        #: "_"-prefixed control frames sent (reader join probes) —
        #: on the wire but never in latest/history/published, so
        #: blackbox tails and /metrics counters carry telemetry only
        self.control = 0
        self._seq = 0
        #: newest event per kind (host-side conflation)
        self.latest = {}
        #: newest `history` events across kinds (the blackbox tail)
        self.history = collections.deque(maxlen=int(history))
        self._lock = threading.Lock()
        self._closed = False
        self.info("telemetry bus on %s", self.endpoint)

    def publish(self, kind, payload=None):
        """Publish one event; NEVER blocks.  Returns the stamped
        event dict — the JSON-round-tripped copy, so the host-side
        ``latest``/``history`` state is byte-equal to what a
        subscriber received (and an ``obs.blackbox`` post-mortem can
        always re-serialize it).  A payload that cannot serialize at
        all is neither sent nor recorded."""
        from veles_tpu import trace
        event = {"kind": str(kind), "ts": time.time(),
                 "role": trace.recorder.role}
        if payload:
            for key, value in payload.items():
                if key not in event:
                    event[key] = value
        with self._lock:
            if self._closed:
                return event
            self._seq += 1
            event["seq"] = self._seq
            # serialize BEFORE recording: latest/history must only
            # ever hold wire-equal, re-serializable events — and
            # strictly valid JSON (a diverged run's inf/nan stats
            # degrade to repr strings, never to bare Infinity tokens)
            try:
                try:
                    blob = json.dumps(event, default=repr,
                                      allow_nan=False).encode()
                except ValueError:
                    blob = json.dumps(_json_safe(event), default=repr,
                                      allow_nan=False).encode()
            except (TypeError, ValueError):
                self.warning("unserializable %r event dropped", kind)
                return event
            event = json.loads(blob.decode("utf-8"))
            control = event["kind"].startswith("_")
            if not control:
                self.latest[event["kind"]] = event
                self.history.append(event)
            try:
                self._socket.send(blob, self._zmq.NOBLOCK)
                if control:
                    self.control += 1
                else:
                    self.published += 1
            except self._zmq.Again:
                self.dropped += 1
        return event

    def recent(self, limit=64):
        """The newest ``limit`` events, copied under the lock — the
        blackbox tail must never race a mid-publish append (a deque
        mutated during iteration would cost the whole post-mortem)."""
        with self._lock:
            events = list(self.history)
        return events[-int(limit):]

    def latest_events(self, kind=None):
        """Newest event per kind (one kind's, or a copy of all),
        under the lock."""
        with self._lock:
            if kind is not None:
                return self.latest.get(kind)
            return dict(self.latest)

    def describe(self):
        with self._lock:
            return {"endpoint": self.endpoint, "hwm": self.hwm,
                    "published": self.published,
                    "dropped": self.dropped,
                    "kinds": sorted(self.latest)}

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._socket.close(linger=0)


class TelemetryReader(Logger):
    """A SUB-socket consumer (dashboard / tests / recorders)."""

    def __init__(self, endpoint, hwm=1024, conflate=False, **kwargs):
        super(TelemetryReader, self).__init__(**kwargs)
        import zmq
        self._zmq = zmq
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.SUB)
        self._socket.setsockopt(zmq.RCVHWM, int(hwm))
        self._socket.setsockopt(zmq.LINGER, 0)
        if conflate:
            self._socket.setsockopt(zmq.CONFLATE, 1)
        self._socket.setsockopt(zmq.SUBSCRIBE, b"")
        self._socket.connect(endpoint)
        self.endpoint = endpoint
        self.received = 0
        self.decode_errors = 0
        #: events consumed by sync() while probing for the join —
        #: handed back by the next poll() so joining a bus mid-session
        #: never swallows real traffic
        self._pending = collections.deque()

    def poll(self, timeout_ms=100):
        """One event (dict) or ``None`` after ``timeout_ms``."""
        if self._pending:
            return self._pending.popleft()
        if not self._socket.poll(timeout_ms):
            return None
        blob = self._socket.recv()
        try:
            event = json.loads(blob.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self.decode_errors += 1
            return None
        self.received += 1
        return event

    def drain(self, timeout_ms=0):
        """Every event currently queued (each popped with at most
        ``timeout_ms`` of extra waiting)."""
        events = []
        while True:
            event = self.poll(timeout_ms)
            if event is None:
                return events
            events.append(event)

    def sync(self, bus, timeout_s=5.0):
        """Defeat the PUB/SUB slow-joiner race: publish ``_sync``
        markers on ``bus`` until one arrives here (True) or the
        deadline passes (False).  Events published before sync
        returns True may not have been delivered — test/smoke
        publishers call this FIRST."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            bus.publish("_sync", {})
            event = self.poll(100)
            if event is not None:
                if event.get("kind") != "_sync":
                    # real traffic: already joined — hand the probed
                    # event back to the next poll(), never drop it
                    self._pending.append(event)
                return True
        return False

    def close(self):
        self._socket.close(linger=0)


def record_events(events, path):
    """Append events to an ndjson file (the ``--record`` format: one
    JSON object per line)."""
    with open(path, "a") as fout:
        for event in events:
            fout.write(json.dumps(event, default=repr) + "\n")
    return len(events)


def load_events(path):
    """Read a recorded ndjson session back (blank lines skipped)."""
    events = []
    with open(path, "r") as fin:
        for line in fin:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events
