"""veles_tpu.watch — training-health telemetry + the live bus.

PR 12 (:mod:`veles_tpu.obs`) instrumented the *serving* side; this
package closes the *training* side and replaces the reference
platform's live-plotting operator surface (PAPER.md §0):

1. **In-program health telemetry** (:mod:`~veles_tpu.watch.health`) —
   ``root.common.engine.health = off|on|strict`` folds per-param-group
   grad-norm / weight-norm / update-ratio / non-finite counts into the
   stitched segment (and epoch-scan window) programs as a handful of
   device scalars riding the deferred-metrics fetch: zero extra
   dispatches, ``off`` bitwise identical, ``strict`` raising a typed
   :class:`~veles_tpu.watch.health.HealthError` naming the first
   non-finite parameter leaf at the window boundary.
2. **A live telemetry bus** (:mod:`~veles_tpu.watch.bus`) — a
   drop-tolerant ZMQ PUB socket (bounded HWM; a slow or dead
   subscriber can never backpressure a train or decode step) that
   workflows, the Decision's epoch closes, ``PodMaster``/``PodRuntime``
   and the generative scheduler publish periodic JSON snapshots onto;
   ``python -m veles_tpu.watch <endpoint>`` renders a live terminal
   dashboard and ``--record file.ndjson`` persists a session.
3. **A perf-regression watchdog** — ``scripts/bench_diff.py``
   compares a fresh ``bench.py`` run against the banked
   ``BENCH_r0*.json`` envelope per stage and exits non-zero on
   regression, turning the bench ladder into a gate.

Disabled path contract (the PR 5 rule): with no bus configured,
:func:`publish` is one attribute check; with ``health=off`` the
stitched programs are byte-identical to an unwatched build.

See ``docs/observability.md`` § Training health & live watch.
"""

from veles_tpu.watch import bus as _bus_mod, health  # noqa: F401
from veles_tpu.watch.bus import (  # noqa: F401
    TelemetryBus, TelemetryReader, load_events, record_events)
from veles_tpu.watch.health import (  # noqa: F401
    HealthError, HealthMonitor, health_mode, monitor)
from veles_tpu.config import root

#: the process-wide bus (None = disabled; publish() is then a no-op)
_bus = None


def enabled():
    """True when a telemetry bus is live in this process."""
    return _bus is not None


def bus():
    """The live :class:`TelemetryBus`, or ``None``."""
    return _bus


def start(endpoint=None, **kwargs):
    """Start (or return) the process bus.  ``endpoint`` default: the
    ``root.common.watch.endpoint`` knob, else a random local port."""
    global _bus
    if _bus is not None:
        return _bus
    node = root.common.get("watch")
    if endpoint is None:
        endpoint = (node.get("endpoint") if node else None) \
            or "tcp://127.0.0.1:0"
    if node is not None:
        kwargs.setdefault("hwm", int(node.get("hwm", 64) or 64))
        kwargs.setdefault("history",
                          int(node.get("history", 256) or 256))
        kwargs.setdefault("conflate",
                          bool(node.get("conflate", False)))
    _bus = TelemetryBus(endpoint, **kwargs)
    return _bus


def shutdown():
    """Close and forget the process bus (test hygiene)."""
    global _bus
    if _bus is not None:
        _bus.close()
        _bus = None


def configure():
    """Apply the ``root.common.watch.endpoint`` knob (called from
    ``Workflow.initialize`` at the same boundary trace/obs re-read
    theirs): a non-empty endpoint starts the bus once per process;
    empty/unset leaves publishing a no-op."""
    node = root.common.get("watch")
    endpoint = node.get("endpoint") if node else None
    if endpoint and _bus is None:
        start(str(endpoint))
    return _bus


def publish(kind, payload=None, **kwargs):
    """Publish one event onto the process bus; a single attribute
    check when no bus is configured.  Keyword args merge into (and
    override) ``payload``."""
    live = _bus
    if live is None:
        return None
    data = dict(payload or {})
    data.update(kwargs)
    return live.publish(kind, data)


def latest(kind=None):
    """Newest event per kind (host-side conflation), or one kind's —
    copied under the bus lock."""
    live = _bus
    if live is None:
        return None if kind else {}
    return live.latest_events(kind)


def recent_events(limit=64):
    """The newest ``limit`` published events (the blackbox tail),
    copied under the bus lock; ``[]`` with no bus."""
    live = _bus
    if live is None:
        return []
    return live.recent(limit)


def metrics_text():
    """Prometheus exposition for the per-role scrape endpoints
    (:func:`veles_tpu.obs.scrape.default_sources`): the latest cached
    health snapshot as ``veles_health_*`` gauges plus the bus's
    publish/drop counters.  Empty string when neither the health knob
    nor the bus is armed (the source contributes nothing to a scrape
    then — families stay contiguous by construction)."""
    lines = []
    snap = monitor.last_snapshot
    if snap:
        lines.append("# HELP veles_health_stat latest in-program "
                     "training-health stats by param group")
        lines.append("# TYPE veles_health_stat gauge")
        for group in sorted(snap.get("groups", {})):
            entry = snap["groups"][group]
            for stat in sorted(entry):
                if stat == "leaves":
                    continue
                lines.append(
                    'veles_health_stat{group="%s",stat="%s"} %g'
                    % (group, stat, entry[stat]))
        lines.append("# HELP veles_health_nonfinite non-finite "
                     "elements per donated param leaf (latest)")
        lines.append("# TYPE veles_health_nonfinite gauge")
        for group in sorted(snap.get("groups", {})):
            for leaf in sorted(snap["groups"][group]["leaves"]):
                lines.append(
                    'veles_health_nonfinite{group="%s",leaf="%s"} %d'
                    % (group, leaf,
                       snap["groups"][group]["leaves"][leaf]))
        lines.append("# TYPE veles_health_step gauge")
        lines.append("veles_health_step %d" % snap.get("step", 0))
    live = _bus
    if live is not None:
        info = live.describe()
        lines.append("# TYPE veles_watch_published_total counter")
        lines.append("veles_watch_published_total %d"
                     % info["published"])
        lines.append("# TYPE veles_watch_dropped_total counter")
        lines.append("veles_watch_dropped_total %d" % info["dropped"])
    return "\n".join(lines) + "\n" if lines else ""


def last_health():
    """The latest host-side health snapshot (cached by
    ``HealthMonitor.snapshot`` — populated whenever the health knob is
    armed, bus or no bus), or ``None``."""
    return monitor.last_snapshot
