"""``python -m veles_tpu.watch`` — the live terminal dashboard.

Usage::

    python -m veles_tpu.watch tcp://127.0.0.1:9461          # live
    python -m veles_tpu.watch tcp://... --record run.ndjson  # + persist
    python -m veles_tpu.watch --replay run.ndjson            # offline
    python -m veles_tpu.watch --smoke                        # CI gate

Live mode subscribes to a telemetry bus (:mod:`veles_tpu.watch.bus`)
and renders a newest-event-per-kind table, with the health block
expanded per param group.  ``--record`` appends every received event
to an ndjson file (one JSON object per line) that ``--replay`` renders
back — the record/replay roundtrip the tests gate on.  ``--once``
prints raw events instead of redrawing (pipe-friendly).

The ``--smoke`` gate (wired into ``scripts/lint.sh``): one traced
stitched training session under ``engine.health=on`` must publish ≥ 4
distinct event kinds consumed by a LIVE subscriber; an injected NaN
under ``health=strict`` must raise :class:`~veles_tpu.watch.health
.HealthError` naming the poisoned layer's param group; and a
record/replay roundtrip must reproduce the session byte-for-byte.
"""

import argparse
import json
import sys
import time


def _fmt_age(age_s):
    if age_s < 10:
        return "%4.1fs" % age_s
    if age_s < 600:
        return "%4.0fs" % age_s
    return "%3.0fm" % (age_s / 60.0)


def _fmt_event(event):
    """One-line digest of an event's interesting fields."""
    kind = event.get("kind")
    skip = {"kind", "ts", "seq", "role"}
    if kind == "health":
        parts = []
        for name, group in sorted((event.get("groups") or {}).items()):
            parts.append(
                "%s g=%.3g w=%.3g r=%.3g nf=%d" % (
                    name, group.get("grad_norm", float("nan")),
                    group.get("weight_norm", float("nan")),
                    group.get("update_ratio", float("nan")),
                    int(group.get("nonfinite", 0))))
        return "step %s | %s" % (event.get("step"),
                                 " | ".join(parts) or "no groups")
    pairs = []
    for key in sorted(event):
        if key in skip:
            continue
        value = event[key]
        if isinstance(value, float):
            value = "%.4g" % value
        elif isinstance(value, (dict, list)):
            value = json.dumps(value, default=repr)
            if len(value) > 40:
                value = value[:37] + "..."
        pairs.append("%s=%s" % (key, value))
    return " ".join(pairs)


def render(latest, received=0, dropped=None, now=None):
    """The dashboard frame: newest event per kind, padded table."""
    now = now if now is not None else time.time()
    lines = ["veles_tpu.watch — %d event(s) received%s" % (
        received,
        "" if dropped is None else ", %d dropped" % dropped)]
    lines.append("%-10s %-6s %-6s %s" % ("KIND", "AGE", "ROLE",
                                         "LATEST"))
    for kind in sorted(latest):
        if kind.startswith("_"):
            continue
        event = latest[kind]
        lines.append("%-10s %-6s %-6s %s" % (
            kind, _fmt_age(max(0.0, now - float(event.get("ts", now)))),
            str(event.get("role", "?"))[:6], _fmt_event(event)))
    return "\n".join(lines)


def consume(reader, duration=None, record=None, once=False,
            interval=0.5, out=None):
    """The live loop: poll → accumulate latest-per-kind → redraw (or
    print raw with ``once``) → optionally append to the record file.
    Returns ``(latest, received)``.  Ctrl-C exits cleanly."""
    out = out or sys.stdout
    latest = {}
    received = 0
    deadline = (time.monotonic() + duration) if duration else None
    last_draw = 0.0
    # one append handle for the whole session (not one open/close per
    # event); flushed per event so a killed dashboard loses nothing
    fout = open(record, "a") if record else None
    try:
        while deadline is None or time.monotonic() < deadline:
            event = reader.poll(200)
            if event is not None:
                received += 1
                latest[event.get("kind", "?")] = event
                if fout is not None:
                    fout.write(json.dumps(event, default=repr) + "\n")
                    fout.flush()
                if once:
                    print(json.dumps(event, default=repr), file=out)
            if not once and time.monotonic() - last_draw >= interval:
                last_draw = time.monotonic()
                # ANSI home+clear keeps the table in place on a tty;
                # harmless noise when redirected
                if out.isatty():
                    out.write("\x1b[H\x1b[2J")
                out.write(render(latest, received) + "\n")
                out.flush()
    except KeyboardInterrupt:
        pass
    finally:
        if fout is not None:
            fout.close()
    return latest, received


def replay(path, out=None):
    """Render a recorded ndjson session offline: final dashboard
    frame + per-kind counts."""
    from veles_tpu.watch.bus import load_events
    out = out or sys.stdout
    events = load_events(path)
    latest = {}
    counts = {}
    for event in events:
        kind = event.get("kind", "?")
        latest[kind] = event
        counts[kind] = counts.get(kind, 0) + 1
    now = max((float(e.get("ts", 0)) for e in events), default=None)
    print(render(latest, received=len(events), now=now), file=out)
    print("kinds: %s" % ", ".join(
        "%s×%d" % (k, counts[k]) for k in sorted(counts)), file=out)
    return events


# -- CI smoke (scripts/lint.sh) ---------------------------------------------

def run_smoke(module_name="veles_tpu.samples.mnist"):
    """The lint.sh watch gate — see the module docstring."""
    import importlib
    import os
    import tempfile

    import numpy

    from veles_tpu import watch
    from veles_tpu.config import root
    from veles_tpu.watch.bus import TelemetryReader, load_events, \
        record_events
    from veles_tpu.watch.health import HealthError

    saved = {k: root.common.engine.get(k, d) for k, d in (
        ("trace", "off"), ("stitch", "on"), ("epoch_scan", "off"),
        ("health", "off"))}
    root.common.engine.trace = "on"
    root.common.engine.stitch = "on"
    root.common.engine.epoch_scan = "auto"
    root.common.engine.health = "on"
    reader = None
    try:
        bus = watch.start("tcp://127.0.0.1:0")
        reader = TelemetryReader(bus.endpoint)
        if not reader.sync(bus):
            print("watch smoke: FAIL — subscriber never joined the "
                  "bus", file=sys.stderr)
            return 1
        # -- gate 1: one traced training session, >=4 event kinds
        # consumed by the LIVE subscriber ---------------------------
        sample = importlib.import_module(module_name)
        wf = sample.create_workflow(max_epochs=2, minibatch_size=500)
        wf.run()
        events = reader.drain(timeout_ms=200)
        kinds = {e["kind"] for e in events if not
                 e["kind"].startswith("_")}
        if len(kinds) < 4:
            print("watch smoke: FAIL — %d event kind(s) on the live "
                  "bus (%s), need >= 4" % (len(kinds), sorted(kinds)),
                  file=sys.stderr)
            return 1
        health_events = [e for e in events if e["kind"] == "health"]
        if not health_events or not health_events[-1].get("groups"):
            print("watch smoke: FAIL — no health snapshot with param "
                  "groups on the bus", file=sys.stderr)
            return 1
        for name, group in health_events[-1]["groups"].items():
            if not numpy.isfinite(group.get("weight_norm", 0.0)) \
                    or group.get("nonfinite", 1) != 0:
                print("watch smoke: FAIL — unhealthy stats for %s: %r"
                      % (name, group), file=sys.stderr)
                return 1
        # -- gate 2: injected NaN caught by strict mode -------------
        root.common.engine.health = "strict"
        wf2 = sample.create_workflow(max_epochs=2,
                                     minibatch_size=500)
        weights = wf2.forwards[0].weights
        weights.map_write()
        weights.mem[0, 0] = numpy.nan
        try:
            wf2.run()
        except HealthError as exc:
            if not exc.leaf or exc.count < 1:
                print("watch smoke: FAIL — HealthError without a "
                      "named leaf: %s" % exc, file=sys.stderr)
                return 1
        else:
            print("watch smoke: FAIL — injected NaN not caught by "
                  "health=strict", file=sys.stderr)
            return 1
        # -- gate 3: record/replay roundtrip ------------------------
        fd, path = tempfile.mkstemp(suffix=".ndjson")
        os.close(fd)
        try:
            record_events(events, path)
            back = load_events(path)
            if back != events:
                print("watch smoke: FAIL — record/replay roundtrip "
                      "drifted (%d vs %d events)"
                      % (len(back), len(events)), file=sys.stderr)
                return 1
        finally:
            os.unlink(path)
        print("watch smoke: OK — %d event(s), kinds %s; strict NaN "
              "caught; record/replay roundtrip exact; bus %r"
              % (len(events), sorted(kinds), bus.describe()))
        return 0
    finally:
        if reader is not None:
            reader.close()
        watch.shutdown()
        for key, value in saved.items():
            setattr(root.common.engine, key, value)
        from veles_tpu import trace
        trace.configure()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.watch",
        description="live telemetry dashboard over the watch bus")
    parser.add_argument("endpoint", nargs="?",
                        help="bus endpoint, e.g. tcp://127.0.0.1:9461")
    parser.add_argument("--record", metavar="FILE",
                        help="append received events to an ndjson file")
    parser.add_argument("--replay", metavar="FILE",
                        help="render a recorded ndjson session")
    parser.add_argument("--once", action="store_true",
                        help="print raw events (no dashboard redraw)")
    parser.add_argument("--duration", type=float, default=None,
                        help="exit after N seconds (default: run "
                             "until Ctrl-C)")
    parser.add_argument("--interval", type=float, default=0.5,
                        help="dashboard redraw interval (seconds)")
    parser.add_argument("--smoke", metavar="MODULE", nargs="?",
                        const="veles_tpu.samples.mnist", default=None,
                        help="run the CI gate (lint.sh)")
    ns = parser.parse_args(argv)
    if ns.smoke:
        return run_smoke(ns.smoke)
    if ns.replay:
        replay(ns.replay)
        return 0
    if not ns.endpoint:
        parser.print_help()
        return 2
    from veles_tpu.watch.bus import TelemetryReader
    reader = TelemetryReader(ns.endpoint)
    try:
        latest, received = consume(
            reader, duration=ns.duration, record=ns.record,
            once=ns.once, interval=ns.interval)
    finally:
        reader.close()
    if not ns.once:
        print(render(latest, received))
    return 0


if __name__ == "__main__":  # pragma: no cover - thin CLI
    sys.exit(main())
