"""In-program training-health telemetry.

PRs 10–12 made training *opaque by design*: a whole epoch is one
``lax.scan`` dispatch, so a NaN'd gradient or an exploding update
ratio produces no host-visible signal until the epoch closes — or
ever.  This module folds compact numerics stats INTO the stitched
segments' programs (:mod:`veles_tpu.stitch`) so they ride the existing
deferred-metrics machinery as a handful of async device scalars —
**zero extra dispatches, zero per-step host syncs**:

* per **param group** (= per stitched stage that donates float
  parameter/momentum buffers — each GD unit is one group):
  ``grad_norm`` (the effective gradient incl. weight decay, recovered
  in-program from the momentum update — declared by the stage's
  ``health`` callable; the GD family provides it), ``weight_norm``,
  ``update_norm``, ``update_ratio`` (‖update‖/‖weights‖) and a
  **non-finite element count per donated leaf**;
* the stats are extra *outputs of the already-dispatched program* —
  published through the same ``StitchStage.metrics`` protocol the
  Decision's deferred metrics use, so they are fetched in the same
  batched ``device_get_all`` cadence and never add a dispatch;
* under :class:`~veles_tpu.pod.runtime.PodRuntime` the window/segment
  programs pin the stats' out-shardings replicated, so GSPMD inserts
  the cross-shard reduction in-program — every shard reports the SAME
  value (the psum'd-health agreement the pod tests assert).

Knob: ``root.common.engine.health = off | on | strict`` — read at
``Workflow.rebuild_stitching()`` time (the same boundary as the
``stitch`` knob).  ``off`` (default) leaves every program **bitwise
byte-identical** to the uninstrumented build; ``on`` collects; and
``strict`` additionally fetches the per-leaf non-finite counts at
window boundaries (every epoch-scan window; every
``metrics_every``-or-:data:`STRICT_CHECK_EVERY` steps on the per-step
path; every class close) and raises a typed :class:`HealthError`
naming the **first non-finite parameter leaf** (stage order, then
leaf name).  NaNs persist through momentum updates, so checking the
latest values is sufficient — no per-step history is kept on device.

The process-wide :data:`monitor` holds the latest device scalars and
serves :meth:`HealthMonitor.snapshot` to the telemetry bus
(:mod:`veles_tpu.watch.bus`), ``web_status`` pushes and the
``obs.blackbox`` flight recorder.
"""

import numpy

from veles_tpu.config import root
from veles_tpu.logger import Logger

#: strict-mode check cadence on the per-step path when
#: ``root.common.engine.metrics_every`` is unset: one batched fetch of
#: the non-finite leaf counts every this-many observed train steps
STRICT_CHECK_EVERY = 32

#: the declared-stat names a stage ``health`` callable may return
#: (``update_ratio`` is derived in the wrapper, never declared)
DECLARED_STATS = ("grad_norm", "weight_norm", "update_norm")


def health_mode():
    """The ``root.common.engine.health`` knob: ``off`` | ``on`` |
    ``strict`` (read at ``rebuild_stitching`` time, like ``stitch``)."""
    value = root.common.engine.get("health", "off")
    if value is None:
        return "off"
    value = str(value).strip().lower()
    if value in ("off", "0", "false", "no", ""):
        return "off"
    if value in ("on", "1", "true", "yes"):
        return "on"
    if value == "strict":
        return "strict"
    raise ValueError(
        "root.common.engine.health must be off|on|strict, got %r"
        % value)


class HealthError(RuntimeError):
    """Strict-mode verdict: a parameter leaf went non-finite.

    ``leaf`` names the first bad leaf (``<unit>.<slot>``, stage order
    then slot name), ``count`` its non-finite element count, ``step``
    the observed train step at the failing boundary."""

    def __init__(self, leaf, count, step):
        self.leaf = leaf
        self.count = int(count)
        self.step = int(step)
        super(HealthError, self).__init__(
            "non-finite parameter leaf %r (%d element(s)) at train "
            "step %d — the first bad param group; inspect "
            "watch.health.monitor.snapshot() / lower the learning "
            "rate (root.common.engine.health=strict)"
            % (leaf, self.count, self.step))


class HealthGroup(object):
    """One instrumented param group (one donating stitched stage):
    the unit the stats land on and the metric-attribute names to read
    them back from (the stitched dispatch assigns them via the
    standard ``StitchStage.metrics`` → ``setattr`` protocol)."""

    __slots__ = ("unit", "name", "stats", "leaves")

    def __init__(self, unit, stats, leaves):
        self.unit = unit
        self.name = unit.name
        #: aggregate stats: {stat_name: metric_attr}
        self.stats = dict(stats)
        #: per-leaf non-finite counts: [(leaf_label, metric_attr)]
        self.leaves = list(leaves)


def _float_leaves(stage):
    """The donated slots health instruments: float-dtype Vectors
    (params/momentum), sorted by slot name.  Integer donations (the
    evaluator's confusion matrix) are not param groups."""
    out = []
    for name in sorted(stage.donated):
        vec = stage.donated[name]
        dtype = getattr(vec, "dtype", None)
        if dtype is None:
            mem = getattr(vec, "mem", None)
            dtype = getattr(mem, "dtype", None)
        if dtype is not None and numpy.issubdtype(dtype,
                                                  numpy.floating):
            out.append(name)
    return out


def _wrap_stage_fn(fn, declared, leaves):
    """The instrumented stage body: run the original ``fn``, then fold
    the health stats over its donated outputs — pure traced jax math,
    so the stats compile into the SAME program (and the same
    ``lax.scan`` body under epoch mode)."""
    def instrumented(t):
        import jax.numpy as jnp
        out = fn(t)
        stats = {}
        total = None
        for leaf in leaves:
            arr = out[leaf].astype(jnp.float32)
            count = jnp.sum(jnp.logical_not(jnp.isfinite(arr)),
                            dtype=jnp.int32)
            stats["health_nf_" + leaf] = count
            total = count if total is None else total + count
        stats["health_nonfinite"] = total
        if declared is not None:
            extra = declared(t, out)
        else:
            # generic fallback for donating stages without a declared
            # health callable: norms over (new, new-old) donated pairs
            wsq = sum(jnp.sum(jnp.square(out[leaf].astype(
                jnp.float32))) for leaf in leaves)
            usq = sum(jnp.sum(jnp.square(
                out[leaf].astype(jnp.float32)
                - t[leaf].astype(jnp.float32))) for leaf in leaves)
            extra = {"weight_norm": jnp.sqrt(wsq),
                     "update_norm": jnp.sqrt(usq)}
        for key in DECLARED_STATS:
            if key in extra:
                stats["health_" + key] = extra[key].astype(jnp.float32)
        if "health_update_norm" in stats \
                and "health_weight_norm" in stats:
            stats["health_update_ratio"] = \
                stats["health_update_norm"] \
                / (stats["health_weight_norm"] + jnp.float32(1e-12))
        out.update(stats)
        return out
    return instrumented


def instrument_stages(stages):
    """Fold health stats into every donating stage of one stitched
    chain (called by :func:`veles_tpu.stitch.build_segments` before
    the segment compiles, so the stats are part of the program from
    its first trace).  Returns the list of :class:`HealthGroup`\\ s
    created; mutates each instrumented stage in place (``fn`` wrapped,
    ``metrics`` extended, ``health_spec`` attached).  Epoch-scan
    window plans reuse the same stage objects, so windows inherit the
    instrumentation with no extra work."""
    groups = []
    for stage in stages:
        if getattr(stage, "health_spec", None) is not None:
            # already instrumented (a failed segment construction left
            # the wrapped stage in build_segments' cache and another
            # chain picked it up) — re-wrapping would compute every
            # stat twice; reuse the existing group
            groups.append(stage.health_spec)
            continue
        leaves = _float_leaves(stage)
        if not leaves:
            continue
        declared = getattr(stage, "health", None)
        stage.fn = _wrap_stage_fn(stage.fn, declared, leaves)
        names = ["health_nf_" + leaf for leaf in leaves]
        names.append("health_nonfinite")
        stat_names = list(DECLARED_STATS) if declared is not None \
            else ["weight_norm", "update_norm"]
        names.extend("health_" + s for s in stat_names)
        names.append("health_update_ratio")
        stage.metrics = tuple(stage.metrics) + tuple(names)
        group = HealthGroup(
            stage.unit,
            stats=dict(
                [(s, "health_" + s) for s in stat_names]
                + [("update_ratio", "health_update_ratio"),
                   ("nonfinite", "health_nonfinite")]),
            leaves=[(leaf, "health_nf_" + leaf) for leaf in leaves])
        stage.health_spec = group
        groups.append(group)
    return groups


class HealthMonitor(Logger):
    """The process-wide collector: latest per-group device scalars
    (async — reading them costs nothing until a snapshot/check
    fetches), the strict-mode cadence, and the host-side snapshot the
    bus / web_status / blackbox consume.

    (Re)armed by ``rebuild_stitching`` through :meth:`install`; one
    training workflow per process owns it, like the trace recorder
    and the perf ledger."""

    def __init__(self, **kwargs):
        super(HealthMonitor, self).__init__(**kwargs)
        self.reset()

    def reset(self):
        self.groups = []
        self.mode = "off"
        #: observed train steps (GD-stage dispatches × their K)
        self.steps = 0
        #: strict-mode batched fetches performed
        self.checks = 0
        self._since_check = 0
        #: the last HOST-side snapshot dict (what blackbox embeds)
        self.last_snapshot = None

    @property
    def armed(self):
        return bool(self.groups) and self.mode != "off"

    def install(self, groups, mode):
        """Arm for one freshly stitched workflow (its full group
        list); resets the counters — a rebuild is a new run."""
        self.reset()
        self.groups = list(groups)
        self.mode = mode

    def describe(self):
        return {"mode": self.mode, "groups": [g.name
                                              for g in self.groups],
                "steps": self.steps, "checks": self.checks}

    def _check_every(self):
        every = int(root.common.engine.get("metrics_every", 0) or 0)
        return every if every > 0 else STRICT_CHECK_EVERY

    def observe(self, steps=1, window=False):
        """One instrumented dispatch landed ``steps`` train steps'
        stats (K for an epoch-scan window).  Free unless strict mode
        is due for a boundary check (every window; every
        ``_check_every()`` steps on the per-step path)."""
        self.steps += int(steps)
        self._since_check += int(steps)
        if self.mode != "strict":
            return
        if window or self._since_check >= self._check_every():
            self.check()

    def check(self):
        """The strict boundary: ONE batched fetch of every group's
        per-leaf non-finite counts (latest values — NaNs persist in
        donated params, so latest is sufficient); raises
        :class:`HealthError` naming the first bad leaf."""
        self._since_check = 0
        self.checks += 1
        from veles_tpu import trace
        from veles_tpu.memory import device_get_all
        trace.instant("watch", "health_check", {"step": self.steps})
        slots = [(group, leaf, attr)
                 for group in self.groups
                 for leaf, attr in group.leaves]
        values = device_get_all(
            [getattr(group.unit, attr, 0)
             for group, _leaf, attr in slots])
        for (group, leaf, _attr), value in zip(slots, values):
            if int(value) > 0:
                raise HealthError("%s.%s" % (group.name, leaf),
                                  int(value), self.steps)

    def maybe_snapshot(self):
        """:meth:`snapshot` when armed, else ``None`` — the
        unconditional call sites (Decision class close) use this so
        ``health=off`` costs two attribute checks."""
        if not self.armed:
            return None
        return self.snapshot()

    def snapshot(self):
        """Fetch every group's full stat set in ONE batched
        ``device_get_all`` and return (and cache) the JSON-able
        snapshot.  Strict mode also applies the non-finite verdict
        here, so a class close never passes silently over a bad
        leaf."""
        from veles_tpu import trace
        from veles_tpu.memory import device_get_all
        trace.instant("watch", "health_snapshot",
                      {"step": self.steps})
        slots = []
        for group in self.groups:
            for stat, attr in sorted(group.stats.items()):
                slots.append((group, "stat", stat, attr))
            for leaf, attr in group.leaves:
                slots.append((group, "leaf", leaf, attr))
        values = device_get_all(
            [getattr(group.unit, attr, 0)
             for group, _kind, _name, attr in slots])
        groups = {}
        first_bad = None
        for (group, kind, name, _attr), value in zip(slots, values):
            entry = groups.setdefault(
                group.name, {"leaves": {}})
            if kind == "stat":
                entry[name] = int(value) if name == "nonfinite" \
                    else float(value)
            else:
                count = int(value)
                entry["leaves"][name] = count
                if count > 0 and first_bad is None:
                    first_bad = ("%s.%s" % (group.name, name), count)
        snap = {"mode": self.mode, "step": self.steps,
                "groups": groups}
        self.last_snapshot = snap
        if self.mode == "strict" and first_bad is not None:
            raise HealthError(first_bad[0], first_bad[1], self.steps)
        return snap


#: the process-wide monitor every instrumented dispatch reports to
monitor = HealthMonitor()
