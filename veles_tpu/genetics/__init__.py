"""Genetic hyperparameter optimization (SURVEY §2.6).

Reference: ``veles/genetics/`` — GA core (``core.py:371-430``),
config-space markers (``config.py:45,110``), optimizer workflow
(``optimization_workflow.py:70,298``).
"""

from veles_tpu.genetics.core import (        # noqa: F401
    Chromosome, GeneSpec, Population)
from veles_tpu.genetics.optimizer import (   # noqa: F401
    GeneticsOptimizer, fitness_from_results)
from veles_tpu.genetics.tune import (        # noqa: F401
    Choice, Range, Tuneable, apply_values, decode_genome,
    default_genome, scan_tuneables, specs_of)
