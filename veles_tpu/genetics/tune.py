"""Tuneable config markers: embed GA search ranges in the config tree.

Parity target: reference ``veles/genetics/config.py`` — ``Tuneable``
(``:45``) / ``Range`` (``:110``) wrappers placed directly on config
values; the optimizer scans the tree for them and substitutes concrete
values per chromosome.
"""

from veles_tpu.config import Config
from veles_tpu.genetics.core import GeneSpec


class Tuneable(object):
    """Base marker: a config value the GA may vary."""

    def spec(self):
        raise NotImplementedError

    def decode(self, gene):
        """gene (float) → concrete config value."""
        raise NotImplementedError


class Range(Tuneable):
    """Continuous (or integer) range [min, max] with a default."""

    def __init__(self, default, minimum, maximum):
        self.default = default
        self.min = minimum
        self.max = maximum
        self.is_int = all(
            isinstance(v, int) and not isinstance(v, bool)
            for v in (default, minimum, maximum))

    def spec(self):
        return GeneSpec(self.min, self.max, is_int=self.is_int)

    def decode(self, gene):
        return int(round(gene)) if self.is_int else float(gene)

    def __repr__(self):
        return "Range(%r, %r, %r)" % (self.default, self.min, self.max)


class Choice(Tuneable):
    """Categorical choice encoded as an integer gene index."""

    def __init__(self, default, *options):
        if default not in options:
            options = (default,) + options
        self.options = list(options)
        self.default = default

    def spec(self):
        return GeneSpec(0, len(self.options) - 1, is_int=True)

    def decode(self, gene):
        return self.options[int(round(gene))]

    def __repr__(self):
        return "Choice(%r, *%r)" % (self.default, self.options)


def scan_tuneables(config):
    """Walks a :class:`veles_tpu.config.Config` tree (or plain dict) and
    returns sorted [(dotted_path, Tuneable)] for every marker found."""
    found = []

    def walk(node, path):
        if isinstance(node, Config):
            items = list(node)   # Config.__iter__ yields (key, value)
        elif isinstance(node, dict):
            items = list(node.items())
        else:
            return
        for key, value in items:
            sub = "%s.%s" % (path, key) if path else str(key)
            if isinstance(value, Tuneable):
                found.append((sub, value))
            else:
                walk(value, sub)

    walk(config, "")
    found.sort(key=lambda pair: pair[0])
    return found


def specs_of(tuneables):
    return [t.spec() for _, t in tuneables]


def decode_genome(tuneables, genes):
    """genes → {dotted_path: concrete value}."""
    return {path: t.decode(g)
            for (path, t), g in zip(tuneables, genes)}


def default_genome(tuneables):
    """The genes encoding every Tuneable's default value."""
    genes = []
    for _, t in tuneables:
        if isinstance(t, Choice):
            genes.append(float(t.options.index(t.default)))
        else:
            genes.append(float(t.default))
    return genes


def apply_values(config, values):
    """Writes {dotted_path: value} into a Config tree."""
    for path, value in values.items():
        node = config
        parts = path.split(".")
        for part in parts[:-1]:
            node = getattr(node, part)
        setattr(node, parts[-1], value)
