"""Genetic-algorithm core: chromosomes, selection, crossover, mutation.

Parity target: reference ``veles/genetics/core.py`` — ``Population``
(``:371-430``) with roulette/tournament selection and four crossover
pipelines + mutation.  Genes are floats (optionally integer-rounded)
inside per-gene [min, max] bounds; fitness is maximized.

All randomness rides the named PRNG stream ``"genetics"``
(:mod:`veles_tpu.prng`) so GA runs are reproducible and snapshottable.
"""

import numpy

from veles_tpu import prng
from veles_tpu.logger import Logger


class Chromosome(object):
    """One candidate: genes (numpy float vector) + fitness (None until
    evaluated; larger is better)."""

    def __init__(self, genes):
        self.genes = numpy.asarray(genes, numpy.float64)
        self.fitness = None

    def copy(self):
        c = Chromosome(self.genes.copy())
        c.fitness = self.fitness
        return c

    def __repr__(self):
        return "<Chromosome %s fitness=%s>" % (
            numpy.array2string(self.genes, precision=4), self.fitness)


class GeneSpec(object):
    """Bounds + integrality of one gene."""

    def __init__(self, minimum, maximum, is_int=False):
        if maximum < minimum:
            raise ValueError("gene bounds inverted: [%s, %s]"
                             % (minimum, maximum))
        self.min = float(minimum)
        self.max = float(maximum)
        self.is_int = is_int

    def clip(self, value):
        v = min(max(float(value), self.min), self.max)
        return float(round(v)) if self.is_int else v

    def sample(self, rng):
        return self.clip(self.min + (self.max - self.min) * rng.numpy.random())


class Population(Logger):
    """Fixed-size population evolved by select → crossover → mutate,
    with elitism (the best chromosome always survives).

    ``specs``: list of :class:`GeneSpec`.
    """

    def __init__(self, specs, size=20, crossover="uniform",
                 selection="roulette", mutation_rate=0.1,
                 mutation_sigma=0.15, tournament_k=3, elite=1):
        super(Population, self).__init__()
        if size < 2:
            raise ValueError("population size must be >= 2")
        self.specs = list(specs)
        self.size = size
        self.crossover_kind = crossover
        self.selection_kind = selection
        self.mutation_rate = mutation_rate
        self.mutation_sigma = mutation_sigma
        self.tournament_k = tournament_k
        self.elite = elite
        self.generation = 0
        self.chromosomes = [
            Chromosome([spec.sample(self.rng) for spec in self.specs])
            for _ in range(size)]

    @property
    def rng(self):
        return prng.get("genetics")

    @property
    def best(self):
        scored = [c for c in self.chromosomes if c.fitness is not None]
        return max(scored, key=lambda c: c.fitness) if scored else None

    @property
    def pending(self):
        """Chromosomes awaiting evaluation."""
        return [c for c in self.chromosomes if c.fitness is None]

    # -- selection ----------------------------------------------------------
    def _select(self):
        if self.selection_kind == "tournament":
            contenders = [
                self.chromosomes[int(self.rng.randint(
                    0, len(self.chromosomes)))]
                for _ in range(self.tournament_k)]
            return max(contenders, key=lambda c: c.fitness)
        # roulette on fitness shifted to positive; non-finite fitnesses
        # (failed evaluations report -inf) are floored to the worst
        # finite value so they get zero weight instead of NaN-poisoning
        # the whole distribution
        fits = numpy.array([c.fitness for c in self.chromosomes],
                           numpy.float64)
        finite = fits[numpy.isfinite(fits)]
        if finite.size == 0:
            return self.chromosomes[
                int(self.rng.randint(0, len(self.chromosomes)))]
        fits = numpy.nan_to_num(fits, nan=finite.min(),
                                posinf=finite.max(), neginf=finite.min())
        shifted = fits - fits.min()
        total = shifted.sum()
        if total <= 0:
            return self.chromosomes[
                int(self.rng.randint(0, len(self.chromosomes)))]
        probs = shifted / total
        pick = self.rng.numpy.random()
        acc = 0.0
        for c, p in zip(self.chromosomes, probs):
            acc += p
            if pick <= acc:
                return c
        return self.chromosomes[-1]

    # -- crossover ----------------------------------------------------------
    def _crossover(self, a, b):
        n = len(self.specs)
        kind = self.crossover_kind
        if kind == "uniform":
            mask = numpy.array([self.rng.numpy.random() < 0.5
                                for _ in range(n)])
            genes = numpy.where(mask, a.genes, b.genes)
        elif kind == "one_point":
            point = int(self.rng.randint(1, max(n, 2)))
            genes = numpy.concatenate([a.genes[:point], b.genes[point:]])
        elif kind == "two_point":
            p1 = int(self.rng.randint(1, max(n, 2)))
            p2 = int(self.rng.randint(1, max(n, 2)))
            p1, p2 = min(p1, p2), max(p1, p2)
            genes = a.genes.copy()
            genes[p1:p2] = b.genes[p1:p2]
        elif kind == "arithmetic":
            w = self.rng.numpy.random()
            genes = w * a.genes + (1.0 - w) * b.genes
        else:
            raise ValueError("unknown crossover %r" % kind)
        return Chromosome([spec.clip(g)
                           for spec, g in zip(self.specs, genes)])

    # -- mutation -----------------------------------------------------------
    def _mutate(self, chromo):
        for i, spec in enumerate(self.specs):
            if self.rng.numpy.random() < self.mutation_rate:
                span = spec.max - spec.min
                chromo.genes[i] = spec.clip(
                    chromo.genes[i] +
                    self.rng.numpy.normal(0.0, self.mutation_sigma * span))
        return chromo

    # -- evolution ----------------------------------------------------------
    def evolve(self):
        """One generation step; every chromosome must be evaluated."""
        if self.pending:
            raise RuntimeError("%d chromosomes not evaluated yet"
                               % len(self.pending))
        ranked = sorted(self.chromosomes, key=lambda c: -c.fitness)
        survivors = [c.copy() for c in ranked[:self.elite]]
        while len(survivors) < self.size:
            child = self._mutate(self._crossover(self._select(),
                                                 self._select()))
            child.fitness = None
            survivors.append(child)
        self.chromosomes = survivors
        self.generation += 1
        self.debug("generation %d: best=%s", self.generation,
                   ranked[0].fitness)
        return ranked[0]
