"""GeneticsOptimizer: hyperparameter search over config Tuneables.

Parity target: reference ``veles/genetics/optimization_workflow.py`` —
``GeneticsOptimizer`` (``:70``) / ``OptimizationWorkflow`` (``:298``):
``--optimize SIZE[:GENERATIONS]`` evolves a population of config
chromosomes, each evaluated by (a) an in-process callable, (b) a child
``veles_tpu`` process reading back ``--result-file`` JSON
(ref ``_exec`` ``:268``), or (c) slave jobs through the cross-slice job
layer (``generate_data_for_slave`` ``:186``) — the TPU build's
task-parallel mode where each job is a whole training run on a slice.
"""

import json
import os
import subprocess
import sys
import tempfile

from veles_tpu.config import root
from veles_tpu.genetics import tune
from veles_tpu.genetics.core import Population
from veles_tpu.logger import Logger


def fitness_from_results(results, fitness_key=None):
    """Extracts a maximizable fitness from a result-file dict.

    Priority: explicit key → ``fitness`` → negated first ``*err*``
    metric → first numeric value.
    """
    def numeric(v):
        try:
            f = float(v)
        except (TypeError, ValueError):
            return None
        return f

    if fitness_key is not None:
        value = numeric(results.get(fitness_key))
        if value is None:
            raise ValueError("result file lacks numeric %r" % fitness_key)
        return value
    if "fitness" in results:
        value = numeric(results["fitness"])
        if value is not None:
            return value
    for key in sorted(results):
        if "err" in key or "loss" in key:
            value = numeric(results[key])
            if value is not None:
                return -value
    for key in sorted(results):
        value = numeric(results[key])
        if value is not None:
            return value
    raise ValueError("no numeric metric in results %r" % (results,))


class GeneticsOptimizer(Logger):
    """Evolves config Tuneables to maximize a fitness.

    Modes (pick one):
      * ``evaluate=callable(overrides_dict) -> fitness`` — in-process.
      * ``workflow_spec=path`` — child ``python -m veles_tpu`` per
        chromosome, fitness from ``--result-file`` JSON.
      * attach to a :class:`veles_tpu.parallel.jobs.JobServer` — call
        :meth:`generate_data_for_slave` / :meth:`apply_data_from_slave`
        (task-parallel jobs; SURVEY §2.4 row 2).
    """

    def __init__(self, population_size=20, generations=None,
                 config=None, evaluate=None, workflow_spec=None,
                 config_file=None, result_file=None, fitness_key=None,
                 max_evaluations=None, extra_args=(),
                 **population_kwargs):
        super(GeneticsOptimizer, self).__init__()
        self.config = config if config is not None else root
        self.tuneables = tune.scan_tuneables(self.config)
        if not self.tuneables:
            raise ValueError(
                "config has no Tuneable (Range/Choice) values to optimize")
        self.evaluate = evaluate
        self.workflow_spec = workflow_spec
        self.config_file = config_file
        self.result_file = result_file
        self.fitness_key = fitness_key
        self.extra_args = tuple(extra_args)
        if generations is None and max_evaluations is None:
            # `--optimize SIZE` without :GENERATIONS must terminate
            generations = 10
        self.generations = generations
        self.max_evaluations = max_evaluations
        self.evaluations = 0
        self.population = Population(
            tune.specs_of(self.tuneables), size=population_size,
            **population_kwargs)
        # chromosome 0 starts at the defaults (the reference seeds the
        # population with the hand-written config)
        self.population.chromosomes[0].genes[:] = \
            tune.default_genome(self.tuneables)
        self._inflight = {}   # slave_id → chromosome (distributed mode)

    # -- shared -------------------------------------------------------------
    def overrides_for(self, chromo):
        return tune.decode_genome(self.tuneables, chromo.genes)

    @property
    def best(self):
        return self.population.best

    # -- standalone ---------------------------------------------------------
    def _evaluate_one(self, chromo):
        overrides = self.overrides_for(chromo)
        if self.evaluate is not None:
            fitness = float(self.evaluate(overrides))
        elif self.workflow_spec is not None:
            fitness = self._evaluate_subprocess(overrides)
        else:
            raise RuntimeError("no evaluate callable or workflow_spec")
        chromo.fitness = fitness
        self.evaluations += 1
        self.debug("evaluated %s → %.6g", overrides, fitness)

    def _evaluate_subprocess(self, overrides):
        """Child `python -m veles_tpu` run (ref ``_exec`` ``:268``)."""
        fd, result_path = tempfile.mkstemp(suffix=".json",
                                           prefix="veles_ga_")
        os.close(fd)
        try:
            cmd = [sys.executable, "-m", "veles_tpu",
                   self.workflow_spec]
            if self.config_file:
                cmd.append(self.config_file)
            cmd.append("--result-file=%s" % result_path)
            cmd += list(self.extra_args)
            cmd += ["%s=%s" % (path, json.dumps(value))
                    for path, value in overrides.items()]
            self.info("spawning: %s", " ".join(cmd))
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                self.warning("child failed (rc=%d): %s", proc.returncode,
                             proc.stderr[-2000:])
                return float("-inf")
            with open(result_path, "r") as fin:
                results = json.load(fin)
            return fitness_from_results(results, self.fitness_key)
        finally:
            os.unlink(result_path)

    def run(self):
        """Standalone evolution loop; returns the best chromosome with
        ``.config_overrides`` attached."""
        generation = 0
        while True:
            for chromo in self.population.pending:
                self._evaluate_one(chromo)
                if self.max_evaluations is not None and \
                        self.evaluations >= self.max_evaluations:
                    break
            best = self.population.best
            generation += 1
            self.info("generation %d done: best fitness %.6g",
                      generation, best.fitness)
            if self.generations is not None and \
                    generation >= self.generations:
                break
            if self.max_evaluations is not None and \
                    self.evaluations >= self.max_evaluations:
                break
            if self.population.pending:   # stopped mid-generation
                break
            self.population.evolve()
        best = self.population.best
        best.config_overrides = self.overrides_for(best)
        if self.result_file:
            with open(self.result_file, "w") as fout:
                json.dump({"fitness": best.fitness,
                           "overrides": best.config_overrides,
                           "evaluations": self.evaluations}, fout,
                          indent=2)
        return best

    # -- distributed (job-layer) mode --------------------------------------
    def checksum(self):
        return "genetics:%d:%s" % (
            len(self.tuneables),
            ",".join(path for path, _ in self.tuneables))

    def generate_data_for_slave(self, slave):
        """One pending chromosome per job; evolves the population when a
        generation completes (ref ``optimization_workflow.py:186``)."""
        from veles_tpu.workflow import NoJobYet
        pending = [c for c in self.population.pending
                   if id(c) not in {id(v) for v in
                                    self._inflight.values()}]
        if not pending:
            if self._inflight:
                # generation boundary: results still in flight — slaves
                # must wait, not quit (protocol "wait" op)
                raise NoJobYet()
            if self.generations is None or \
                    self.population.generation + 1 < self.generations:
                self.population.evolve()
                return self.generate_data_for_slave(slave)
            return None   # generation cap reached
        chromo = pending[0]
        self._inflight[slave.id] = chromo
        return {"genes": chromo.genes.tolist(),
                "overrides": self.overrides_for(chromo)}

    def apply_data_from_slave(self, data, slave):
        chromo = self._inflight.pop(slave.id, None)
        if chromo is None:
            return
        chromo.fitness = float(data["fitness"])
        self.evaluations += 1

    def drop_slave(self, slave):
        """Requeue the dead slave's chromosome (ref ``:218-222``)."""
        self._inflight.pop(slave.id, None)
