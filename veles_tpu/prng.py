"""Named deterministic random streams over JAX threefry keys.

Parity target: reference ``veles/prng/`` — named generators (``"master"``
etc., ``random_generator.py:64``), seeding from file bytes or integers
(``:106``), state pickling (``:93-99``), and a device-side uniform stream
unit backed by xorshift1024* kernels (``prng/uniform.py:49``,
``ocl/random.cl``).

TPU re-design: the stream IS a ``jax.random`` key that is *split*, never
reused — a counter-based design that stays deterministic under ``vmap`` /
``pjit`` / retraces (the reference's mutable xorshift state cannot).  Each
named generator also carries a mirrored ``numpy.random.Generator`` for
host-side consumers (shuffling, loaders) so interpret-mode runs match.
"""

import hashlib
import os
import threading

import numpy

_streams = {}
_base_seed = 0x5eed
_lock = threading.Lock()


class RandomGenerator(object):
    """A named deterministic stream.

    Holds a JAX PRNG key (split-on-demand) and a numpy Generator seeded from
    the same entropy.  Pickleable: state is (seed, counter).
    """

    def __init__(self, name, seed=None):
        self.name = name
        self.seed(seed if seed is not None else 0x5eed)

    # -- seeding -----------------------------------------------------------
    def seed(self, seed):
        """Seed from an int, bytes, or a file path (ref
        ``random_generator.py:106`` accepts file contents /dev/urandom)."""
        if isinstance(seed, str) and os.path.exists(seed):
            with open(seed, "rb") as fin:
                seed = fin.read(64)
        if isinstance(seed, (bytes, bytearray)):
            seed = int.from_bytes(
                hashlib.sha256(bytes(seed)).digest()[:8], "little")
        self._seed = int(seed) & 0xFFFFFFFFFFFFFFFF
        self._counter = 0
        self._numpy = numpy.random.Generator(
            numpy.random.Philox(key=self._seed))
        return self

    # -- JAX side ----------------------------------------------------------
    @property
    def jax_seed(self):
        return self._seed

    def key(self):
        """Return a fresh, never-before-returned JAX PRNG key.

        Derivation is ``fold_in(key(seed), counter)`` — reproducible given
        (seed, number of prior draws), stable across processes.
        """
        import jax
        self._counter += 1
        base = jax.random.key(self._seed)
        return jax.random.fold_in(base, self._counter)

    # -- numpy side (host consumers: shuffles, init fills) -----------------
    @property
    def numpy(self):
        return self._numpy

    def shuffle(self, arr):
        self._counter += 1
        self._numpy.shuffle(arr)

    def permutation(self, n):
        self._counter += 1
        return self._numpy.permutation(n)

    def fill_normal(self, arr, stddev=1.0, mean=0.0):
        self._counter += 1
        arr[...] = self._numpy.normal(
            loc=mean, scale=stddev, size=arr.shape).astype(arr.dtype)

    def fill_uniform(self, arr, low=-1.0, high=1.0):
        self._counter += 1
        arr[...] = self._numpy.uniform(
            low=low, high=high, size=arr.shape).astype(arr.dtype)

    def randint(self, low, high=None, size=None):
        self._counter += 1
        return self._numpy.integers(low, high, size=size)

    # -- pickling ----------------------------------------------------------
    def __getstate__(self):
        # The exact Philox position (counter/buffer) rides along so a
        # resumed run continues the identical numpy stream (ref
        # ``random_generator.py:93-99`` pickles the mtrand state tuple).
        return {"name": self.name, "seed": self._seed,
                "counter": self._counter,
                "numpy_state": self._numpy.bit_generator.state}

    def __setstate__(self, state):
        self.name = state["name"]
        self._seed = state["seed"]
        self._counter = state["counter"]
        self._numpy = numpy.random.Generator(
            numpy.random.Philox(key=self._seed))
        self._numpy.bit_generator.state = state["numpy_state"]

    def __repr__(self):
        return "<RandomGenerator %r seed=%#x n=%d>" % (
            self.name, self._seed, self._counter)


def _derived_seed(name):
    offset = int.from_bytes(
        hashlib.sha256(name.encode()).digest()[:4], "little")
    return _base_seed + offset


def get(name="master"):
    """The named-stream registry (ref ``prng/__init__.py`` ``get``).

    Streams created after :func:`seed_all` still derive from the global
    base seed, so creation order never changes a stream's sequence."""
    with _lock:
        stream = _streams.get(name)
        if stream is None:
            stream = _streams[name] = RandomGenerator(
                name, seed=_derived_seed(name))
        return stream


def seed_all(seed):
    """Set the global base seed and (re)seed every stream,
    deterministically differentiated by name hash so streams stay
    independent."""
    global _base_seed
    with _lock:
        _base_seed = int(seed)
        for name, stream in _streams.items():
            stream.seed(_derived_seed(name))
        if "master" not in _streams:
            _streams["master"] = RandomGenerator(
                "master", seed=_derived_seed("master"))
