"""Ensemble train/test managers.

Contract with workflows (mirrors the reference's config seam): each
member run receives config overrides
``root.common.ensemble.{index,size,train_ratio}`` plus a distinct PRNG
seed, and reports metrics through ``--result-file``.  Loaders honor
``root.common.ensemble.train_ratio`` automatically
(:mod:`veles_tpu.loader.base`), so any StandardWorkflow model is
ensemble-able unmodified.

Like the genetics optimizer, members can also be farmed to slaves as
jobs through :class:`veles_tpu.parallel.jobs.JobServer` — each job is a
whole training run (task parallelism, SURVEY §2.4).
"""

import json
import os
import subprocess
import sys
import tempfile

from veles_tpu.logger import Logger


class _EnsembleBase(Logger):
    def __init__(self, workflow_spec=None, config_file=None,
                 result_file=None, evaluate=None, extra_args=()):
        super(_EnsembleBase, self).__init__()
        self.workflow_spec = workflow_spec
        self.config_file = config_file
        self.result_file = result_file
        self.evaluate = evaluate   # in-process hook (tests/embedding)
        #: CLI args every member inherits (-d, --fused, overrides)
        self.extra_args = tuple(extra_args)

    def _spawn(self, overrides, extra_args=()):
        """One child training/testing run; returns its results dict
        (ref ``base_workflow.py:135-150``)."""
        fd, result_path = tempfile.mkstemp(suffix=".json",
                                           prefix="veles_ens_")
        os.close(fd)
        try:
            cmd = [sys.executable, "-m", "veles_tpu", self.workflow_spec]
            if self.config_file:
                cmd.append(self.config_file)
            cmd.append("--result-file=%s" % result_path)
            cmd += list(self.extra_args)
            cmd += list(extra_args)
            cmd += ["%s=%s" % (path, json.dumps(value))
                    for path, value in overrides.items()]
            self.info("spawning: %s", " ".join(cmd))
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                self.warning("member failed (rc=%d): %s",
                             proc.returncode, proc.stderr[-2000:])
                return None
            with open(result_path, "r") as fin:
                return json.load(fin)
        finally:
            os.unlink(result_path)

    def _write(self, payload):
        if self.result_file:
            with open(self.result_file, "w") as fout:
                json.dump(payload, fout, indent=2)


class EnsembleModelManager(_EnsembleBase):
    """Trains ``size`` members, each on a ``train_ratio`` random subset
    (ref ``model_workflow.py:50``)."""

    def __init__(self, size=5, train_ratio=1.0, seed_base=1234,
                 **kwargs):
        super(EnsembleModelManager, self).__init__(**kwargs)
        if size < 1:
            raise ValueError("ensemble size must be >= 1")
        if not 0.0 < train_ratio <= 1.0:
            raise ValueError("train_ratio must be in (0, 1]")
        self.size = size
        self.train_ratio = train_ratio
        self.seed_base = seed_base
        self.results = []
        self._pending = list(range(size))   # job-layer work set
        self._inflight = {}

    def overrides_for(self, index):
        return {
            "common.ensemble.index": index,
            "common.ensemble.size": self.size,
            "common.ensemble.train_ratio": self.train_ratio,
            "common.engine.seed": self.seed_base + index,
        }

    def run(self):
        self.results = []
        for index in range(self.size):
            overrides = self.overrides_for(index)
            if self.evaluate is not None:
                member = self.evaluate(overrides)
            else:
                member = self._spawn(overrides)
            self.results.append({"index": index,
                                 "overrides": overrides,
                                 "results": member})
        trained = [r for r in self.results if r["results"] is not None]
        self.info("ensemble: %d/%d members trained", len(trained),
                  self.size)
        payload = {"size": self.size, "train_ratio": self.train_ratio,
                   "models": self.results}
        self._write(payload)
        return payload

    # -- job-layer mode (one member per slave job) -------------------------
    def checksum(self):
        return "ensemble-train:%d:%s" % (self.size, self.workflow_spec)

    def generate_data_for_slave(self, slave):
        if not self._pending:
            if self._inflight:
                from veles_tpu.workflow import NoJobYet
                raise NoJobYet()   # a member may be requeued on drop
            return None
        index = self._pending.pop(0)
        self._inflight[slave.id] = index
        return {"index": index, "overrides": self.overrides_for(index)}

    def apply_data_from_slave(self, data, slave):
        self._inflight.pop(slave.id, None)
        self.results.append(data)

    def drop_slave(self, slave):
        index = self._inflight.pop(slave.id, None)
        if index is not None:   # requeue (ref base_workflow.py:124-128)
            self._pending.insert(0, index)


class EnsembleTestManager(_EnsembleBase):
    """Runs every trained member on the test set and aggregates
    (ref ``test_workflow.py:50``)."""

    def __init__(self, input_file=None, input_data=None, **kwargs):
        super(EnsembleTestManager, self).__init__(**kwargs)
        if input_data is not None:
            self.listing = input_data
        elif input_file:
            with open(input_file, "r") as fin:
                self.listing = json.load(fin)
        else:
            raise ValueError("input_file or input_data required")

    def run(self):
        outputs = []
        for member in self.listing["models"]:
            overrides = dict(member["overrides"])
            if self.evaluate is not None:
                result = self.evaluate(overrides)
            else:
                # resume the member's trained snapshot (recorded by the
                # Snapshotter's result metric); testing a fresh workflow
                # would score random weights
                snapshot = (member.get("results") or {}).get("snapshot")
                extra = ("--test",)
                if snapshot:
                    extra += ("-w", snapshot)
                else:
                    self.warning(
                        "member %s has no snapshot in its results — "
                        "testing an untrained model (add a Snapshotter "
                        "to the training workflow)", member["index"])
                result = self._spawn(overrides, extra_args=extra)
            outputs.append({"index": member["index"], "results": result})
        payload = {"size": self.listing["size"], "tests": outputs,
                   "aggregate": self.aggregate(outputs)}
        self._write(payload)
        return payload

    @staticmethod
    def aggregate(outputs):
        """Averages every shared numeric metric across members."""
        acc = {}
        counts = {}
        for entry in outputs:
            results = entry.get("results") or {}
            for key, value in results.items():
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                acc[key] = acc.get(key, 0.0) + v
                counts[key] = counts.get(key, 0) + 1
        return {key: acc[key] / counts[key] for key in acc}
