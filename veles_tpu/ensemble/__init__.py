"""Ensembles: train N models on random train-subsets, test by voting.

Parity target: reference ``veles/ensemble/`` — ``EnsembleModelManager``
(``model_workflow.py:50``) spawning one child ``veles`` process per
member (``base_workflow.py:135-150``) with ``train_ratio`` subsets
(``loader/base.py:524``), and ``EnsembleTestManager``
(``test_workflow.py:50``) evaluating the listing produced by training;
results ride ``--result-file`` JSON (``workflow.py:827-851``).
"""

from veles_tpu.ensemble.manager import (     # noqa: F401
    EnsembleModelManager, EnsembleTestManager)
