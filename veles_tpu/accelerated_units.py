"""AcceleratedUnit: backend-dispatched compute units with a jit cache.

Parity target: reference ``veles/accelerated_units.py`` —
``AcceleratedUnit`` (``:130``) dispatches one of
``numpy_run/ocl_run/cuda_run`` per attached device, builds + caches kernel
programs (``build_program`` ``:298``, cache ``:605-674``) and optionally
numba-JITs the numpy path (``:254-265``); ``AcceleratedWorkflow``
(``:827``) owns the device.

TPU re-design: the two backend methods are ``numpy_run`` (eager interpret
— the debug path, pdb-able) and ``tpu_run`` (default: calls jitted pure
functions over ``Vector.devmem`` arrays).  ``build_program``'s #define
specialization + binary cache collapses into :meth:`AcceleratedUnit.jit`
— XLA retraces per input shape and caches compiles; the unit-level cache
table keyed by (fn, shapes) keeps retrace bookkeeping observable the way
the reference's ``.cache`` dir was.

Scheduler fast path: a unit additionally exposing ``stitch_stage()``
(a pure stage over its Vectors) can be fused with its neighbours into
ONE XLA program per segment at ``Workflow.initialize()`` — see
:mod:`veles_tpu.stitch` and ``docs/engine_fast_path.md``.  When a
stitched workflow runs, the segment executes at the head unit's
``run_wrapped`` and member ``tpu_run`` bodies are skipped for that
pass; ``root.common.engine.stitch = off`` (or any direct ``run()``
call) keeps the per-unit dispatch below.
"""

import jax

from veles_tpu.memory import Vector
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow


class AcceleratedUnit(Unit):
    """Unit with per-backend execution paths."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(AcceleratedUnit, self).__init__(workflow, **kwargs)
        self.device = None
        self.intermittent = kwargs.get("intermittent", False)
        #: documented common unit param: force the eager numpy path
        #: regardless of the attached device (per-unit debugging)
        self.force_numpy = bool(kwargs.get("force_numpy", False))

    def init_unpickled(self):
        super(AcceleratedUnit, self).init_unpickled()
        self._jit_cache_ = {}

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, device=None, **kwargs):
        """Attach the device and initialize all Vector attributes
        (the reference scans the class hierarchy for backend interfaces,
        ``accelerated_units.py:220-241``; here the contract is just the
        two well-known method names)."""
        if device is not None:
            self.device = device
        elif self.device is None:
            wf = self.workflow
            self.device = getattr(wf, "device", None)
        super(AcceleratedUnit, self).initialize(**kwargs)
        for vec in self._vectors():
            vec.initialize(self.device)

    def _vectors(self):
        for value in self.__dict__.values():
            if isinstance(value, Vector):
                yield value

    def init_vectors(self, *vectors):
        for vec in vectors:
            vec.initialize(self.device)

    # -- dispatch -----------------------------------------------------------
    @property
    def is_interpret(self):
        return self.device is None or self.device.is_interpret

    def run(self):
        if self.force_numpy or self.is_interpret:
            return self.numpy_run()
        return self.tpu_run()

    def numpy_run(self):
        raise NotImplementedError(
            "%s defines no numpy_run" % type(self).__name__)

    def tpu_run(self):
        """Default: reuse the numpy path through the Vector coherence
        protocol (correct but host-bound); compute units override with a
        jitted body."""
        return self.numpy_run()

    # -- jit cache (replaces build_program/#define specialization) ----------
    def jit(self, fn, static_argnums=(), donate_argnums=()):
        """Compile-cache a pure function for this unit.

        Keyed on the function *object* (never its name — same-named
        closures must not alias); XLA handles per-shape retraces below
        this.  Define the body once (module level or in ``initialize``)
        rather than per call, or every call re-jits."""
        key = (fn, tuple(static_argnums), tuple(donate_argnums))
        cached = self._jit_cache_.get(key)
        if cached is None:
            cached = jax.jit(fn, static_argnums=static_argnums,
                             donate_argnums=donate_argnums)
            self._jit_cache_[key] = cached
        return cached

    @property
    def compile_stats(self):
        return {fn.__name__: getattr(jitted, "_cache_size",
                                     lambda: None)()
                for (fn, _, _), jitted in self._jit_cache_.items()}

    def unmap_vectors(self, *vectors):
        """Reference API compatibility (``accelerated_units.py:480``):
        declare host edits finished on the given vectors."""
        for vec in vectors:
            vec.unmap()


class AcceleratedWorkflow(Workflow):
    """Workflow owning a device (ref ``accelerated_units.py:827``)."""

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super(AcceleratedWorkflow, self).__init__(workflow, **kwargs)
        self.device = kwargs.get("device")

    def initialize(self, device=None, **kwargs):
        if device is None:
            device = self.device
        if device is None:
            from veles_tpu.backends import AutoDevice
            device = AutoDevice()
        return super(AcceleratedWorkflow, self).initialize(
            device=device, **kwargs)
