"""Vector: the universal buffer bridging host numpy and device HBM.

Parity target: reference ``veles/memory.py`` — ``Array`` (``:110``): a
numpy mirror + device buffer with an explicit
``map_read/map_write/map_invalidate/unmap`` coherence protocol
(``:371-383``), transparent device→host sync when pickling
(``__getstate__`` ``:284-299``) and a ``Watcher`` accounting peak device
allocation (``:56-107``).

TPU re-design: the device buffer is a ``jax.Array``.  JAX arrays are
immutable, so the mutable-buffer protocol becomes *generation tracking*:
the Vector knows whether host or device holds the freshest data and
converts lazily.  ``map_write → unmap`` round-trips still work (host edit
then re-upload), but the idiomatic fast path for jitted units is
``v.devmem`` in / reassign ``v.devmem`` out — no copies, donation-friendly.
Pickling syncs device→host exactly like the reference, so whole-workflow
snapshots capture weights regardless of where they live.
"""

import threading

import numpy

from veles_tpu import trace
from veles_tpu.distributable import Pickleable


class Watcher(object):
    """Device-memory accounting (ref ``memory.py:56-107``).

    Besides the reference's peak-allocation bookkeeping, the Watcher
    counts **transfer traffic in both directions**: every Vector
    upload and staging-ring upload reports ``h2d_bytes`` /
    ``h2d_transfers``, and every device→host fetch (``map_read``
    coherence syncs, the deferred-metrics ``device_get_all`` batch)
    reports ``d2h_bytes`` / ``d2h_transfers`` — so the bench ladder
    records ``h2d_bytes_per_step`` AND ``d2h_bytes_per_step`` and the
    input-pipeline / deferred-metrics work shows up as eliminated
    transfer bytes, not just img/s.  Each accounting call also samples
    a ``veles_tpu.trace`` counter track ("h2d" category) when tracing
    is on, so Perfetto shows the cumulative byte curves on the
    timeline.

    The Watcher is also the **live HBM ledger** behind
    ``veles_tpu.prof``: tracked bytes carry a *category* (the
    Vector's ``category`` tag — ``params`` / ``dataset`` / ``staging``
    / ``kv`` / ``other``) with current + peak accounting per category,
    and a per-Vector registry of resident buffers, so
    ``perf_report()`` can say not just *how much* HBM is in use but
    *whose* it is and what the headroom was."""

    lock = threading.Lock()
    bytes_in_use = 0
    peak_bytes = 0
    #: bumped by reset(); holds taken before the current generation
    #: were already wiped from the ledger, so their releases must be
    #: no-ops (GC can finalize a Vector long after a reset)
    generation = 0
    h2d_bytes = 0
    h2d_transfers = 0
    d2h_bytes = 0
    d2h_transfers = 0
    #: per-category current/peak resident bytes ({category: int})
    bytes_by_category = {}
    peak_by_category = {}
    #: id(owner) -> (shape, dtype str, nbytes, category) for every
    #: live tracked device buffer — the per-Vector ledger detail
    _vectors = {}

    @classmethod
    def track(cls, nbytes, category=None, owner=None):
        cat = category or "other"
        with cls.lock:
            cls.bytes_in_use += nbytes
            cls.peak_bytes = max(cls.peak_bytes, cls.bytes_in_use)
            total = cls.bytes_by_category.get(cat, 0) + nbytes
            cls.bytes_by_category[cat] = total
            cls.peak_by_category[cat] = max(
                cls.peak_by_category.get(cat, 0), total)
            if owner is not None:
                cls._vectors[id(owner)] = (
                    getattr(owner, "shape", None),
                    str(getattr(owner, "dtype", None)), nbytes, cat)

    @classmethod
    def untrack(cls, nbytes, category=None, owner=None):
        cat = category or "other"
        with cls.lock:
            cls.bytes_in_use -= nbytes
            cls.bytes_by_category[cat] = \
                cls.bytes_by_category.get(cat, 0) - nbytes
            if owner is not None:
                cls._vectors.pop(id(owner), None)

    @classmethod
    def hbm_ledger(cls, top=8):
        """JSON-able residency snapshot: totals, per-category
        current/peak, and the ``top`` biggest resident buffers."""
        with cls.lock:
            by_category = {
                cat: {"bytes": cls.bytes_by_category.get(cat, 0),
                      "peak": peak}
                for cat, peak in cls.peak_by_category.items()}
            vectors = sorted(cls._vectors.values(),
                             key=lambda v: -v[2])[:top]
        return {
            "bytes_in_use": cls.bytes_in_use,
            "peak_bytes": cls.peak_bytes,
            "by_category": by_category,
            "top_vectors": [
                {"shape": list(shape) if shape else None,
                 "dtype": dtype, "nbytes": nbytes, "category": cat}
                for shape, dtype, nbytes, cat in vectors],
        }

    @classmethod
    def track_h2d(cls, nbytes):
        with cls.lock:
            cls.h2d_bytes += int(nbytes)
            cls.h2d_transfers += 1
            total = cls.h2d_bytes
        trace.counter("h2d", "h2d_bytes", total)

    @classmethod
    def track_d2h(cls, nbytes):
        with cls.lock:
            cls.d2h_bytes += int(nbytes)
            cls.d2h_transfers += 1
            total = cls.d2h_bytes
        trace.counter("h2d", "d2h_bytes", total)

    @classmethod
    def reset(cls):
        with cls.lock:
            cls.generation += 1
            cls.bytes_in_use = 0
            cls.peak_bytes = 0
            cls.h2d_bytes = 0
            cls.h2d_transfers = 0
            cls.d2h_bytes = 0
            cls.d2h_transfers = 0
            cls.bytes_by_category = {}
            cls.peak_by_category = {}
            cls._vectors = {}


class Vector(Pickleable):
    """Host-mirrored device buffer.

    ``category`` tags the buffer for the Watcher's HBM ledger
    (``params`` / ``dataset`` / ``staging`` / ``kv``; ``None`` groups
    under ``other``) — set it at construction (weights, resident
    datasets and minibatch staging buffers already are), it rides
    pickling and is read at device-upload time."""

    def __init__(self, data=None, category=None):
        super(Vector, self).__init__()
        self._mem = None          # host numpy array (may be stale)
        self._device = None
        self.category = category
        if data is not None:
            self.reset(data)

    def init_unpickled(self):
        super(Vector, self).init_unpickled()
        self._devmem_ = None       # jax.Array (transient)
        self._host_fresh_ = True   # host copy up to date
        self._dev_fresh_ = False   # device copy up to date
        self._tracked_bytes_ = 0
        self._tracked_category_ = None
        self._tracked_gen_ = 0
        #: pod-mesh placement (NamedSharding); process-local like the
        #: device handle, installed by PodRuntime via set_sharding()
        self._sharding_ = None
        # pre-category pickles (and bare __new__ construction paths)
        # lack the attribute entirely
        if not hasattr(self, "category"):
            self.category = None

    # -- basic properties ---------------------------------------------------
    def reset(self, data):
        """Install new host contents (ref ``Array.reset`` semantics)."""
        self._mem = numpy.ascontiguousarray(data) \
            if data is not None else None
        self._drop_devmem()
        self._host_fresh_ = True
        self._dev_fresh_ = False
        return self

    @property
    def shape(self):
        ref = self._devmem_ if self._devmem_ is not None else self._mem
        return tuple(ref.shape) if ref is not None else None

    @property
    def size(self):
        shape = self.shape
        if shape is None:
            return 0
        return int(numpy.prod(shape)) if shape else 1

    @property
    def dtype(self):
        ref = self._devmem_ if self._devmem_ is not None else self._mem
        return numpy.dtype(str(ref.dtype)) if ref is not None else None

    @property
    def nbytes(self):
        ref = self._devmem_ if self._devmem_ is not None else self._mem
        if ref is None:
            return 0
        return int(numpy.prod(ref.shape)) * ref.dtype.itemsize

    def __bool__(self):
        return self.shape is not None

    def __len__(self):
        shape = self.shape
        return shape[0] if shape else 0

    def __repr__(self):
        where = "dev" if (self._devmem_ is not None
                          and not self._host_fresh_) else "host"
        return "<Vector %s %s @%s>" % (self.shape, self.dtype, where)

    # -- device attachment --------------------------------------------------
    def initialize(self, device):
        """Attach to a device; uploads lazily on first devmem access."""
        self._device = device
        return self

    @property
    def device(self):
        return self._device

    # -- the coherence protocol --------------------------------------------
    @property
    def mem(self):
        """Host view.  Always safe to *read*; call :meth:`unmap` after
        in-place writes to publish them to the device."""
        self.map_read()
        return self._mem

    @mem.setter
    def mem(self, value):
        self.reset(value)

    @property
    def devmem(self):
        """The ``jax.Array``; uploads the host copy if it is fresher."""
        if self._device is None or self._device.is_interpret:
            return self.mem
        if self._devmem_ is None or not self._dev_fresh_:
            if self._mem is None:
                raise ValueError("empty Vector has no device memory")
            if self._sharding_ is not None:
                # pod placement: EVERY upload of this Vector (epoch
                # reshuffles included) lands with its mesh sharding,
                # so the AOT pod executables never see a drifted
                # single-device array
                import jax
                self._set_devmem(jax.device_put(self._mem,
                                                self._sharding_))
            else:
                self._set_devmem(self._device.put(self._mem))
            Watcher.track_h2d(self._mem.nbytes)
            self._dev_fresh_ = True   # host and device now agree
        return self._devmem_

    @devmem.setter
    def devmem(self, value):
        """Publish a new device array (the jitted-unit fast path)."""
        if self._device is not None and self._device.is_interpret:
            self._mem = numpy.asarray(value)
            self._host_fresh_ = True
            self._dev_fresh_ = False
            return
        self._set_devmem(value)
        self._dev_fresh_ = True
        self._host_fresh_ = False

    def map_read(self):
        """Ensure the host copy reflects device state (implicit D2H sync
        point, ref ``memory.py:371``)."""
        if not self._host_fresh_ and self._devmem_ is not None:
            self._mem = numpy.asarray(self._devmem_)
            self._host_fresh_ = True   # copies agree; device stays fresh
            Watcher.track_d2h(self._mem.nbytes)
        return self

    def map_write(self):
        """Declare intent to edit the host copy in place: next devmem
        access re-uploads."""
        self.map_read()
        if self._mem is not None and not self._mem.flags.writeable:
            # numpy views of jax arrays are read-only — materialize.
            self._mem = numpy.array(self._mem)
        self._dev_fresh_ = False
        return self

    def publish(self, host_array=None, device_array=None):
        """Install matching host and device copies in ONE step — the
        consume half of the prefetch staging ring: a background worker
        prepared both representations (host fill + async upload), so
        neither side needs a transfer here.  The previous device
        minibatch is released first (its buffer returns to the
        allocator — the donation analogue for a producer that cannot
        alias into jit's donate_argnums).

        Passing only ``host_array`` behaves like an in-place
        ``map_write`` edit; passing both marks BOTH sides fresh."""
        if host_array is not None:
            if self._mem is None or self._mem.shape != host_array.shape \
                    or not self._mem.flags.writeable:
                self._mem = numpy.array(host_array)
            else:
                self._mem[...] = host_array
            self._host_fresh_ = True
            self._dev_fresh_ = False
        if device_array is not None:
            self._set_devmem(device_array)
            self._dev_fresh_ = True
            if host_array is None:
                self._host_fresh_ = False
        return self

    @property
    def sharding(self):
        """The pinned pod-mesh placement (None = plain single-device
        puts through ``device.put``)."""
        return self._sharding_

    def set_sharding(self, sharding):
        """Pin (or clear, with None) this Vector's device placement to
        a ``jax.sharding.Sharding`` — the pod runtime's reshard
        primitive.  The freshest contents are preserved: a live device
        copy syncs to host first, then the device side drops so the
        next ``devmem`` access re-places it under the new sharding
        (chip-kill reshard = set a smaller mesh's shardings and touch
        ``devmem``).  No-op when the sharding is unchanged."""
        if sharding is self._sharding_:
            return self
        if self._devmem_ is not None:
            self.map_read()
        self._sharding_ = sharding
        self._drop_devmem()
        self._dev_fresh_ = False
        return self

    def map_invalidate(self):
        """Declare the host copy garbage (device will be overwritten)."""
        self._host_fresh_ = True
        self._dev_fresh_ = False
        self._drop_devmem()
        return self

    def unmap(self):
        """Compatibility no-op: publishing host edits is what
        :meth:`map_write` declares; the upload itself is lazy."""
        return self

    # -- pickling (snapshots) ----------------------------------------------
    def __getstate__(self):
        self.map_read()   # device → host sync (ref memory.py:284-299)
        return super(Vector, self).__getstate__()

    # -- helpers ------------------------------------------------------------
    def _set_devmem(self, value):
        self._untrack_devmem()
        self._devmem_ = value
        self._tracked_bytes_ = (
            int(numpy.prod(value.shape)) * value.dtype.itemsize
            if value is not None and value.shape else 0)
        if self._tracked_bytes_:
            self._tracked_category_ = getattr(self, "category", None)
            Watcher.track(self._tracked_bytes_,
                          self._tracked_category_, owner=self)
            self._tracked_gen_ = Watcher.generation

    def _untrack_devmem(self):
        if self._tracked_bytes_:
            # a Watcher.reset() since the hold was taken already
            # wiped these bytes; releasing them again would drive
            # the ledger (and its category) negative
            if getattr(self, "_tracked_gen_", 0) == Watcher.generation:
                Watcher.untrack(self._tracked_bytes_,
                                self._tracked_category_, owner=self)
            self._tracked_bytes_ = 0

    def _drop_devmem(self):
        self._untrack_devmem()
        self._devmem_ = None

    def __del__(self):
        try:
            self._drop_devmem()
        except Exception:  # pragma: no cover - interpreter shutdown
            pass


class StagingRing(object):
    """Double-buffered host staging for the loader prefetch ring.

    A fixed ring of reusable host staging buffers (allocated ONCE —
    the seed prefetch path allocated a fresh ``zeros_like`` per
    background fill) plus a non-blocking upload helper: a background
    worker ``acquire()``\\ s the next slot, fills/normalizes/pads in
    place, then ``upload()``\\ s it so the device copy is in flight
    while the consumer still computes on the previous minibatch.

    Slot-reuse contract: a slot may be overwritten once ``depth``
    newer acquisitions happened — the caller picks ``depth`` ≥ its
    maximum fills-in-flight plus buffers still being read (the loader
    ring uses 3: ≤ 2 in-flight fills + 1 slot the consumer may still
    be publish-copying).
    """

    def __init__(self, shape, dtype, depth=2):
        self.depth = int(depth)
        self._slots = [numpy.zeros(shape, dtype=dtype)
                       for _ in range(self.depth)]
        self._pos = 0
        self._lock = threading.Lock()

    def acquire(self):
        """Next reusable staging buffer (round-robin).  The span
        covers the slot-lock wait — contention here means the ring is
        too shallow for the fills in flight."""
        with trace.span("loader", "ring_acquire"):
            with self._lock:
                slot = self._slots[self._pos]
                self._pos = (self._pos + 1) % self.depth
        return slot

    @staticmethod
    def upload(device, array):
        """Kick a host→device copy of a staged buffer and return the
        device array (``None`` when there is no jit device).  The put
        runs on the CALLING (background) thread — the scheduler thread
        never blocks on it — and the traffic is accounted so
        ``h2d_bytes_per_step`` bench records see staged uploads too."""
        if device is None or getattr(device, "is_interpret", True):
            return None
        with trace.span("loader", "staging_upload"):
            out = device.put(array)
        Watcher.track_h2d(array.nbytes)
        return out


def device_get_all(values):
    """Fetch a mixed list of device scalars / arrays / host numbers in
    ONE batched ``jax.device_get`` (a single transfer+sync instead of
    one per value) — the deferred-metrics fetch the device-resident
    evaluators rely on: per-minibatch metrics stay async device
    scalars, and epoch accounting pays exactly one round-trip.

    Host values (ints, floats, numpy) pass through untouched, so
    callers may mix eager (interpret) and device metrics freely."""
    device_idx = [i for i, v in enumerate(values)
                  if not isinstance(v, (int, float, numpy.number))
                  and not isinstance(v, numpy.ndarray)]
    out = list(values)
    if device_idx:
        import jax
        fetched = jax.device_get([values[i] for i in device_idx])
        Watcher.track_d2h(sum(getattr(v, "nbytes", 0)
                              for v in fetched))
        for i, val in zip(device_idx, fetched):
            out[i] = val
    return out


#: Reference-compatible alias (the reference class is ``Array``,
#: ``memory.py:110``; "Vector" is what Znicz unit attributes call theirs).
Array = Vector
