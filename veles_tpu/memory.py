"""Vector: the universal buffer bridging host numpy and device HBM.

Parity target: reference ``veles/memory.py`` — ``Array`` (``:110``): a
numpy mirror + device buffer with an explicit
``map_read/map_write/map_invalidate/unmap`` coherence protocol
(``:371-383``), transparent device→host sync when pickling
(``__getstate__`` ``:284-299``) and a ``Watcher`` accounting peak device
allocation (``:56-107``).

TPU re-design: the device buffer is a ``jax.Array``.  JAX arrays are
immutable, so the mutable-buffer protocol becomes *generation tracking*:
the Vector knows whether host or device holds the freshest data and
converts lazily.  ``map_write → unmap`` round-trips still work (host edit
then re-upload), but the idiomatic fast path for jitted units is
``v.devmem`` in / reassign ``v.devmem`` out — no copies, donation-friendly.
Pickling syncs device→host exactly like the reference, so whole-workflow
snapshots capture weights regardless of where they live.
"""

import threading

import numpy

from veles_tpu.distributable import Pickleable


class Watcher(object):
    """Device-memory accounting (ref ``memory.py:56-107``)."""

    lock = threading.Lock()
    bytes_in_use = 0
    peak_bytes = 0

    @classmethod
    def track(cls, nbytes):
        with cls.lock:
            cls.bytes_in_use += nbytes
            cls.peak_bytes = max(cls.peak_bytes, cls.bytes_in_use)

    @classmethod
    def untrack(cls, nbytes):
        with cls.lock:
            cls.bytes_in_use -= nbytes

    @classmethod
    def reset(cls):
        with cls.lock:
            cls.bytes_in_use = 0
            cls.peak_bytes = 0


class Vector(Pickleable):
    """Host-mirrored device buffer."""

    def __init__(self, data=None):
        super(Vector, self).__init__()
        self._mem = None          # host numpy array (may be stale)
        self._device = None
        if data is not None:
            self.reset(data)

    def init_unpickled(self):
        super(Vector, self).init_unpickled()
        self._devmem_ = None       # jax.Array (transient)
        self._host_fresh_ = True   # host copy up to date
        self._dev_fresh_ = False   # device copy up to date
        self._tracked_bytes_ = 0

    # -- basic properties ---------------------------------------------------
    def reset(self, data):
        """Install new host contents (ref ``Array.reset`` semantics)."""
        self._mem = numpy.ascontiguousarray(data) \
            if data is not None else None
        self._drop_devmem()
        self._host_fresh_ = True
        self._dev_fresh_ = False
        return self

    @property
    def shape(self):
        ref = self._devmem_ if self._devmem_ is not None else self._mem
        return tuple(ref.shape) if ref is not None else None

    @property
    def size(self):
        shape = self.shape
        if shape is None:
            return 0
        return int(numpy.prod(shape)) if shape else 1

    @property
    def dtype(self):
        ref = self._devmem_ if self._devmem_ is not None else self._mem
        return numpy.dtype(str(ref.dtype)) if ref is not None else None

    @property
    def nbytes(self):
        ref = self._devmem_ if self._devmem_ is not None else self._mem
        if ref is None:
            return 0
        return int(numpy.prod(ref.shape)) * ref.dtype.itemsize

    def __bool__(self):
        return self.shape is not None

    def __len__(self):
        shape = self.shape
        return shape[0] if shape else 0

    def __repr__(self):
        where = "dev" if (self._devmem_ is not None
                          and not self._host_fresh_) else "host"
        return "<Vector %s %s @%s>" % (self.shape, self.dtype, where)

    # -- device attachment --------------------------------------------------
    def initialize(self, device):
        """Attach to a device; uploads lazily on first devmem access."""
        self._device = device
        return self

    @property
    def device(self):
        return self._device

    # -- the coherence protocol --------------------------------------------
    @property
    def mem(self):
        """Host view.  Always safe to *read*; call :meth:`unmap` after
        in-place writes to publish them to the device."""
        self.map_read()
        return self._mem

    @mem.setter
    def mem(self, value):
        self.reset(value)

    @property
    def devmem(self):
        """The ``jax.Array``; uploads the host copy if it is fresher."""
        if self._device is None or self._device.is_interpret:
            return self.mem
        if self._devmem_ is None or not self._dev_fresh_:
            if self._mem is None:
                raise ValueError("empty Vector has no device memory")
            self._set_devmem(self._device.put(self._mem))
            self._dev_fresh_ = True   # host and device now agree
        return self._devmem_

    @devmem.setter
    def devmem(self, value):
        """Publish a new device array (the jitted-unit fast path)."""
        if self._device is not None and self._device.is_interpret:
            self._mem = numpy.asarray(value)
            self._host_fresh_ = True
            self._dev_fresh_ = False
            return
        self._set_devmem(value)
        self._dev_fresh_ = True
        self._host_fresh_ = False

    def map_read(self):
        """Ensure the host copy reflects device state (implicit D2H sync
        point, ref ``memory.py:371``)."""
        if not self._host_fresh_ and self._devmem_ is not None:
            self._mem = numpy.asarray(self._devmem_)
            self._host_fresh_ = True   # copies agree; device stays fresh
        return self

    def map_write(self):
        """Declare intent to edit the host copy in place: next devmem
        access re-uploads."""
        self.map_read()
        if self._mem is not None and not self._mem.flags.writeable:
            # numpy views of jax arrays are read-only — materialize.
            self._mem = numpy.array(self._mem)
        self._dev_fresh_ = False
        return self

    def map_invalidate(self):
        """Declare the host copy garbage (device will be overwritten)."""
        self._host_fresh_ = True
        self._dev_fresh_ = False
        self._drop_devmem()
        return self

    def unmap(self):
        """Compatibility no-op: publishing host edits is what
        :meth:`map_write` declares; the upload itself is lazy."""
        return self

    # -- pickling (snapshots) ----------------------------------------------
    def __getstate__(self):
        self.map_read()   # device → host sync (ref memory.py:284-299)
        return super(Vector, self).__getstate__()

    # -- helpers ------------------------------------------------------------
    def _set_devmem(self, value):
        if self._tracked_bytes_:
            Watcher.untrack(self._tracked_bytes_)
        self._devmem_ = value
        self._tracked_bytes_ = (
            int(numpy.prod(value.shape)) * value.dtype.itemsize
            if value is not None and value.shape else 0)
        if self._tracked_bytes_:
            Watcher.track(self._tracked_bytes_)

    def _drop_devmem(self):
        if self._tracked_bytes_:
            Watcher.untrack(self._tracked_bytes_)
            self._tracked_bytes_ = 0
        self._devmem_ = None

    def __del__(self):
        try:
            self._drop_devmem()
        except Exception:  # pragma: no cover - interpreter shutdown
            pass


def device_get_all(values):
    """Fetch a mixed list of device scalars / arrays / host numbers in
    ONE batched ``jax.device_get`` (a single transfer+sync instead of
    one per value) — the deferred-metrics fetch the device-resident
    evaluators rely on: per-minibatch metrics stay async device
    scalars, and epoch accounting pays exactly one round-trip.

    Host values (ints, floats, numpy) pass through untouched, so
    callers may mix eager (interpret) and device metrics freely."""
    device_idx = [i for i, v in enumerate(values)
                  if not isinstance(v, (int, float, numpy.number))
                  and not isinstance(v, numpy.ndarray)]
    out = list(values)
    if device_idx:
        import jax
        fetched = jax.device_get([values[i] for i in device_idx])
        for i, val in zip(device_idx, fetched):
            out[i] = val
    return out


#: Reference-compatible alias (the reference class is ``Array``,
#: ``memory.py:110``; "Vector" is what Znicz unit attributes call theirs).
Array = Vector
