"""L0 kernel substrate: Pallas TPU kernels + jnp fallbacks.

The TPU-native re-design of the reference's shared kernel templates
(``ocl/`` + ``cuda/``, SURVEY §2.2):

=====================  ==========================================
reference template      this package
=====================  ==========================================
matrix_multiplication   :mod:`veles_tpu.ops.gemm` (Pallas tiled)
matrix_reduce           :mod:`veles_tpu.ops.reduce`
random (xorshift1024*)  :mod:`veles_tpu.ops.random` (TPU PRNG)
fullbatch_loader        :mod:`veles_tpu.ops.gather`
mean_disp_normalizer    :mod:`veles_tpu.ops.normalize`
join.jcl (Jinja2)       :mod:`veles_tpu.ops.join`
benchmark               :mod:`veles_tpu.ops.benchmark`
=====================  ==========================================

Every op has (a) a Pallas TPU kernel for the hot path and (b) a pure jnp
fallback that XLA fuses — used on CPU, under interpret mode, and as the
golden reference in tests.  Dispatch is by the current JAX default
platform unless forced via ``use_pallas=``.
"""

from veles_tpu.ops.gemm import matmul  # noqa: F401
from veles_tpu.ops.reduce import matrix_reduce  # noqa: F401
from veles_tpu.ops.random import uniform, normal  # noqa: F401
from veles_tpu.ops.gather import take_rows  # noqa: F401
from veles_tpu.ops.normalize import mean_disp_normalize  # noqa: F401
from veles_tpu.ops.join import join  # noqa: F401


def on_tpu():
    """True when the default JAX backend is a TPU (incl. tunnel
    platforms whose devices report a TPU device_kind)."""
    import jax
    try:
        dev = jax.devices()[0]
    except RuntimeError:
        return False
    return "TPU" in getattr(dev, "device_kind", "").upper() \
        or dev.platform == "tpu"
