"""Tiled matmul: the MXU workhorse.

Re-designs the reference's ``#define``-specialized GEMM family
(``ocl/matrix_multiplication_begin.cl:1-64``, ``_precise.cl``,
``_subsum.cl``, ``_end.cl``, ``ocl/gemm.cl``; CUDA twins) as ONE Pallas
kernel: a (M/bm, N/bn, K/bk) grid with float32 VMEM accumulation and a
fused epilogue (bias + activation) — the fusion the reference obtained by
textually pasting activation code between ``_begin``/``_end`` includes.

The reference's precision levels (Kahan / multipartial sums,
``config.py:246-249``) map to the accumulator dtype: the MXU natively
accumulates bf16 products in float32, which is *more* precise than the
reference's float32 products + float32 sums, so PRECISION_LEVEL>0 needs no
special kernel on TPU.

``matmul`` carries a custom VJP so ``jax.grad`` differentiates *through*
the Pallas kernel (backward = two more tiled matmuls) — gradient units and
hand-written GD units share one code path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: fallback tiles when neither the caller nor the autotune DB
#: (``ops.benchmark.autotune_gemm`` → ``devices/device_infos.json``)
#: supplies measured ones — MXU-aligned, nothing more
DEFAULT_TILES = (512, 512, 512)   # (bm, bk, bn)


def _precision():
    """Map the reference's precision levels (Kahan/multipartial sums,
    ``config.py:246-249``) onto MXU pass counts for float32 operands:
    0 → DEFAULT (bf16 passes), 1 → HIGH (bf16_3x), 2 → HIGHEST (f32)."""
    from veles_tpu.config import root
    level = root.common.engine.get("precision_level", 0)
    return (jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGH,
            jax.lax.Precision.HIGHEST)[min(int(level), 2)]

_ACTIVATIONS = {
    None: lambda x: x,
    "linear": lambda x: x,
    "tanh": lambda x: jnp.tanh(x * 0.6666) * 1.7159,  # Znicz scaled tanh
    "sigmoid": jax.nn.sigmoid,
    # Znicz smooth ReLU — the clamped log1p form shared with
    # znicz.fused._ACT and the numpy units (the naive log(1+exp(x))
    # overflows to inf past x ≈ 88)
    "relu": lambda x: jnp.log1p(jnp.exp(jnp.minimum(x, 30.0))),
    "strict_relu": lambda x: jnp.maximum(x, 0.0),
}


def _matmul_kernel(a_ref, b_ref, bias_ref, o_ref, acc_ref, *, n_k,
                   activation, has_bias):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32,
                          precision=_precision())

    @pl.when(k == n_k - 1)
    def _epilogue():
        acc = acc_ref[:]
        if has_bias:
            acc = acc + bias_ref[:].astype(jnp.float32)
        acc = _ACTIVATIONS[activation](acc)
        o_ref[:] = acc.astype(o_ref.dtype)


from veles_tpu.ops.util import COMPILER_PARAMS as _COMPILER_PARAMS
from veles_tpu.ops.util import pad_axis as _pad_to_impl, round_up


def _pad_to(x, mult, axis):
    return _pad_to_impl(x, mult, axis)


@functools.partial(jax.jit, static_argnames=("activation", "tiles",
                                             "out_dtype", "interpret"))
def _matmul_pallas(a, b, bias, activation=None, tiles=None, out_dtype=None,
                   interpret=False):
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    out_dtype = out_dtype or a.dtype
    bm, bk, bn = tiles or DEFAULT_TILES
    bm, bk, bn = min(bm, round_up(m, 8)), min(bk, round_up(k, 128)), \
        min(bn, round_up(n, 128))
    a_p = _pad_to(_pad_to(a, bm, 0), bk, 1)
    b_p = _pad_to(_pad_to(b, bk, 0), bn, 1)
    has_bias = bias is not None
    bias_p = _pad_to(bias.reshape(1, -1), bn, 1) if has_bias \
        else jnp.zeros((1, bn), a.dtype)
    mp, kp = a_p.shape
    np_ = b_p.shape[1]
    n_k = kp // bk
    grid = (mp // bm, np_ // bn, n_k)
    out = pl.pallas_call(
        functools.partial(_matmul_kernel, n_k=n_k, activation=activation,
                          has_bias=has_bias),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a_p, b_p, bias_p)
    return out[:m, :n]


def _matmul_jnp(a, b, bias, activation=None, out_dtype=None):
    out = jnp.dot(a, b, preferred_element_type=jnp.float32,
                  precision=_precision())
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    out = _ACTIVATIONS[activation](out)
    return out.astype(out_dtype or a.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def matmul(a, b, bias=None, activation=None, tiles=None, use_pallas=None):
    """``activation(a @ b + bias)`` with MXU tiling.

    a: (M, K); b: (K, N); bias: (N,) or None.  ``tiles``: (bm, bk, bn)
    from the autotune DB.  ``use_pallas``: force kernel choice (default:
    pallas on TPU, jnp elsewhere).
    """
    return _matmul_fwd(a, b, bias, activation, tiles, use_pallas)[0]


def _dispatch(use_pallas, tiles, dtype, shape=None):
    """(use_pallas_bool, tiles) for this call.  Priority: explicit
    ``use_pallas`` arg > explicit ``root.common.engine.pallas_gemm``
    config > the autotune DB's measured winner for this device
    generation, shape class and precision level
    (``ops.benchmark.gemm_choice``) > XLA.  This runs at
    TRACE time only (jit caches the result), so the DB lookup costs
    nothing per step."""
    from veles_tpu.ops.benchmark import gemm_choice
    choice = None if use_pallas is False else gemm_choice(dtype,
                                                          shape=shape)
    db_tiles = choice[1] if choice else None
    if use_pallas is not None:
        # explicit choice still benefits from measured tiles
        return use_pallas, tiles or db_tiles
    from veles_tpu.config import root
    from veles_tpu.ops import on_tpu
    configured = root.common.engine.get("pallas_gemm", None)
    if configured is not None:
        return bool(configured) and on_tpu(), tiles or db_tiles
    if not on_tpu() or choice is None:
        # no measurement for this generation: XLA's GEMM is the safe
        # default (run scripts/autotune.py on the chip to decide)
        return False, tiles
    return choice[0] == "pallas", tiles or db_tiles


def _matmul_fwd(a, b, bias, activation, tiles, use_pallas):
    pallas, eff_tiles = _dispatch(use_pallas, tiles, a.dtype,
                                  (a.shape[0], a.shape[1], b.shape[1]))
    if pallas:
        from veles_tpu.config import root
        out = _matmul_pallas(
            a, b, bias, activation=activation, tiles=eff_tiles,
            interpret=bool(root.common.engine.get("interpret", False)))
    else:
        out = _matmul_jnp(a, b, bias, activation=activation)
    # linear backward never reads the output — don't pin it in residuals
    saved_out = out if activation not in (None, "linear") else None
    return out, (a, b, bias, saved_out)


def _matmul_bwd(activation, tiles, use_pallas, residuals, g):
    a, b, bias, out = residuals
    g = g.astype(jnp.float32)
    # d(activation) evaluated from the *output* where possible — matches
    # the reference's backward units which consume the forward output
    # (e.g. GDTanh uses y: err *= y*y*(-0.388484177) + 1.14381894).
    if activation in (None, "linear"):
        dact = g
    elif activation == "tanh":
        y = out.astype(jnp.float32)
        dact = g * (y * y * (-0.388484177) + 1.14381894)
    elif activation == "sigmoid":
        y = out.astype(jnp.float32)
        dact = g * y * (1.0 - y)
    elif activation == "relu":
        y = out.astype(jnp.float32)
        dact = g * (1.0 - jnp.exp(-y))
    elif activation == "strict_relu":
        y = out.astype(jnp.float32)
        dact = g * (y > 0.0)
    else:  # pragma: no cover
        raise ValueError(activation)
    dact = dact.astype(a.dtype)
    da = matmul(dact, b.T, None, None, tiles, use_pallas)
    db = matmul(a.T, dact, None, None, tiles, use_pallas)
    dbias = None if bias is None else jnp.sum(dact, axis=0).astype(
        bias.dtype)
    return da.astype(a.dtype), db.astype(b.dtype), dbias


matmul.defvjp(_matmul_fwd, _matmul_bwd)


# ---------------------------------------------------------------------------
# Fused backward-pass GD kernels — dW / db / dX with the weight-decay +
# momentum update folded into the dW epilogue, updating the DONATED
# parameter buffers in place.  The dense reference is
# ``znicz.gd._gd_math``; these kernels reproduce it block-tiled:
#
#     δ = err_output ⊙ act'(y)        (recomputed per block — cheaper
#                                      than materializing (B, N) in HBM)
#     dW = xᵀ·δ / B ;  v' = m·v − lr·(dW + λ·W) ;  W' = W + v'
#     db = Σδ / B  (own small kernel) ;  err_input = δ·Wᵀ  (δ·W transposed)
#
# Hyper-parameters ride as a (1, 128) float32 VMEM operand (block ==
# array dims, so no tiling constraint) because they are TRACED scalars
# — an LRAdjuster rescaling them must not retrace, mirroring the
# stitched `_gd_math` contract.
# ---------------------------------------------------------------------------

#: fallback (bf, bn, bk) = (fan-in, neurons, batch) tiles for the GD
#: kernel family when the autotune DB (``ops.benchmark.autotune_gd``)
#: has no measurement for this device generation
GD_DEFAULT_TILES = (256, 256, 256)

#: hp operand layout: [0]=lr [1]=lr_bias [2]=decay [3]=decay_bias
#: [4]=moment [5]=moment_bias [6]=1/batch
(_HP_LR, _HP_LR_B, _HP_DECAY, _HP_DECAY_B, _HP_MOM, _HP_MOM_B,
 _HP_INVB) = range(7)

#: activation derivatives from the *output* (Znicz convention) —
#: duplicated from ``znicz.gd._DERIVS`` because ops must not import
#: znicz (gd.py imports from here, not the reverse)
_GD_DERIVS = {
    None: lambda y: jnp.ones_like(y),
    "tanh": lambda y: y * y * (-0.388484177) + 1.14381894,
    "sigmoid": lambda y: y * (1.0 - y),
    "relu": lambda y: 1.0 - jnp.exp(-y),
    "strict_relu": lambda y: (y > 0).astype(y.dtype),
}


def _gd_delta(eo_ref, y_ref, activation):
    return (eo_ref[:].astype(jnp.float32)
            * _GD_DERIVS[activation](y_ref[:].astype(jnp.float32)))


def _gd_dw_kernel(x_ref, eo_ref, y_ref, w_ref, vw_ref, hp_ref, w_out,
                  vw_out, acc_ref, *, n_k, activation, transposed):
    """Grid (F/bf, N/bn, B/bk); batch is the sequential axis.  The
    weight/momentum blocks live in the STORAGE layout ((N, F) when
    transposed) — the transpose is absorbed by swapping the dot operand
    order, never by relaying out a block."""
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    delta = _gd_delta(eo_ref, y_ref, activation)
    x = x_ref[:].astype(jnp.float32)
    if transposed:
        # storage (N, F): accumulate δᵀ·x directly in that layout
        acc_ref[:] += jax.lax.dot_general(
            delta, x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        acc_ref[:] += jax.lax.dot_general(
            x, delta, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == n_k - 1)
    def _epilogue():
        hp = hp_ref[:]
        grad = acc_ref[:] * hp[0, _HP_INVB]
        w = w_ref[:].astype(jnp.float32)
        v_new = hp[0, _HP_MOM] * vw_ref[:].astype(jnp.float32) \
            - hp[0, _HP_LR] * (grad + hp[0, _HP_DECAY] * w)
        w_out[:] = (w + v_new).astype(w_out.dtype)
        vw_out[:] = v_new.astype(vw_out.dtype)


def _gd_db_kernel(eo_ref, y_ref, b_ref, vb_ref, hp_ref, b_out, vb_out,
                  acc_ref, *, n_k, activation):
    """Grid (N/bn, B/bk): the bias row accumulates Σδ over batch blocks
    into a (1, bn) scratch, then applies the same fused update."""
    kk = pl.program_id(1)

    @pl.when(kk == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    delta = _gd_delta(eo_ref, y_ref, activation)
    acc_ref[:] += jnp.sum(delta, axis=0, keepdims=True)

    @pl.when(kk == n_k - 1)
    def _epilogue():
        hp = hp_ref[:]
        grad = acc_ref[:] * hp[0, _HP_INVB]
        b = b_ref[:].astype(jnp.float32)
        v_new = hp[0, _HP_MOM_B] * vb_ref[:].astype(jnp.float32) \
            - hp[0, _HP_LR_B] * (grad + hp[0, _HP_DECAY_B] * b)
        b_out[:] = (b + v_new).astype(b_out.dtype)
        vb_out[:] = v_new.astype(vb_out.dtype)


def _gd_dx_kernel(eo_ref, y_ref, w_ref, o_ref, acc_ref, *, n_k,
                  activation, transposed):
    """Grid (B/bk, F/bf, N/bn): err_input = δ·Wᵀ (δ·W when the storage
    is transposed) against the PRE-update weights — the caller passes
    the original weight array, so standard backprop semantics hold even
    though the dW kernel updates the same logical buffer."""
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    delta = _gd_delta(eo_ref, y_ref, activation)
    w = w_ref[:].astype(jnp.float32)
    contract = (((1,), (0,)), ((), ())) if transposed \
        else (((1,), (1,)), ((), ()))
    acc_ref[:] += jax.lax.dot_general(
        delta, w, contract, preferred_element_type=jnp.float32)

    @pl.when(kk == n_k - 1)
    def _out():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def gd_fused_pallas(x, y, err_output, w, b, vw, vb, lr, lr_bias, decay,
                    decay_bias, moment, moment_bias, activation=None,
                    need_err_input=True, has_bias=True, transposed=False,
                    tiles=None, interpret=False):
    """Pallas twin of ``znicz.gd._gd_math`` — same positional signature,
    same ``(w, b, vw, vb, err_input)`` returns (``b``/``vb`` pass
    through untouched when ``has_bias`` is false, ``err_input`` is
    ``None`` when not needed).  Numerics: float32 accumulation like the
    reference, but block-tiled summation order, so parity vs the XLA
    path is documented-tolerance (~1e-5 relative), not bitwise."""
    batch = x.shape[0]
    x2 = x.reshape(batch, -1)
    eo = err_output.reshape(batch, -1)
    y2 = y.reshape(batch, -1)
    f, n = x2.shape[1], eo.shape[1]
    bf, bn, bk = tiles or GD_DEFAULT_TILES
    bf = min(bf, round_up(f, 128))
    bn = min(bn, round_up(n, 128))
    bk = min(bk, round_up(batch, 8))
    x_p = _pad_to(_pad_to(x2, bk, 0), bf, 1)
    eo_p = _pad_to(_pad_to(eo, bk, 0), bn, 1)
    y_p = _pad_to(_pad_to(y2, bk, 0), bn, 1)
    if transposed:
        w_p = _pad_to(_pad_to(w, bn, 0), bf, 1)
        vw_p = _pad_to(_pad_to(vw, bn, 0), bf, 1)
        w_spec = pl.BlockSpec((bn, bf), lambda i, j, kk: (j, i))
        acc_shape = (bn, bf)
    else:
        w_p = _pad_to(_pad_to(w, bf, 0), bn, 1)
        vw_p = _pad_to(_pad_to(vw, bf, 0), bn, 1)
        w_spec = pl.BlockSpec((bf, bn), lambda i, j, kk: (i, j))
        acc_shape = (bf, bn)
    bp, fp = x_p.shape
    np_ = eo_p.shape[1]
    n_kb = bp // bk
    hp = jnp.zeros((1, 128), jnp.float32).at[0, :7].set(jnp.stack(
        [jnp.asarray(v, jnp.float32) for v in
         (lr, lr_bias, decay, decay_bias, moment, moment_bias)]
        + [jnp.float32(1.0 / batch)]))
    hp_spec = pl.BlockSpec((1, 128), lambda *_: (0, 0))

    # err_input FIRST (traced order is irrelevant to XLA, but keeping
    # the pre-update weight read textually before the aliased update
    # makes the intent obvious)
    if need_err_input:
        err_input = pl.pallas_call(
            functools.partial(_gd_dx_kernel, n_k=np_ // bn,
                              activation=activation,
                              transposed=transposed),
            grid=(bp // bk, fp // bf, np_ // bn),
            in_specs=[
                pl.BlockSpec((bk, bn), lambda i, j, kk: (i, kk)),
                pl.BlockSpec((bk, bn), lambda i, j, kk: (i, kk)),
                pl.BlockSpec((bn, bf), lambda i, j, kk: (kk, j))
                if transposed else
                pl.BlockSpec((bf, bn), lambda i, j, kk: (j, kk)),
            ],
            out_specs=pl.BlockSpec((bk, bf), lambda i, j, kk: (i, j)),
            out_shape=jax.ShapeDtypeStruct((bp, fp), jnp.float32),
            scratch_shapes=[pltpu.VMEM((bk, bf), jnp.float32)],
            compiler_params=_COMPILER_PARAMS(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(eo_p, y_p, w_p)[:batch, :f]
    else:
        err_input = None

    w_new, vw_new = pl.pallas_call(
        functools.partial(_gd_dw_kernel, n_k=n_kb,
                          activation=activation, transposed=transposed),
        grid=(fp // bf, np_ // bn, n_kb),
        in_specs=[
            pl.BlockSpec((bk, bf), lambda i, j, kk: (kk, i)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            w_spec, w_spec, hp_spec,
        ],
        out_specs=[w_spec, w_spec],
        out_shape=[jax.ShapeDtypeStruct(w_p.shape, w.dtype),
                   jax.ShapeDtypeStruct(vw_p.shape, vw.dtype)],
        scratch_shapes=[pltpu.VMEM(acc_shape, jnp.float32)],
        input_output_aliases={3: 0, 4: 1},
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x_p, eo_p, y_p, w_p, vw_p, hp)
    if transposed:
        w_new, vw_new = w_new[:n, :f], vw_new[:n, :f]
    else:
        w_new, vw_new = w_new[:f, :n], vw_new[:f, :n]

    if has_bias:
        b_p = _pad_to(b.reshape(1, -1), bn, 1)
        vb_p = _pad_to(vb.reshape(1, -1), bn, 1)
        row = pl.BlockSpec((1, bn), lambda i, kk: (0, i))
        b_new, vb_new = pl.pallas_call(
            functools.partial(_gd_db_kernel, n_k=n_kb,
                              activation=activation),
            grid=(np_ // bn, n_kb),
            in_specs=[
                pl.BlockSpec((bk, bn), lambda i, kk: (kk, i)),
                pl.BlockSpec((bk, bn), lambda i, kk: (kk, i)),
                row, row,
                pl.BlockSpec((1, 128), lambda i, kk: (0, 0)),
            ],
            out_specs=[row, row],
            out_shape=[jax.ShapeDtypeStruct(b_p.shape, b.dtype),
                       jax.ShapeDtypeStruct(vb_p.shape, vb.dtype)],
            scratch_shapes=[pltpu.VMEM((1, bn), jnp.float32)],
            input_output_aliases={2: 0, 3: 1},
            compiler_params=_COMPILER_PARAMS(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(eo_p, y_p, b_p, vb_p, hp)
        b_new = b_new[0, :n].reshape(b.shape)
        vb_new = vb_new[0, :n].reshape(vb.shape)
    else:
        b_new, vb_new = b, vb
    return w_new, b_new, vw_new, vb_new, err_input


def gd_kernel_choice(dtype=jnp.float32, shape=None, db_path=None):
    """Resolve the training-kernel backend for the fused GD stage —
    ``(backend, tiles, interpret)``.

    ``root.common.engine.kernels``: ``xla`` forces the dense reference
    (``_gd_math``); ``pallas`` forces the fused kernels — compiled on
    TPU, interpret-mode Pallas elsewhere (parity/debug; slow); ``auto``
    (default) takes the autotune DB's measured winner on TPU
    (``ops.benchmark.autotune_gd``) and the dense reference elsewhere.
    Runs at stage-build/trace time only, so the DB lookup costs nothing
    per step and the resolved backend never retraces."""
    from veles_tpu.config import root
    from veles_tpu.ops import on_tpu
    mode = str(root.common.engine.get("kernels", "auto") or "auto")
    tpu = on_tpu()
    tiles = None
    if tpu and mode != "xla":
        from veles_tpu.ops.benchmark import gemm_choice
        choice = gemm_choice(dtype, db_path, kernel="gd", shape=shape)
        tiles = tuple(choice[1]) if choice and choice[1] else None
        if mode != "pallas" and (choice is None or choice[0] != "pallas"):
            return "xla", None, False
    elif mode != "pallas":
        return "xla", None, False
    if mode == "xla":
        return "xla", None, False
    return "pallas", tiles, not tpu
