"""Tiled matmul: the MXU workhorse.

Re-designs the reference's ``#define``-specialized GEMM family
(``ocl/matrix_multiplication_begin.cl:1-64``, ``_precise.cl``,
``_subsum.cl``, ``_end.cl``, ``ocl/gemm.cl``; CUDA twins) as ONE Pallas
kernel: a (M/bm, N/bn, K/bk) grid with float32 VMEM accumulation and a
fused epilogue (bias + activation) — the fusion the reference obtained by
textually pasting activation code between ``_begin``/``_end`` includes.

The reference's precision levels (Kahan / multipartial sums,
``config.py:246-249``) map to the accumulator dtype: the MXU natively
accumulates bf16 products in float32, which is *more* precise than the
reference's float32 products + float32 sums, so PRECISION_LEVEL>0 needs no
special kernel on TPU.

``matmul`` carries a custom VJP so ``jax.grad`` differentiates *through*
the Pallas kernel (backward = two more tiled matmuls) — gradient units and
hand-written GD units share one code path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: fallback tiles when neither the caller nor the autotune DB
#: (``ops.benchmark.autotune_gemm`` → ``devices/device_infos.json``)
#: supplies measured ones — MXU-aligned, nothing more
DEFAULT_TILES = (512, 512, 512)   # (bm, bk, bn)


def _precision():
    """Map the reference's precision levels (Kahan/multipartial sums,
    ``config.py:246-249``) onto MXU pass counts for float32 operands:
    0 → DEFAULT (bf16 passes), 1 → HIGH (bf16_3x), 2 → HIGHEST (f32)."""
    from veles_tpu.config import root
    level = root.common.engine.get("precision_level", 0)
    return (jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGH,
            jax.lax.Precision.HIGHEST)[min(int(level), 2)]

_ACTIVATIONS = {
    None: lambda x: x,
    "linear": lambda x: x,
    "tanh": lambda x: jnp.tanh(x * 0.6666) * 1.7159,  # Znicz scaled tanh
    "sigmoid": jax.nn.sigmoid,
    # Znicz smooth ReLU — the clamped log1p form shared with
    # znicz.fused._ACT and the numpy units (the naive log(1+exp(x))
    # overflows to inf past x ≈ 88)
    "relu": lambda x: jnp.log1p(jnp.exp(jnp.minimum(x, 30.0))),
    "strict_relu": lambda x: jnp.maximum(x, 0.0),
}


def _matmul_kernel(a_ref, b_ref, bias_ref, o_ref, acc_ref, *, n_k,
                   activation, has_bias):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32,
                          precision=_precision())

    @pl.when(k == n_k - 1)
    def _epilogue():
        acc = acc_ref[:]
        if has_bias:
            acc = acc + bias_ref[:].astype(jnp.float32)
        acc = _ACTIVATIONS[activation](acc)
        o_ref[:] = acc.astype(o_ref.dtype)


from veles_tpu.ops.util import pad_axis as _pad_to_impl, round_up


def _pad_to(x, mult, axis):
    return _pad_to_impl(x, mult, axis)


@functools.partial(jax.jit, static_argnames=("activation", "tiles",
                                             "out_dtype", "interpret"))
def _matmul_pallas(a, b, bias, activation=None, tiles=None, out_dtype=None,
                   interpret=False):
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    out_dtype = out_dtype or a.dtype
    bm, bk, bn = tiles or DEFAULT_TILES
    bm, bk, bn = min(bm, round_up(m, 8)), min(bk, round_up(k, 128)), \
        min(bn, round_up(n, 128))
    a_p = _pad_to(_pad_to(a, bm, 0), bk, 1)
    b_p = _pad_to(_pad_to(b, bk, 0), bn, 1)
    has_bias = bias is not None
    bias_p = _pad_to(bias.reshape(1, -1), bn, 1) if has_bias \
        else jnp.zeros((1, bn), a.dtype)
    mp, kp = a_p.shape
    np_ = b_p.shape[1]
    n_k = kp // bk
    grid = (mp // bm, np_ // bn, n_k)
    out = pl.pallas_call(
        functools.partial(_matmul_kernel, n_k=n_k, activation=activation,
                          has_bias=has_bias),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a_p, b_p, bias_p)
    return out[:m, :n]


def _matmul_jnp(a, b, bias, activation=None, out_dtype=None):
    out = jnp.dot(a, b, preferred_element_type=jnp.float32,
                  precision=_precision())
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    out = _ACTIVATIONS[activation](out)
    return out.astype(out_dtype or a.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def matmul(a, b, bias=None, activation=None, tiles=None, use_pallas=None):
    """``activation(a @ b + bias)`` with MXU tiling.

    a: (M, K); b: (K, N); bias: (N,) or None.  ``tiles``: (bm, bk, bn)
    from the autotune DB.  ``use_pallas``: force kernel choice (default:
    pallas on TPU, jnp elsewhere).
    """
    return _matmul_fwd(a, b, bias, activation, tiles, use_pallas)[0]


def _dispatch(use_pallas, tiles, dtype, shape=None):
    """(use_pallas_bool, tiles) for this call.  Priority: explicit
    ``use_pallas`` arg > explicit ``root.common.engine.pallas_gemm``
    config > the autotune DB's measured winner for this device
    generation, shape class and precision level
    (``ops.benchmark.gemm_choice``) > XLA.  This runs at
    TRACE time only (jit caches the result), so the DB lookup costs
    nothing per step."""
    from veles_tpu.ops.benchmark import gemm_choice
    choice = None if use_pallas is False else gemm_choice(dtype,
                                                          shape=shape)
    db_tiles = choice[1] if choice else None
    if use_pallas is not None:
        # explicit choice still benefits from measured tiles
        return use_pallas, tiles or db_tiles
    from veles_tpu.config import root
    from veles_tpu.ops import on_tpu
    configured = root.common.engine.get("pallas_gemm", None)
    if configured is not None:
        return bool(configured) and on_tpu(), tiles or db_tiles
    if not on_tpu() or choice is None:
        # no measurement for this generation: XLA's GEMM is the safe
        # default (run scripts/autotune.py on the chip to decide)
        return False, tiles
    return choice[0] == "pallas", tiles or db_tiles


def _matmul_fwd(a, b, bias, activation, tiles, use_pallas):
    pallas, eff_tiles = _dispatch(use_pallas, tiles, a.dtype,
                                  (a.shape[0], a.shape[1], b.shape[1]))
    if pallas:
        from veles_tpu.config import root
        out = _matmul_pallas(
            a, b, bias, activation=activation, tiles=eff_tiles,
            interpret=bool(root.common.engine.get("interpret", False)))
    else:
        out = _matmul_jnp(a, b, bias, activation=activation)
    # linear backward never reads the output — don't pin it in residuals
    saved_out = out if activation not in (None, "linear") else None
    return out, (a, b, bias, saved_out)


def _matmul_bwd(activation, tiles, use_pallas, residuals, g):
    a, b, bias, out = residuals
    g = g.astype(jnp.float32)
    # d(activation) evaluated from the *output* where possible — matches
    # the reference's backward units which consume the forward output
    # (e.g. GDTanh uses y: err *= y*y*(-0.388484177) + 1.14381894).
    if activation in (None, "linear"):
        dact = g
    elif activation == "tanh":
        y = out.astype(jnp.float32)
        dact = g * (y * y * (-0.388484177) + 1.14381894)
    elif activation == "sigmoid":
        y = out.astype(jnp.float32)
        dact = g * y * (1.0 - y)
    elif activation == "relu":
        y = out.astype(jnp.float32)
        dact = g * (1.0 - jnp.exp(-y))
    elif activation == "strict_relu":
        y = out.astype(jnp.float32)
        dact = g * (y > 0.0)
    else:  # pragma: no cover
        raise ValueError(activation)
    dact = dact.astype(a.dtype)
    da = matmul(dact, b.T, None, None, tiles, use_pallas)
    db = matmul(a.T, dact, None, None, tiles, use_pallas)
    dbias = None if bias is None else jnp.sum(dact, axis=0).astype(
        bias.dtype)
    return da.astype(a.dtype), db.astype(b.dtype), dbias


matmul.defvjp(_matmul_fwd, _matmul_bwd)
