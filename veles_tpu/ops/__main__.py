"""``python -m veles_tpu.ops`` — the kernel-family CI smoke.

``--smoke`` (wired into ``scripts/lint.sh``) gates the Pallas kernel
families end to end on any host, TPU or not:

1. **parity oracles** — interpret-mode Pallas vs the dense XLA
   reference for every family shipped by ``veles_tpu.ops``: the fused
   backward-GD kernels (dW+optimizer epilogue / db / dX,
   ``ops.gemm.gd_fused_pallas`` vs ``znicz.gd._gd_math``, every
   activation × both storage layouts), the gather+normalize loader
   head (``ops.gather``), and flash-attention fwd+bwd (the
   ``jax.custom_vjp`` pair vs dense attention under ``jax.grad``);
2. **autotune table round-trip** — a real (toy-shape) ``autotune_gd``
   sweep into a temp DB, read back through ``gemm_choice`` and
   ``gd_kernel_choice``, plus the ``scripts.autotune`` stdout-envelope
   unwrap (``DeviceInfo.load_db``);
3. **prof ledger** — a short stitched training run under
   ``root.common.engine.kernels=pallas`` must finish with ZERO
   steady-state recompiles (the fused kernels are resolved at
   stage-build time, so swapping them in must not perturb the
   one-compile-per-shape contract).
"""

import argparse
import sys

import numpy


def _fail(msg):
    print("ops smoke: FAIL — %s" % msg, file=sys.stderr)
    return 1


def _check_gd_parity():
    import jax.numpy as jnp

    from veles_tpu.ops.gemm import _GD_DERIVS, gd_fused_pallas
    from veles_tpu.znicz.gd import _gd_math

    rng = numpy.random.default_rng(7)
    batch, f, n = 24, 70, 50
    x = jnp.asarray(rng.standard_normal((batch, f)), jnp.float32)
    eo = jnp.asarray(rng.standard_normal((batch, n)), jnp.float32)
    hp = (0.05, 0.05, 0.0005, 0.0, 0.9, 0.9)
    worst = 0.0
    for activation in sorted(_GD_DERIVS, key=str):
        for transposed in (False, True):
            w = jnp.asarray(rng.standard_normal(
                (n, f) if transposed else (f, n)), jnp.float32) * 0.1
            vw = jnp.zeros_like(w)
            b = jnp.asarray(rng.standard_normal((n,)), jnp.float32)
            vb = jnp.zeros_like(b)
            y = jnp.asarray(rng.standard_normal((batch, n)),
                            jnp.float32)
            ref = _gd_math(x, y, eo, w, b, vw, vb, *hp,
                           activation=activation,
                           transposed=transposed)
            got = gd_fused_pallas(x, y, eo, w, b, vw, vb, *hp,
                                  activation=activation,
                                  transposed=transposed,
                                  tiles=(32, 32, 8), interpret=True)
            for name, r, g in zip(("w", "b", "vw", "vb", "err_input"),
                                  ref, got):
                err = float(jnp.max(jnp.abs(r - g)))
                worst = max(worst, err)
                if err > 5e-5:
                    return None, (
                        "fused GD %s mismatch (activation=%s, "
                        "transposed=%s): max |Δ| = %.3e"
                        % (name, activation, transposed, err))
    return worst, None


def _check_gather_parity():
    import jax.numpy as jnp

    from veles_tpu.ops.gather import (
        _gather_norm_jnp, _gather_norm_pallas, _norm_row)

    rng = numpy.random.default_rng(11)
    data = jnp.asarray(rng.integers(0, 256, (37, 5, 3)), jnp.uint8)
    idx = jnp.asarray([3, 36, -1, 0, 17, -1, 9, 2], jnp.int32)
    feat = int(numpy.prod(data.shape[1:]))
    for norm in ((1.0 / 255.0, 0.0),
                 (rng.standard_normal(feat).astype(numpy.float32),
                  rng.standard_normal(feat).astype(numpy.float32))):
        ref = _gather_norm_jnp(data, idx,
                               jnp.asarray(norm[0], jnp.float32),
                               jnp.asarray(norm[1], jnp.float32))
        got = _gather_norm_pallas(data.reshape(data.shape[0], -1),
                                  idx, _norm_row(norm[0], feat),
                                  _norm_row(norm[1], feat),
                                  interpret=True)
        got = got.reshape(ref.shape)
        err = float(jnp.max(jnp.abs(ref - got)))
        if err > 1e-6:
            return None, ("gather+normalize mismatch: max |Δ| = %.3e"
                          % err)
        if float(jnp.max(jnp.abs(got[jnp.asarray([2, 5])]))) != 0.0:
            return None, "gather+normalize: pad rows are not zero"
    return 0.0, None


def _check_attention_parity():
    import jax
    import jax.numpy as jnp

    from veles_tpu.config import root
    from veles_tpu.ops.attention import flash_attention

    rng = numpy.random.default_rng(13)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 128, 2, 32)),
                           jnp.float32) for _ in range(3))
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

    def loss(fn, use_pallas):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v, causal=True, block_q=64,
                              block_k=64, use_pallas=use_pallas) * do)
        return inner

    saved = root.common.engine.get("interpret", False)
    root.common.engine.interpret = True       # Pallas arm -> interpret
    try:
        ref = jax.grad(loss(flash_attention, False),
                       argnums=(0, 1, 2))(q, k, v)
        got = jax.grad(loss(flash_attention, True),
                       argnums=(0, 1, 2))(q, k, v)
        fwd_ref = flash_attention(q, k, v, causal=True,
                                  use_pallas=False)
        fwd_got = flash_attention(q, k, v, causal=True, block_q=64,
                                  block_k=64, use_pallas=True)
    finally:
        root.common.engine.interpret = saved
    worst = float(jnp.max(jnp.abs(fwd_ref - fwd_got)))
    for name, r, g in zip("qkv", ref, got):
        worst = max(worst, float(jnp.max(jnp.abs(r - g))))
    if worst > 5e-5:
        return None, ("flash-attention fwd+bwd mismatch: max |Δ| = "
                      "%.3e" % worst)
    return worst, None


def _check_autotune_roundtrip():
    import json
    import os
    import tempfile

    import jax.numpy as jnp

    from veles_tpu.backends import DeviceInfo
    from veles_tpu.ops.benchmark import autotune_gd, gemm_choice

    path = os.path.join(tempfile.mkdtemp(prefix="veles_ops_smoke_"),
                        "device_infos.json")
    try:
        info = autotune_gd(shape=(16, 128, 64), runs=1, db_path=path)
        if "gd_v2" not in info.ratings or "gd" not in info.ratings:
            return "autotune_gd left no gd_v2/gd ratings"
        gemm_choice.cache_clear()
        choice = gemm_choice(jnp.float32, db_path=path, kernel="gd",
                             shape=(16, 128, 64))
        if choice is None or choice[0] not in ("pallas", "xla"):
            return "gemm_choice(kernel='gd') did not round-trip: %r" \
                % (choice,)
        # the scripts.autotune stdout envelope must load as the flat DB
        with open(path) as fin:
            flat = json.load(fin)
        with open(path, "w") as fout:
            json.dump({"devices": flat, "_this_run": {"ts": 0.0}},
                      fout)
        db = DeviceInfo.load_db(path)
        if "_this_run" in db or not any(
                "gd_v2" in i.ratings for i in db.values()):
            return "load_db did not unwrap the autotune envelope"
        gemm_choice.cache_clear()
        choice2 = gemm_choice(jnp.float32, db_path=path, kernel="gd",
                              shape=(16, 128, 64))
        if choice2 != choice:
            return "enveloped DB changed the gd verdict: %r vs %r" \
                % (choice2, choice)
    finally:
        if os.path.exists(path):
            os.unlink(path)
        os.rmdir(os.path.dirname(path))
        gemm_choice.cache_clear()
    return None


def _check_ledger_zero_recompiles():
    """A short stitched training run with kernels=pallas: the fused-GD
    swap happens at stage-build time, so after the warmup compile the
    ledger must stay recompile-free."""
    from veles_tpu import prof, trace
    from veles_tpu.backends import CPUDevice
    from veles_tpu.config import root
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class SmokeLoader(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.default_rng(3)
            self.original_data.mem = rng.standard_normal(
                (64, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(64)]
            self.class_lengths[:] = [0, 0, 64]

    saved = {k: root.common.engine.get(k, d) for k, d in
             (("kernels", "auto"), ("trace", "off"), ("stitch", "on"))}
    root.common.engine.kernels = "pallas"
    root.common.engine.trace = "on"
    root.common.engine.stitch = "on"
    try:
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: SmokeLoader(w, minibatch_size=16),
            layers=[{"type": "all2all_tanh",
                     "->": {"output_sample_shape": 16},
                     "<-": {"learning_rate": 0.05}},
                    {"type": "softmax",
                     "->": {"output_sample_shape": 4}}],
            decision_config={"max_epochs": 3})
        wf.launcher = DummyLauncher()
        wf.initialize(device=CPUDevice())
        wf.run()
        segments = prof.ledger.entries("segment")
        if not segments:
            return "no stitched segments registered under " \
                "kernels=pallas"
        if prof.ledger.recompiles or prof.flagged:
            return ("%d steady-state recompile(s) under "
                    "kernels=pallas: %r"
                    % (prof.ledger.recompiles, prof.flagged))
    finally:
        for k, val in saved.items():
            setattr(root.common.engine, k, val)
        trace.configure()
    return None


def run_smoke():
    gd_err, msg = _check_gd_parity()
    if msg:
        return _fail(msg)
    _, msg = _check_gather_parity()
    if msg:
        return _fail(msg)
    attn_err, msg = _check_attention_parity()
    if msg:
        return _fail(msg)
    msg = _check_autotune_roundtrip()
    if msg:
        return _fail(msg)
    msg = _check_ledger_zero_recompiles()
    if msg:
        return _fail(msg)
    print("ops smoke: OK — GD parity max |Δ| = %.3e, attention "
          "fwd+bwd max |Δ| = %.3e, gather+normalize exact, gd "
          "autotune table round-trips, 0 recompiles under "
          "kernels=pallas" % (gd_err, attn_err))
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.ops",
        description="Kernel-family utilities; --smoke runs the CI "
                    "parity/autotune/ledger gate.")
    parser.add_argument("--smoke", action="store_true",
                        help="run the kernel CI smoke (interpret-mode "
                             "parity oracles, autotune round-trip, "
                             "zero-recompile ledger gate)")
    args = parser.parse_args(argv)
    if args.smoke:
        return run_smoke()
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
