"""Matrix reduction (ref ``ocl/matrix_reduce.cl:1-69``,
``cuda/matrix_reduce.cu``: an include-style template reducing a matrix
along rows or columns with an ``A_COL`` switch and a ``REDUCE_SIZE``
workgroup tree).

On TPU the VPU's (8, 128) lanes make XLA's own reduction codegen
excellent; the Pallas path exists for the fused cases (reduce of a
function of the input without materializing it) and as the autotune
benchmark's second kernel.  Both paths accumulate in float32.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.util import pad_axis, round_up as _round_up


def matrix_reduce(a, axis=0, op="sum", use_pallas=None):
    """Reduce a 2D matrix along ``axis`` (0: over rows → per-column
    result, like the reference's default; 1: over columns → per-row)."""
    if use_pallas is None:
        from veles_tpu.config import root
        from veles_tpu.ops import on_tpu
        use_pallas = bool(root.common.engine.get("pallas_reduce", False)) \
            and on_tpu()
    if use_pallas:
        from veles_tpu.config import root
        return _reduce_pallas(
            a, axis=axis, op=op,
            interpret=bool(root.common.engine.get("interpret", False)))
    return _reduce_jnp(a, axis=axis, op=op)


def _reduce_jnp(a, axis, op):
    acc = a.astype(jnp.float32)
    fn = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[op]
    return fn(acc, axis=axis).astype(a.dtype)


def _reduce_kernel(a_ref, o_ref, acc_ref, *, n_blocks, axis, op):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _zero():
        if op == "sum":
            acc_ref[:] = jnp.zeros_like(acc_ref)
        elif op == "max":
            acc_ref[:] = jnp.full_like(acc_ref, -jnp.inf)
        else:
            acc_ref[:] = jnp.full_like(acc_ref, jnp.inf)

    block = a_ref[:].astype(jnp.float32)
    if op == "sum":
        acc_ref[:] += jnp.sum(block, axis=axis, keepdims=True)
    elif op == "max":
        acc_ref[:] = jnp.maximum(acc_ref[:],
                                 jnp.max(block, axis=axis, keepdims=True))
    else:
        acc_ref[:] = jnp.minimum(acc_ref[:],
                                 jnp.min(block, axis=axis, keepdims=True))

    @pl.when(i == n_blocks - 1)
    def _done():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("axis", "op", "interpret"))
def _reduce_pallas(a, axis=0, op="sum", interpret=False):
    m, n = a.shape
    if axis == 0:
        # march down the rows in blocks; result (1, n)
        bm = min(512, _round_up(m, 8))
        a_p = _pad_value(a, bm, 0, op)
        n_blocks = a_p.shape[0] // bm
        out = pl.pallas_call(
            functools.partial(_reduce_kernel, n_blocks=n_blocks, axis=0,
                              op=op),
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec((bm, n), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, n), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, n), a.dtype),
            scratch_shapes=[pltpu.VMEM((1, n), jnp.float32)],
            interpret=interpret,
        )(a_p)
        return out[0]
    # axis == 1: march across columns; result (m, 1)
    bn = min(512, _round_up(n, 128))
    a_p = _pad_value(a, bn, 1, op)
    n_blocks = a_p.shape[1] // bn
    out = pl.pallas_call(
        functools.partial(_reduce_kernel, n_blocks=n_blocks, axis=1,
                          op=op),
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec((m, bn), lambda i: (0, i))],
        out_specs=pl.BlockSpec((m, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((m, 1), a.dtype),
        scratch_shapes=[pltpu.VMEM((m, 1), jnp.float32)],
        interpret=interpret,
    )(a_p)
    return out[:, 0]


def _pad_value(a, mult, axis, op):
    value = {"sum": 0.0, "max": -jnp.inf, "min": jnp.inf}[op]
    return pad_axis(a, mult, axis, value=value)
