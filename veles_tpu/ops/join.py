"""Per-sample concatenation of N buffers (ref Jinja2-templated
``ocl/join.jcl:12-39`` / ``cuda/join.jcu``, consumed by ``InputJoiner``
``veles/input_joiner.py:49``).

The reference generates an N-ary kernel signature per arity with Jinja2;
under XLA ``jnp.concatenate`` already emits a single fused copy, so the
"template" collapses to one call.  Inputs are flattened per-sample first
(the reference's kernels operate on flat per-sample offsets).
"""

import jax.numpy as jnp


def join(arrays, axis=1):
    """Concatenate per-sample: each (B, ...) input is flattened to
    (B, -1) then concatenated along features."""
    flat = [a.reshape(a.shape[0], -1) for a in arrays]
    return jnp.concatenate(flat, axis=axis)
