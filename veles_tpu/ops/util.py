"""Shared tiling/padding helpers for the kernel substrate."""

import jax.numpy as jnp


def round_up(x, mult):
    return ((x + mult - 1) // mult) * mult


def pad_axis(a, mult, axis, value=0.0):
    """Pad ``axis`` up to a multiple of ``mult`` with ``value``."""
    size = a.shape[axis]
    rem = size % mult
    if rem == 0:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(a, pad, constant_values=value)
