"""Shared tiling/padding helpers for the kernel substrate."""

import jax.numpy as jnp

#: jax renamed TPUCompilerParams -> CompilerParams across releases;
#: THE one shim every Pallas kernel module resolves (attention and
#: qgemm import it instead of keeping per-module copies)
try:
    from jax.experimental.pallas import tpu as _pltpu
    COMPILER_PARAMS = (getattr(_pltpu, "CompilerParams", None)
                       or getattr(_pltpu, "TPUCompilerParams", None))
except ImportError:        # pragma: no cover - pallas-less jax
    COMPILER_PARAMS = None


def round_up(x, mult):
    return ((x + mult - 1) // mult) * mult


def pad_axis(a, mult, axis, value=0.0):
    """Pad ``axis`` up to a multiple of ``mult`` with ``value``."""
    size = a.shape[axis]
    rem = size % mult
    if rem == 0:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(a, pad, constant_values=value)
