"""Minibatch gather: device-side sample selection by shuffled indices.

Parity target: ``ocl/fullbatch_loader.cl:5-30`` /
``cuda/fullbatch_loader.cu`` — gathers minibatch samples (and labels) from
the device-resident full dataset by an index vector, zero-padding the tail
of a short final batch.

TPU re-design: the jnp path is ``jnp.take`` (XLA emits an efficient
dynamic-gather); the Pallas path uses scalar-prefetched indices as the
BlockSpec index map, so each sample row is DMA'd straight from the
dataset in HBM into the output block — no materialized one-hot, no host
round-trip for the epoch shuffle.
"""

import functools

import jax
import jax.numpy as jnp
import numpy
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def take_rows(data, indices, use_pallas=None):
    """``data[indices]`` along axis 0.  Negative indices (the reference's
    "empty slot" marker for short batches) produce zero rows.

    Backend dispatch (when ``use_pallas`` is None):
    ``root.common.engine.pallas_gather`` (True/False force) → the
    device DB's measured A/B (``autotune_gather``) → the XLA path.
    The compiled Pallas DMA kernel runs on TPU only; a config FORCE
    additionally honors ``engine.interpret`` so CPU tests can pin the
    in-scan composition through the Pallas interpreter."""
    from veles_tpu.config import root   # deferred: import cycle
    auto = use_pallas is None
    if auto:
        from veles_tpu.ops import on_tpu
        forced = root.common.engine.get("pallas_gather", None)
        if isinstance(forced, bool):
            # a forced kernel also honors interpret mode (the Pallas
            # interpreter runs on any backend — how CPU tests pin the
            # in-scan composition the TPU path executes)
            interp = bool(root.common.engine.get("interpret", False))
            use_pallas = forced and (on_tpu() or interp)
            auto = False          # explicit config force: never mask
        else:
            from veles_tpu.ops.benchmark import gather_choice
            f = int(numpy.prod(data.shape[1:])) if data.ndim >= 2 \
                else None
            # the verdict only transfers to the ROW SIZE it was
            # measured at: the kernel's shape support (and its win)
            # is not generic, and a Mosaic rejection of an unmeasured
            # shape would surface at COMPILE time of the enclosing
            # program, far from any fallback
            measured = gather_choice(str(jnp.dtype(data.dtype)),
                                     row_elems=f)
            use_pallas = bool(measured) and on_tpu()
    key = (data.shape[1:], str(jnp.dtype(data.dtype)))
    if use_pallas and data.ndim >= 2 \
            and (not auto or key not in _PALLAS_REJECTED):
        try:
            flat = data.reshape(data.shape[0], -1)
            out = _gather_pallas(
                flat, indices,
                interpret=bool(root.common.engine.get("interpret",
                                                      False)))
            return out.reshape((indices.shape[0],) + data.shape[1:])
        except Exception:
            if not auto:
                raise     # forced callers want the kernel error
            # auto-dispatch degrades to XLA, negative-cached per
            # (row shape, dtype) so the retry cost is paid once
            _PALLAS_REJECTED.add(key)
    return _gather_jnp(data, indices)


def take_rows_norm(data, indices, norm, use_pallas=None):
    """Fused gather + affine normalize: float32
    ``data[indices]*scale + shift`` with negative indices producing
    ZERO rows (masking applies AFTER the normalize, so a short batch's
    padding stays 0 rather than ``shift``).

    This is the fullbatch loader's native-dtype head: the dataset stays
    resident in its storage dtype (e.g. uint8 pixels) and the first
    forward program receives normalized float32 — the gather's DMA and
    the normalizer's multiply-add are one kernel, so the raw bytes are
    read exactly once.  ``norm`` is the loader's affine
    ``(scale, shift)`` pair (``NormalizerBase.as_affine``): scalars or
    flat per-feature arrays.  Dispatch mirrors :func:`take_rows` (the
    gather A/B verdict transfers: the epilogue adds two VPU ops to a
    DMA-bound kernel)."""
    from veles_tpu.config import root   # deferred: import cycle
    scale, shift = norm
    auto = use_pallas is None
    if auto:
        from veles_tpu.ops import on_tpu
        forced = root.common.engine.get("pallas_gather", None)
        if isinstance(forced, bool):
            interp = bool(root.common.engine.get("interpret", False))
            use_pallas = forced and (on_tpu() or interp)
            auto = False
        else:
            from veles_tpu.ops.benchmark import gather_choice
            f = int(numpy.prod(data.shape[1:])) if data.ndim >= 2 \
                else None
            measured = gather_choice(str(jnp.dtype(data.dtype)),
                                     row_elems=f)
            use_pallas = bool(measured) and on_tpu()
    key = ("norm", data.shape[1:], str(jnp.dtype(data.dtype)))
    if use_pallas and data.ndim >= 2 \
            and (not auto or key not in _PALLAS_REJECTED):
        try:
            flat = data.reshape(data.shape[0], -1)
            f = flat.shape[1]
            out = _gather_norm_pallas(
                flat, indices,
                _norm_row(scale, f), _norm_row(shift, f),
                interpret=bool(root.common.engine.get("interpret",
                                                      False)))
            return out.reshape((indices.shape[0],) + data.shape[1:])
        except Exception:
            if not auto:
                raise
            _PALLAS_REJECTED.add(key)
    return _gather_norm_jnp(data, indices,
                            jnp.asarray(scale, jnp.float32),
                            jnp.asarray(shift, jnp.float32))


def _norm_row(v, f):
    """scale/shift as a (1, f) float32 row the kernel broadcasts."""
    v = jnp.asarray(v, jnp.float32)
    return jnp.broadcast_to(v.reshape(1, -1), (1, f))


@jax.jit
def _gather_norm_jnp(data, indices, scale, shift):
    taken = jnp.take(data, jnp.maximum(indices, 0), axis=0)
    flat = taken.reshape(taken.shape[0], -1).astype(jnp.float32)
    normed = (flat * scale.reshape(1, -1)
              + shift.reshape(1, -1)).reshape(taken.shape)
    mask = (indices >= 0).reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(mask, normed, 0.0)


def _gather_norm_kernel(idx_ref, data_ref, scale_ref, shift_ref, o_ref):
    i = pl.program_id(0)
    valid = idx_ref[i] >= 0

    @pl.when(valid)
    def _copy():
        o_ref[:] = (data_ref[:].astype(jnp.float32)
                    * scale_ref[:].reshape(1, 1, -1)
                    + shift_ref[:].reshape(1, 1, -1))

    @pl.when(jnp.logical_not(valid))
    def _zero():
        o_ref[:] = jnp.zeros_like(o_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_norm_pallas(data, indices, scale, shift, interpret=False):
    # same (n, 1, f) / (1, 1, f) block trick as _gather_pallas (block
    # dims equal to array dims sidestep the sublane rule); scale/shift
    # ride as whole-array (1, f) operands every grid point maps to
    n, f = data.shape
    b = indices.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, f), lambda i, idx_ref: (jnp.maximum(
                idx_ref[i], 0), 0, 0)),
            pl.BlockSpec((1, f), lambda i, idx_ref: (0, 0)),
            pl.BlockSpec((1, f), lambda i, idx_ref: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, f), lambda i, idx_ref: (i, 0, 0)),
    )
    out = pl.pallas_call(
        _gather_norm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, f), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(indices, jnp.int32), data.reshape(n, 1, f),
      scale, shift)
    return out.reshape(b, f)


#: (row shape, dtype) pairs the Pallas kernel rejected at trace time
#: this process (auto-dispatch only; forced callers see the error)
_PALLAS_REJECTED = set()


@jax.jit
def _gather_jnp(data, indices):
    # jitted: the eager form is 3 separate op dispatches per minibatch,
    # which a high-latency transport (tunneled PJRT) pays 3 round trips
    # for; one compiled program per (shape, dtype) serves every batch
    taken = jnp.take(data, jnp.maximum(indices, 0), axis=0)
    mask = (indices >= 0).reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(mask, taken, 0)


def _gather_kernel(idx_ref, data_ref, o_ref):
    i = pl.program_id(0)
    valid = idx_ref[i] >= 0

    @pl.when(valid)
    def _copy():
        o_ref[:] = data_ref[:]

    @pl.when(jnp.logical_not(valid))
    def _zero():
        o_ref[:] = jnp.zeros_like(o_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_pallas(data, indices, interpret=False):
    # The Mosaic lowering requires a block's last two dims to be
    # divisible by (8, 128) OR equal to the array's dims.  A (1, f)
    # block over (n, f) fails the sublane rule for any n > 1, so the
    # data rides as (n, 1, f) with (1, 1, f) blocks — both trailing
    # block dims then EQUAL the array dims, with no padding and no
    # copy (the reshape is a view of the same HBM bytes).
    n, f = data.shape
    b = indices.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            # the index map reads the prefetched indices: block row i of
            # the output comes from dataset row indices[i]
            pl.BlockSpec((1, 1, f), lambda i, idx_ref: (jnp.maximum(
                idx_ref[i], 0), 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, f), lambda i, idx_ref: (i, 0, 0)),
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, f), data.dtype),
        interpret=interpret,
    )(jnp.asarray(indices, jnp.int32), data.reshape(n, 1, f))
    return out.reshape(b, f)
