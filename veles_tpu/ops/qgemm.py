"""Quantized (int8-weight) matmul: the serving-side MXU kernel.

The int8 half of the reference's dtype-specialized GEMM family — the
native engine's int8/register-blocking change banked +35% serving
throughput (PAPER.md §L0/L1), and the same headroom exists on-chip:
weights cross the HBM→VMEM boundary at a quarter of the f32 width, so
a weight-bound serving GEMM speeds up with the bytes.

ONE Pallas kernel, the ``ops/gemm.py`` shape discipline verbatim — a
(M/bm, N/bn, K/bk) grid with float32 VMEM accumulation — but the B
operand stays **int8 end to end**: it is DMA'd from HBM as stored (no
dequantized f32 copy ever materializes), widened to the activation
dtype inside VMEM for the MXU pass, and the per-output-channel dequant
(``acc * scale[N]``) is fused into the epilogue together with bias and
activation.  Weight-only quantization: activations stay bf16/f32, so
the numerics are "W8A16" — ``out = act((x @ q) * scale + bias)``.

No custom VJP on purpose: this is a SERVING kernel (deploy-time
quantized params are not trained through), so ``qmatmul`` is a plain
function — gradients through a quantized deploy are a bug, and the
missing VJP makes them a loud one.

The dense-jnp reference path (``_qmatmul_jnp``) is the interpret/CPU
fallback AND the parity oracle: it performs the dot-then-scale in the
same order as the kernel epilogue, so interpret-mode Pallas output is
bitwise-comparable (``tests/test_quant.py``).

Dispatch consults the autotune DB like :func:`veles_tpu.ops.gemm
.matmul` does — ``ratings["gemm_int8"]`` rows written by
``scripts/autotune.py``'s int8 sweep (``--skip-int8`` to omit).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.gemm import _ACTIVATIONS as _GEMM_ACTIVATIONS
from veles_tpu.ops.gemm import _precision
from veles_tpu.ops.util import COMPILER_PARAMS as _COMPILER_PARAMS
from veles_tpu.ops.util import pad_axis as _pad_to, round_up

#: fallback tiles when neither the caller nor the autotune DB supplies
#: measured ones — MXU-aligned; bk is the int8 operand's sublane dim
#: and must stay a multiple of 32 (the int8 (32, 128) register tile)
DEFAULT_TILES = (512, 512, 512)   # (bm, bk, bn)

#: the fused-epilogue activations: the shared gemm table plus gelu —
#: the transformer MLP's up-projection runs ``gelu(x @ w1 + b1)`` in
#: one quantized dispatch
_ACTIVATIONS = dict(_GEMM_ACTIVATIONS)
_ACTIVATIONS["gelu"] = jax.nn.gelu


def _qmatmul_kernel(a_ref, b_ref, scale_ref, bias_ref, o_ref, acc_ref,
                    *, n_k, activation, has_bias):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # the int8 block widens to the ACTIVATION dtype in VMEM — the MXU
    # pass is bf16/f32 like the float kernel; only the HBM traffic and
    # footprint are int8
    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:].astype(a_ref.dtype),
                          preferred_element_type=jnp.float32,
                          precision=_precision())

    @pl.when(k == n_k - 1)
    def _epilogue():
        # dot-then-scale: per-output-channel dequant commutes with the
        # K contraction exactly (scale depends only on the column), so
        # the epilogue pays ONE multiply per output element instead of
        # one per weight — and the dense reference does the same order
        acc = acc_ref[:] * scale_ref[:].astype(jnp.float32)
        if has_bias:
            acc = acc + bias_ref[:].astype(jnp.float32)
        acc = _ACTIVATIONS[activation](acc)
        o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("activation", "tiles",
                                             "out_dtype", "interpret"))
def _qmatmul_pallas(a, q, scale, bias, activation=None, tiles=None,
                    out_dtype=None, interpret=False):
    m, k = a.shape
    k2, n = q.shape
    assert k == k2, (a.shape, q.shape)
    out_dtype = out_dtype or a.dtype
    bm, bk, bn = tiles or DEFAULT_TILES
    # bk is simultaneously a's lane dim (128-aligned) and the int8
    # operand's sublane dim (32-aligned): 128 covers both
    bm, bk, bn = min(bm, round_up(m, 8)), min(bk, round_up(k, 128)), \
        min(bn, round_up(n, 128))
    a_p = _pad_to(_pad_to(a, bm, 0), bk, 1)
    q_p = _pad_to(_pad_to(q, bk, 0), bn, 1)
    scale_p = _pad_to(scale.reshape(1, -1).astype(jnp.float32), bn, 1)
    has_bias = bias is not None
    bias_p = _pad_to(bias.reshape(1, -1), bn, 1) if has_bias \
        else jnp.zeros((1, bn), a.dtype)
    mp, kp = a_p.shape
    np_ = q_p.shape[1]
    n_k = kp // bk
    grid = (mp // bm, np_ // bn, n_k)
    out = pl.pallas_call(
        functools.partial(_qmatmul_kernel, n_k=n_k,
                          activation=activation, has_bias=has_bias),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a_p, q_p, scale_p, bias_p)
    return out[:m, :n]


@functools.partial(jax.jit, static_argnames=("activation",
                                             "out_dtype"))
def _qmatmul_jnp(a, q, scale, bias, activation=None, out_dtype=None):
    """The dense reference: int8 widened to the activation dtype, dot
    with f32 accumulation, then scale/bias/activation in the SAME
    order as the kernel epilogue — the interpret/CPU fallback and the
    parity oracle in one function.  Jitted so XLA applies the same
    mul+add fusion it applies inside the interpret-mode kernel body
    (the single-block bitwise gate would otherwise differ by one ulp
    of fma)."""
    out = jnp.dot(a, q.astype(a.dtype),
                  preferred_element_type=jnp.float32,
                  precision=_precision())
    out = out * scale.reshape(-1).astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    out = _ACTIVATIONS[activation](out)
    return out.astype(out_dtype or a.dtype)


def _dispatch(use_pallas, tiles, dtype, shape=None):
    """(use_pallas_bool, tiles) for this call — the ``ops.gemm``
    priority order: explicit arg > ``root.common.engine.pallas_gemm``
    config > the autotune DB's measured ``gemm_int8`` winner for this
    device generation > XLA (the dense-jnp path).  Runs at trace time
    only."""
    from veles_tpu.ops.benchmark import gemm_choice
    choice = None if use_pallas is False else gemm_choice(
        dtype, kernel="gemm_int8", shape=shape)
    db_tiles = choice[1] if choice else None
    if use_pallas is not None:
        return use_pallas, tiles or db_tiles
    from veles_tpu.config import root
    from veles_tpu.ops import on_tpu
    configured = root.common.engine.get("pallas_gemm", None)
    if configured is not None:
        return bool(configured) and on_tpu(), tiles or db_tiles
    if not on_tpu() or choice is None:
        # no measurement for this generation: the dense path is the
        # safe default (run scripts/autotune.py on the chip to decide)
        return False, tiles
    return choice[0] == "pallas", tiles or db_tiles


def qmatmul(a, q, scale, bias=None, activation=None, tiles=None,
            use_pallas=None, out_dtype=None):
    """``activation((a @ q) * scale + bias)`` with int8 weights.

    a: (M, K) bf16/f32 activations; q: (K, N) **int8** weights as
    stored in HBM; scale: (N,) float32 per-output-channel dequant
    factors; bias: (N,) or None.  ``tiles``: (bm, bk, bn) from the
    autotune DB's ``gemm_int8`` entry.  ``use_pallas``: force the
    kernel choice (default: the DB's measured winner on TPU, dense
    jnp elsewhere).  Serving-only: no VJP is defined — quantized
    params are not trained through.
    """
    pallas, eff_tiles = _dispatch(use_pallas, tiles, a.dtype,
                                  (a.shape[0], a.shape[1], q.shape[1]))
    if pallas:
        from veles_tpu.config import root
        return _qmatmul_pallas(
            a, q, scale, bias, activation=activation, tiles=eff_tiles,
            out_dtype=out_dtype,
            interpret=bool(root.common.engine.get("interpret", False)))
    return _qmatmul_jnp(a, q, scale, bias, activation=activation,
                        out_dtype=out_dtype)
