"""Flash attention: Pallas TPU kernels (forward AND backward) + VJP.

The hot op of the transformer family (SURVEY §5.7 notes attention is
beyond reference parity — this is the TPU build's flagship Pallas
kernel).  Forward is a tiled online-softmax kernel: Q blocks stream
through VMEM while K/V blocks arrive per grid step, so the (Sq, Sk)
score matrix never materializes in HBM.  Backward recomputes
probabilities blockwise from the saved log-sum-exp (the standard
flash-attention trade: extra FLOPs for O(S) memory) via TWO Pallas
kernels — dq streams K blocks per Q block; dk/dv streams Q blocks per
K block — with causal block skipping and swept block sizes
(``flash_attention_bwd_v2`` in the autotune DB); the pre-Pallas
``lax.scan`` fallback (:func:`_bwd_blockwise`) remains the non-TPU
path.  Both directions accept global causal offsets (static for the
offset-0 flagship path, scalar-prefetched when traced) so the kernels
serve as ring-attention hop blocks.

Layouts follow :mod:`veles_tpu.parallel.ring` — tensors are
``(batch, seq, heads, head_dim)`` — so :func:`flash_attention` is a
drop-in for its per-hop block math, composing with ring/Ulysses
sequence parallelism.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

#: jax renamed TPUCompilerParams -> CompilerParams across releases;
#: the decode path resolves whichever this jax ships via the ONE
#: shared shim (the training kernels above predate the rename and
#: keep the new-name spelling)
from veles_tpu.ops.util import COMPILER_PARAMS as _COMPILER_PARAMS


def _round_up(x, mult):
    return (x + mult - 1) // mult * mult


def _bhsd(x, b, h, d, block):
    """(b, s, h, d) → (b·h, s_pad, d_pad) for the kernels' per-(b·h)
    grids.  Each tensor pads to ITS OWN block multiple: padding q and
    k to a common multiple would leave trailing blocks unvisited when
    the smaller block size doesn't divide the padded length.  Shared
    by forward and backward so a padding fix can never apply to one
    side only."""
    x = jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)
    s_pad = _round_up(x.shape[1], block)
    d_pad = _round_up(d, 128)
    return jnp.pad(x, ((0, 0), (0, s_pad - x.shape[1]),
                       (0, d_pad - d)))


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                 acc_ref, m_ref, l_ref, *, n_k, scale, causal,
                 block_q, block_k, seq_k, q_off, k_off):
    """Grid: (batch*heads, q_blocks, k_blocks); K is the arbitrary
    (sequential) dimension; running (acc, m, l) live in VMEM scratch.
    ``q_off``/``k_off``: global positions of element 0 — causal masks
    stay correct when q/k are shards of a longer (ring-distributed)
    sequence; padding masks stay LOCAL."""
    qi = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: skip blocks strictly above the (global) diagonal
    run = True
    if causal:
        run = q_off + qi * block_q + block_q - 1 >= k_off + kk * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale       # (bq, d)
        k = k_ref[0].astype(jnp.float32)               # (bk, d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        mask = k_pos < seq_k                           # key padding
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0)
            mask = mask & (k_off + k_pos <= q_off + q_pos)
        scores = jnp.where(mask, scores, NEG_INF)

        m_prev = m_ref[...]                            # (bq, 1)
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)                    # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)                # (bq, 1)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, d)
        m_ref[...] = m_new

    @pl.when(kk == n_k - 1)
    def _finish():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[...] + jnp.log(l))[:, 0]


def _attn_kernel_dyn(offs_ref, *args, kernel, **kw):
    """Scalar-prefetch wrapper: ring shards pass TRACED global offsets
    (device-index-dependent), which cannot be closure constants — they
    ride in as a prefetched (2,) int32 and the causal block-skip
    becomes a runtime predicate."""
    kernel(*args, q_off=offs_ref[0], k_off=offs_ref[1], **kw)


def _static_offsets(q_offset, k_offset):
    return isinstance(q_offset, int) and isinstance(k_offset, int)


def _dyn_spec(spec):
    """Same block routing, one extra (ignored) scalar-prefetch arg —
    keeps the static and dynamic paths structurally identical."""
    return pl.BlockSpec(
        spec.block_shape,
        lambda a, b_, c, offs, _m=spec.index_map: _m(a, b_, c))


def _flash_fwd(q, k, v, causal=False, block_q=128, block_k=128,
               interpret=False, q_offset=0, k_offset=0):
    """(o, lse); inputs (b, s, h, d) — kernel works per (b·h) slice.
    ``q_offset``/``k_offset``: global causal positions of element 0
    (ring/sequence shards); python ints compile to the static
    block-skip, traced scalars take the scalar-prefetch path."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    bq = min(block_q, _round_up(sq, 8))
    bk = min(block_k, _round_up(sk, 8))

    q3 = _bhsd(q, b, h, d, bq)
    k3, v3 = _bhsd(k, b, h, d, bk), _bhsd(v, b, h, d, bk)
    sq_p, d_p = q3.shape[1], q3.shape[2]
    sk_p = k3.shape[1]
    n_q, n_k = sq_p // bq, sk_p // bk
    grid = (b * h, n_q, n_k)

    in_specs = [
        pl.BlockSpec((1, bq, d_p), lambda bh, qi, kk: (bh, qi, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, qi, kk: (bh, kk, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, qi, kk: (bh, kk, 0)),
    ]
    out_specs = [
        pl.BlockSpec((1, bq, d_p), lambda bh, qi, kk: (bh, qi, 0)),
        pl.BlockSpec((1, bq), lambda bh, qi, kk: (bh, qi)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b * h, sq_p, d_p), q.dtype),
        jax.ShapeDtypeStruct((b * h, sq_p), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((bq, d_p), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
    ]
    params = _COMPILER_PARAMS(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    kw = dict(n_k=n_k, scale=scale, causal=causal, block_q=bq,
              block_k=bk, seq_k=sk)
    if _static_offsets(q_offset, k_offset):
        out, lse = pl.pallas_call(
            functools.partial(_attn_kernel, q_off=q_offset,
                              k_off=k_offset, **kw),
            grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=out_shape, scratch_shapes=scratch,
            compiler_params=params, interpret=interpret,
        )(q3, k3, v3)
    else:
        offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                          jnp.asarray(k_offset, jnp.int32)])
        out, lse = pl.pallas_call(
            functools.partial(_attn_kernel_dyn, kernel=_attn_kernel,
                              **kw),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=grid,
                in_specs=[_dyn_spec(s) for s in in_specs],
                out_specs=[_dyn_spec(s) for s in out_specs],
                scratch_shapes=scratch),
            out_shape=out_shape, compiler_params=params,
            interpret=interpret,
        )(offs, q3, k3, v3)
    out = out[:, :sq, :d].reshape(b, h, sq, d)
    return jnp.moveaxis(out, 1, 2), lse[:, :sq].reshape(b, h, sq)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, n_k, scale, causal, block_q,
                   block_k, seq_k, q_off, k_off):
    """dq: grid (b·h, q_blocks, k_blocks); K sequential; the running
    dq accumulator lives in VMEM scratch (the forward's layout)."""
    qi = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # skip K blocks strictly above the (global) diagonal — the 2x
        # FLOP saving the XLA scan fallback cannot express
        run = q_off + qi * block_q + block_q - 1 >= k_off + kk * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0]                                   # (bq, d) mm dtype
        k = k_ref[0]                                   # (bk, d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        mask = k_pos < seq_k
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0)
            mask = mask & (k_off + k_pos <= q_off + q_pos)
        p = jnp.where(mask, jnp.exp(scores - lse_ref[0][:, None]), 0.0)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        ds = p * (dp - delta_ref[0][:, None]) * scale
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(q.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, d)

    @pl.when(kk == n_k - 1)
    def _finish():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, n_q, scale,
                    causal, block_q, block_k, seq_k, q_off, k_off):
    """dk/dv: grid (b·h, k_blocks, q_blocks); Q sequential; running
    (dk, dv) accumulators in VMEM scratch."""
    kk = pl.program_id(1)
    qj = pl.program_id(2)

    @pl.when(qj == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = q_off + qj * block_q + block_q - 1 >= k_off + kk * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0]                                   # (bq, d)
        k = k_ref[0]                                   # (bk, d)
        do = do_ref[0]                                 # (bq, d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        mask = k_pos < seq_k
        if causal:
            q_pos = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0)
            mask = mask & (k_off + k_pos <= q_off + q_pos)
        p = jnp.where(mask, jnp.exp(scores - lse_ref[0][:, None]), 0.0)
        p_mm = p.astype(q.dtype)
        dv_acc[...] += jax.lax.dot_general(
            p_mm, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        ds = p * (dp - delta_ref[0][:, None]) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bk, d)

    @pl.when(qj == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal=False, block_q=128,
               block_k=128, interpret=False, q_offset=0, k_offset=0,
               delta=None):
    """Pallas flash backward: (dq, dk, dv) from saved (q, k, v, o,
    lse).  Two kernels — dq streams K blocks per Q block; dk/dv
    streams Q blocks per K block — each shaped exactly like the
    forward (VMEM accumulators, per-tensor padding, causal block
    skipping), so the backward's matmuls tile the MXU at the swept
    block sizes instead of the XLA scan fallback's fixed-128 serial
    chain (PROFILE_LM.md: backward 75% of the LM step at 34.6
    TFLOP/s — the round-5 target).  ``lse`` is (b, h, sq)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    bq = min(block_q, _round_up(sq, 8))
    bk = min(block_k, _round_up(sk, 8))

    def bhs(x, block):    # (b, h, s) → (b·h, s_pad)
        x = x.reshape(b * h, x.shape[2]).astype(jnp.float32)
        s_pad = _round_up(x.shape[1], block)
        return jnp.pad(x, ((0, 0), (0, s_pad - x.shape[1])))

    # delta = rowsum(do ⊙ o): one cheap bandwidth-bound pass outside
    # the kernels (the standard flash-backward preprocessing); ring
    # callers precompute it ONCE for all n hops
    if delta is None:
        delta = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                           o.astype(jnp.float32))

    q3 = _bhsd(q, b, h, d, bq)
    k3, v3 = _bhsd(k, b, h, d, bk), _bhsd(v, b, h, d, bk)
    do3 = _bhsd(do.astype(q.dtype), b, h, d, bq)
    lse2, delta2 = bhs(lse, bq), bhs(delta, bq)
    sq_p, d_p = q3.shape[1], q3.shape[2]
    sk_p = k3.shape[1]
    n_q, n_k = sq_p // bq, sk_p // bk

    dq_specs = [
        pl.BlockSpec((1, bq, d_p), lambda bh, qi, kk: (bh, qi, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, qi, kk: (bh, kk, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, qi, kk: (bh, kk, 0)),
        pl.BlockSpec((1, bq, d_p), lambda bh, qi, kk: (bh, qi, 0)),
        pl.BlockSpec((1, bq), lambda bh, qi, kk: (bh, qi)),
        pl.BlockSpec((1, bq), lambda bh, qi, kk: (bh, qi)),
    ]
    dq_out_spec = pl.BlockSpec((1, bq, d_p),
                               lambda bh, qi, kk: (bh, qi, 0))
    dq_out_shape = jax.ShapeDtypeStruct((b * h, sq_p, d_p), q.dtype)
    dq_scratch = [pltpu.VMEM((bq, d_p), jnp.float32)]
    dkv_specs = [
        pl.BlockSpec((1, bq, d_p), lambda bh, kk, qj: (bh, qj, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, kk, qj: (bh, kk, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, kk, qj: (bh, kk, 0)),
        pl.BlockSpec((1, bq, d_p), lambda bh, kk, qj: (bh, qj, 0)),
        pl.BlockSpec((1, bq), lambda bh, kk, qj: (bh, qj)),
        pl.BlockSpec((1, bq), lambda bh, kk, qj: (bh, qj)),
    ]
    dkv_out_specs = [
        pl.BlockSpec((1, bk, d_p), lambda bh, kk, qj: (bh, kk, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, kk, qj: (bh, kk, 0)),
    ]
    dkv_out_shape = [
        jax.ShapeDtypeStruct((b * h, sk_p, d_p), k.dtype),
        jax.ShapeDtypeStruct((b * h, sk_p, d_p), v.dtype),
    ]
    dkv_scratch = [pltpu.VMEM((bk, d_p), jnp.float32),
                   pltpu.VMEM((bk, d_p), jnp.float32)]
    params = _COMPILER_PARAMS(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    dq_kw = dict(n_k=n_k, scale=scale, causal=causal, block_q=bq,
                 block_k=bk, seq_k=sk)
    dkv_kw = dict(n_q=n_q, scale=scale, causal=causal, block_q=bq,
                  block_k=bk, seq_k=sk)
    if _static_offsets(q_offset, k_offset):
        dq3 = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, q_off=q_offset,
                              k_off=k_offset, **dq_kw),
            grid=(b * h, n_q, n_k), in_specs=dq_specs,
            out_specs=dq_out_spec, out_shape=dq_out_shape,
            scratch_shapes=dq_scratch, compiler_params=params,
            interpret=interpret,
        )(q3, k3, v3, do3, lse2, delta2)
        dk3, dv3 = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, q_off=q_offset,
                              k_off=k_offset, **dkv_kw),
            grid=(b * h, n_k, n_q), in_specs=dkv_specs,
            out_specs=dkv_out_specs, out_shape=dkv_out_shape,
            scratch_shapes=dkv_scratch, compiler_params=params,
            interpret=interpret,
        )(q3, k3, v3, do3, lse2, delta2)
    else:
        offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                          jnp.asarray(k_offset, jnp.int32)])
        _dyn = _dyn_spec
        dq3 = pl.pallas_call(
            functools.partial(_attn_kernel_dyn,
                              kernel=_bwd_dq_kernel, **dq_kw),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=(b * h, n_q, n_k),
                in_specs=[_dyn(s) for s in dq_specs],
                out_specs=_dyn(dq_out_spec),
                scratch_shapes=dq_scratch),
            out_shape=dq_out_shape, compiler_params=params,
            interpret=interpret,
        )(offs, q3, k3, v3, do3, lse2, delta2)
        dk3, dv3 = pl.pallas_call(
            functools.partial(_attn_kernel_dyn,
                              kernel=_bwd_dkv_kernel, **dkv_kw),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=(b * h, n_k, n_q),
                in_specs=[_dyn(s) for s in dkv_specs],
                out_specs=[_dyn(s) for s in dkv_out_specs],
                scratch_shapes=dkv_scratch),
            out_shape=dkv_out_shape, compiler_params=params,
            interpret=interpret,
        )(offs, q3, k3, v3, do3, lse2, delta2)

    def unsd(x3, s):      # (b·h, s_pad, d_pad) → (b, s, h, d)
        x = x3[:, :s, :d].reshape(b, h, s, d)
        return jnp.moveaxis(x, 1, 2)

    return unsd(dq3, sq), unsd(dk3, sk), unsd(dv3, sk)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, n_k, scale, block_k,
                   heads, row_step=0):
    """Single-query decode step: grid (batch*heads, k_blocks); K is
    the sequential dimension; the per-row KV length arrives scalar-
    prefetched (``len_ref``, one int32 per *batch* row — heads share
    it).  The q block is the forward kernel's layout padded to the
    8-sublane minimum (row 0 is the real query; rows 1–7 compute
    garbage that is sliced away), so the online-softmax scratch
    discipline is identical to :func:`_attn_kernel`.  K blocks fully
    beyond the row's length are skipped — the decode analogue of the
    causal block skip, and where the win over a dense masked pass
    comes from when the cache is long but the sequence is young.

    ``row_step=1`` is the VERIFY variant (speculative decode): the 8
    q sublanes are CONSECUTIVE positions of one sequence — row ``j``
    writes at ``length - 1 + j`` and may read keys ``< length + j`` —
    so the per-row mask staggers by the sublane index and one dispatch
    prices K+1 draft tokens at one decode step's DMA traffic."""
    bh = pl.program_id(0)
    kk = pl.program_id(1)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[bh // heads]
    run = kk * block_k < length + 7 * row_step

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale       # (8, d)
        k = k_ref[0].astype(jnp.float32)               # (bk, d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (8, bk)
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        limit = length + row_step * jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0)
        scores = jnp.where(k_pos < limit, scores, NEG_INF)
        m_prev = m_ref[...]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(kk == n_k - 1)
    def _finish():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _decode_jnp(q, k, v, lengths):
    """Dense masked reference for the decode step: q (b, 1, h, d)
    against a (b, S, h, d) KV buffer where only the first
    ``lengths[i]`` keys of row ``i`` are valid.  The oracle the Pallas
    kernel is parity-tested against (``tests/test_attention.py``)."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / (d ** 0.5)
    mask = (jnp.arange(k.shape[1])[None, None, None, :]
            < lengths[:, None, None, None])
    scores = jnp.where(mask, scores, NEG_INF)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)
    probs = jnp.exp(scores - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def _decode_pallas(q, k, v, lengths, block_k=128, interpret=False,
                   row_step=0):
    b, sq, h, d = q.shape
    if sq > 8:
        raise ValueError(
            "decode/verify q carries %d rows but the kernel's q tile "
            "is one 8-sublane block — draft_k must stay <= 7" % sq)
    scale = 1.0 / (d ** 0.5)
    sk = k.shape[1]
    bk = min(block_k, _round_up(sk, 8))
    q3 = _bhsd(q, b, h, d, 8)                   # (b·h, 8, d_p)
    k3, v3 = _bhsd(k, b, h, d, bk), _bhsd(v, b, h, d, bk)
    d_p = q3.shape[2]
    n_k = k3.shape[1] // bk
    grid = (b * h, n_k)
    in_specs = [
        pl.BlockSpec((1, 8, d_p), lambda bh, kk, lens: (bh, 0, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, kk, lens: (bh, kk, 0)),
        pl.BlockSpec((1, bk, d_p), lambda bh, kk, lens: (bh, kk, 0)),
    ]
    out_spec = pl.BlockSpec((1, 8, d_p), lambda bh, kk, lens: (bh, 0, 0))
    scratch = [
        pltpu.VMEM((8, d_p), jnp.float32),
        pltpu.VMEM((8, 1), jnp.float32),
        pltpu.VMEM((8, 1), jnp.float32),
    ]
    out = pl.pallas_call(
        functools.partial(_decode_kernel, n_k=n_k, scale=scale,
                          block_k=bk, heads=h, row_step=row_step),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=grid, in_specs=in_specs,
            out_specs=out_spec, scratch_shapes=scratch),
        out_shape=jax.ShapeDtypeStruct((b * h, 8, d_p), q.dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(lengths, jnp.int32), q3, k3, v3)
    return jnp.moveaxis(out[:, :sq, :d].reshape(b, h, sq, d), 1, 2)


def decode_attention(q, k, v, lengths, block_k=None, use_pallas=None,
                     interpret=None):
    """Single-query (q_len = 1) attention against a masked KV buffer —
    the generative decode step's hot op (:mod:`veles_tpu.gen`).

    ``q``: (b, 1, h, d) or (b, h, d); ``k``/``v``: (b, S, h, d) cache
    buffers whose tail beyond ``lengths[i]`` (int32, (b,), each ≥ 1)
    is garbage and masked out; returns attention over the valid prefix
    with q's leading shape.  Row ``i``'s output depends only on row
    ``i``'s query and valid keys, so slots of a continuous batch can
    never bleed into each other (the batching parity gate's
    substrate).  TPU takes the Pallas kernel (lengths scalar-
    prefetched, fully-masked K blocks skipped); elsewhere the dense
    masked reference runs — both share the start-aligned mask
    convention of the prefill flash path (``q_offset``/``k_offset``
    there, ``lengths`` here)."""
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    lengths = jnp.asarray(lengths, jnp.int32)
    pallas = use_pallas if use_pallas is not None else _on_tpu()
    if pallas:
        if interpret is None:
            from veles_tpu.config import root
            interpret = bool(root.common.engine.get("interpret", False))
        out = _decode_pallas(q, k, v, lengths,
                             block_k=block_k or 128,
                             interpret=interpret)
    else:
        out = _decode_jnp(q, k, v, lengths)
    return out[:, 0] if squeeze else out


def _gather_pool(pool, tables):
    """(num_blocks, BS, h, d) pool + (b, max_blocks) int32 tables →
    the (b, max_blocks·BS, h, d) contiguous VIEW of each sequence.
    Table entries past a sequence's allocation point at the trash
    block (id 0), whose garbage lands beyond ``lengths`` and is
    masked — when ``max_blocks·BS`` equals the contiguous engine's
    ``max_seq`` the gathered buffer is value-identical to the
    slot-major cache at every valid position, which is what keeps the
    paged==contiguous parity gate bitwise on the dense path."""
    g = pool[tables]                       # (b, mb, BS, h, d)
    b, mb, bs = g.shape[:3]
    return g.reshape(b, mb * bs, g.shape[3], g.shape[4])


def _paged_decode_jnp(q, k_pool, v_pool, tables, lengths):
    """Dense masked reference for the PAGED decode step: gather the
    block pool through the block tables into the contiguous layout,
    then run the exact :func:`_decode_jnp` math.  The oracle the
    paged Pallas kernel is parity-tested against."""
    return _decode_jnp(q, _gather_pool(k_pool, tables),
                       _gather_pool(v_pool, tables), lengths)


def _paged_decode_kernel(len_ref, tab_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, n_b, scale,
                         block_size, heads, row_step=0):
    """Paged decode step: grid (batch*heads, max_blocks); the KV
    blocks arrive ALREADY ROUTED by the block table — the BlockSpec
    index map reads the scalar-prefetched ``tab_ref`` to aim each
    grid step's DMA at ``tables[row, kk]`` in the shared pool (the
    PagedAttention gather, done by the memory system instead of an
    HBM materialization).  Everything else is :func:`_decode_kernel`:
    per-row lengths scalar-prefetched, online-softmax scratch, and
    blocks fully past the row's length skipped (their table entries
    point at the trash block; the DMA still lands but the compute
    does not run)."""
    bh = pl.program_id(0)
    kk = pl.program_id(1)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[bh // heads]
    run = kk * block_size < length + 7 * row_step

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale       # (8, d)
        k = k_ref[0, 0].astype(jnp.float32)            # (BS, d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (8, BS)
        k_pos = kk * block_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        limit = length + row_step * jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0)
        scores = jnp.where(k_pos < limit, scores, NEG_INF)
        m_prev = m_ref[...]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(kk == n_b - 1)
    def _finish():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pool, v_pool, tables, lengths,
                         interpret=False, row_step=0):
    b, sq, h, d = q.shape
    if sq > 8:
        raise ValueError(
            "decode/verify q carries %d rows but the kernel's q tile "
            "is one 8-sublane block — draft_k must stay <= 7" % sq)
    block_size = k_pool.shape[1]
    if block_size % 8:
        raise ValueError(
            "paged block_size %d breaks the kernel's 8-sublane "
            "padding — use a multiple of 8" % block_size)
    n_b = tables.shape[1]
    scale = 1.0 / (d ** 0.5)
    q3 = _bhsd(q, b, h, d, 8)                   # (b·h, 8, d_p)
    d_p = q3.shape[2]
    # pool → (num_blocks, h, BS, d_p): per-(b·h, block) DMA units
    def pool4(x):
        x = jnp.moveaxis(x, 2, 1)               # (NB, h, BS, d)
        return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, d_p - d)))
    k4, v4 = pool4(k_pool), pool4(v_pool)
    grid = (b * h, n_b)
    in_specs = [
        pl.BlockSpec((1, 8, d_p),
                     lambda bh, kk, lens, tabs: (bh, 0, 0)),
        pl.BlockSpec((1, 1, block_size, d_p),
                     lambda bh, kk, lens, tabs:
                     (tabs[bh // h, kk], bh % h, 0, 0)),
        pl.BlockSpec((1, 1, block_size, d_p),
                     lambda bh, kk, lens, tabs:
                     (tabs[bh // h, kk], bh % h, 0, 0)),
    ]
    out_spec = pl.BlockSpec((1, 8, d_p),
                            lambda bh, kk, lens, tabs: (bh, 0, 0))
    scratch = [
        pltpu.VMEM((8, d_p), jnp.float32),
        pltpu.VMEM((8, 1), jnp.float32),
        pltpu.VMEM((8, 1), jnp.float32),
    ]
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, n_b=n_b, scale=scale,
                          block_size=block_size, heads=h,
                          row_step=row_step),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=grid, in_specs=in_specs,
            out_specs=out_spec, scratch_shapes=scratch),
        out_shape=jax.ShapeDtypeStruct((b * h, 8, d_p), q.dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(lengths, jnp.int32),
      jnp.asarray(tables, jnp.int32), q3, k4, v4)
    return jnp.moveaxis(out[:, :sq, :d].reshape(b, h, sq, d), 1, 2)


def paged_decode_attention(q, k_pool, v_pool, tables, lengths,
                           use_pallas=None, interpret=None):
    """Single-query attention against a PAGED KV pool — the decode hot
    op of ``veles_tpu.gen``'s block-pool cache (ROADMAP item 3a).

    ``q``: (b, 1, h, d) or (b, h, d); ``k_pool``/``v_pool``:
    (num_blocks, block_size, h, d) shared pools; ``tables``: (b,
    max_blocks) int32 — row ``i``'s sequence lives in blocks
    ``tables[i]`` in order, entries past its allocation pointing at
    the trash block 0; ``lengths``: (b,) int32 valid token counts.
    Same row-independence contract as :func:`decode_attention` (the
    continuous-batching parity substrate).  TPU takes the paged
    Pallas kernel — the block table rides in scalar-prefetched and
    routes each K/V block's DMA, so the gather never materializes in
    HBM; elsewhere an XLA gather + the dense masked reference runs,
    value-identical to the contiguous cache path at every valid
    position."""
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    lengths = jnp.asarray(lengths, jnp.int32)
    tables = jnp.asarray(tables, jnp.int32)
    pallas = use_pallas if use_pallas is not None else _on_tpu()
    if pallas:
        if interpret is None:
            from veles_tpu.config import root
            interpret = bool(root.common.engine.get("interpret", False))
        out = _paged_decode_pallas(q, k_pool, v_pool, tables, lengths,
                                   interpret=interpret)
    else:
        out = _paged_decode_jnp(q, k_pool, v_pool, tables, lengths)
    return out[:, 0] if squeeze else out


def chunk_attention(q, k, v, start, use_pallas=None, interpret=None):
    """Causal attention of ONE prefill chunk against the sequence's
    full KV buffer — the chunked-prefill hot op.  ``q``: (1, C, h, d)
    chunk queries whose global positions are ``start + i`` (``start``
    may be a traced int32 — the chunk program stays fixed-shape);
    ``k``/``v``: (1, S, h, d) the sequence's cache buffer (chunk K/V
    already written at [start, start+C)).  Keys at or beyond
    ``start + C`` are hidden by the causal offset mask, so the stale
    tail of the cache can never leak into a chunk.  TPU rides the
    flash kernel's scalar-prefetched q_offset path; elsewhere the
    XLA-fused reference."""
    pallas = _resolve_backend(use_pallas, q.dtype, q.shape)
    if pallas:
        if interpret is None:
            from veles_tpu.config import root
            interpret = bool(root.common.engine.get("interpret", False))
        o, _lse = _flash_fwd(q, k, v, causal=True,
                             q_offset=jnp.asarray(start, jnp.int32),
                             k_offset=jnp.asarray(0, jnp.int32),
                             interpret=interpret)
        return o
    o, _lse = _mha_jnp(q, k, v, True, q_offset=start)
    return o


def _verify_jnp(q, k, v, lengths):
    """Dense masked reference for the K-token VERIFY step
    (speculative decode): q (b, Kp1, h, d) — row ``j`` of sequence
    ``i`` is the query at global position ``lengths[i] - 1 + j`` and
    may read keys ``< lengths[i] + j`` (its own K/V is already
    written, like the decode step's).  Row 0 is EXACTLY the plain
    decode query — same einsum forms and mask arithmetic as
    :func:`_decode_jnp`, the greedy-acceptance equivalence gate's
    substrate."""
    d = q.shape[-1]
    kp1 = q.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / (d ** 0.5)
    limits = (lengths[:, None] + jnp.arange(kp1)[None, :])
    mask = (jnp.arange(k.shape[1])[None, None, None, :]
            < limits[:, None, :, None])
    scores = jnp.where(mask, scores, NEG_INF)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)
    probs = jnp.exp(scores - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def verify_attention(q, k, v, lengths, block_k=None, use_pallas=None,
                     interpret=None):
    """K-token causal verify against a masked KV buffer — the
    speculative-decode hot op (ROADMAP item 3b): ONE dispatch scores
    a slot's current token plus its K draft continuations.

    ``q``: (b, K+1, h, d) — row ``j`` of sequence ``i`` queries from
    global position ``lengths[i] - 1 + j`` (K/V for all K+1 tokens
    already written at [lengths-1, lengths+K)); ``k``/``v``: (b, S,
    h, d) cache buffers; ``lengths``: (b,) int32 — the valid extent
    INCLUDING row 0's token.  Row ``j`` reads keys ``< lengths[i] +
    j``: the same mask plain decode would apply after accepting
    ``j`` drafts, so greedy acceptance over the outputs is an exact
    equivalence with plain decode.  TPU rides the decode kernel with
    the per-sublane staggered mask (``row_step=1``); elsewhere the
    dense masked reference.  K+1 must stay <= 8 (one q sublane
    tile)."""
    lengths = jnp.asarray(lengths, jnp.int32)
    pallas = use_pallas if use_pallas is not None else _on_tpu()
    if pallas:
        if interpret is None:
            from veles_tpu.config import root
            interpret = bool(root.common.engine.get("interpret", False))
        return _decode_pallas(q, k, v, lengths,
                              block_k=block_k or 128,
                              interpret=interpret, row_step=1)
    return _verify_jnp(q, k, v, lengths)


def paged_verify_attention(q, k_pool, v_pool, tables, lengths,
                           use_pallas=None, interpret=None):
    """The PAGED twin of :func:`verify_attention`: same staggered
    per-row mask, KV gathered through the block tables (Pallas: the
    table-routed BlockSpec DMA of the paged decode kernel; elsewhere
    the XLA gather + dense reference).  Draft positions past a
    sequence's allocation route their writes to the trash block
    upstream, so the gathered garbage sits beyond every row's mask."""
    lengths = jnp.asarray(lengths, jnp.int32)
    tables = jnp.asarray(tables, jnp.int32)
    pallas = use_pallas if use_pallas is not None else _on_tpu()
    if pallas:
        if interpret is None:
            from veles_tpu.config import root
            interpret = bool(root.common.engine.get("interpret", False))
        return _paged_decode_pallas(q, k_pool, v_pool, tables, lengths,
                                    interpret=interpret, row_step=1)
    return _verify_jnp(q, _gather_pool(k_pool, tables),
                       _gather_pool(v_pool, tables), lengths)


def _mha_jnp(q, k, v, causal, q_offset=0, k_offset=0):
    """XLA-fused fallback (CPU / tiny shapes); returns (o, lse).
    ``q_offset``/``k_offset``: global causal positions of element 0
    (ring/sequence shards)."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / (d ** 0.5)
    if causal:
        # start-aligned (k_pos <= q_pos) like the Pallas kernel, the
        # blockwise VJP and mha_reference — NOT end-aligned tril
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = (k_offset + jnp.arange(sk))[None, :] <= \
            (q_offset + jnp.arange(sq))[:, None]
        scores = jnp.where(mask, scores, NEG_INF)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)
    probs = jnp.exp(scores - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype), lse


def _bwd_dense_block(q, k_blk, v_blk, lse, do, delta, causal, q_off,
                     k_off):
    """Dense (un-tiled) flash backward of ONE K/V block against the
    GLOBAL (lse, delta): the ring fallback's hop math, kept here next
    to its siblings so the mm-dtype / f32-accumulation conventions
    live in one module.  Returns (dq_blk, dk_blk, dv_blk)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(q.shape[1])[:, None]
        kpos = k_off + jnp.arange(k_blk.shape[1])[None, :]
        p = jnp.where(qpos >= kpos,
                      jnp.exp(scores - lse[..., None]), 0.0)
    else:
        p = jnp.exp(scores - lse[..., None])
    mm = q.dtype
    do_mm = do.astype(mm)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p.astype(mm), do_mm,
                    preferred_element_type=jnp.float32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do_mm, v_blk,
                    preferred_element_type=jnp.float32)
    ds = (p * (dp - delta[..., None]) * scale).astype(mm)
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k_blk,
                    preferred_element_type=jnp.float32)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q,
                    preferred_element_type=jnp.float32)
    return (dq.astype(q.dtype), dk.astype(k_blk.dtype),
            dv.astype(v_blk.dtype))


def _bwd_blockwise(res, do, causal, block_k):
    """Flash backward from saved (q, k, v, o, lse): scan over K blocks,
    probabilities recomputed — O(S·block) memory."""
    q, k, v, o, lse = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    do32 = do.astype(jnp.float32)
    delta = jnp.einsum("bqhd,bqhd->bhq", do32,
                       o.astype(jnp.float32))        # rowsum(do ⊙ o)

    n_blocks = (sk + block_k - 1) // block_k
    sk_pad = n_blocks * block_k
    kp = jnp.pad(k, ((0, 0), (0, sk_pad - sk), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, sk_pad - sk), (0, 0), (0, 0)))

    q_pos = jnp.arange(sq)

    # matmul OPERANDS in the inputs' own dtype (bf16 stays bf16 on the
    # MXU — all-f32 operands force the 3-pass f32 matmul mode, ~3x
    # slower), accumulation in f32 via preferred_element_type; the
    # softmax/rescale arithmetic (exp, lse, delta, ds) stays f32
    mm = q.dtype
    do_mm = do.astype(mm)

    def one_block(carry, idx):
        dq_acc, = carry
        k_blk = jax.lax.dynamic_slice_in_dim(kp, idx * block_k,
                                             block_k, axis=1)
        v_blk = jax.lax.dynamic_slice_in_dim(vp, idx * block_k,
                                             block_k, axis=1)
        k_pos = idx * block_k + jnp.arange(block_k)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        mask = (k_pos < sk)[None, None, None, :]
        if causal:
            mask = mask & (k_pos[None, None, None, :]
                           <= q_pos[None, None, :, None])
        p = jnp.where(mask, jnp.exp(scores - lse[..., None]), 0.0)
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p.astype(mm), do_mm,
                            preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do_mm, v_blk,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        ds_mm = ds.astype(mm)
        dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds_mm, k_blk,
                            preferred_element_type=jnp.float32)
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds_mm, q,
                            preferred_element_type=jnp.float32)
        return (dq_acc + dq_blk,), (dk_blk, dv_blk)

    (dq,), (dk_blocks, dv_blocks) = jax.lax.scan(
        one_block, (jnp.zeros(q.shape, jnp.float32),),
        jnp.arange(n_blocks))
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(b, sk_pad, h, d)[:, :sk]
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(b, sk_pad, h, d)[:, :sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, block_q=None, block_k=None,
                    use_pallas=None):
    """Tiled attention ``softmax(q·kᵀ/√d)·v`` over (b, s, h, d) tensors.

    ``block_q``/``block_k`` default to the autotune DB's measured blocks
    for this device generation (``ops.benchmark.gemm_choice`` with
    kernel="flash_attention"), falling back to 128.  ``use_pallas``:
    force the kernel choice; default auto — the Pallas kernel on TPU,
    the XLA-fused fallback elsewhere.
    """
    o, _lse = _fwd_impl(q, k, v, causal, block_q, block_k, use_pallas)
    return o


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _db_choice(dtype, shape=None, kernel="flash_attention"):
    try:
        from veles_tpu.ops.benchmark import gemm_choice
        return gemm_choice(dtype, kernel=kernel, shape=shape)
    except Exception:
        return None


def _resolve_blocks(block_q, block_k, dtype, shape=None):
    """Caller-supplied blocks win; else the autotune DB's measured
    blocks for this device generation — routed by the actual
    (b, s, h, d) onto the measured sequence-regime classes
    (``flash_attention_v2``); else 128s.  Trace-time only."""
    if block_q is None or block_k is None:
        choice = _db_choice(dtype, shape)
        db = choice[1] if choice else None
        if db:
            block_q = block_q or int(db[0])
            block_k = block_k or int(db[1])
    return block_q or 128, block_k or 128


def _resolve_backend(use_pallas, dtype, shape=None):
    """Explicit arg > the autotune DB's measured winner for this
    device generation (per sequence regime) > Pallas-on-TPU default."""
    if use_pallas is not None:
        return use_pallas
    if not _on_tpu():
        return False
    choice = _db_choice(dtype, shape)
    if choice is not None:
        return choice[0] == "pallas"
    return True


def _fwd_impl(q, k, v, causal, block_q, block_k, use_pallas):
    block_q, block_k = _resolve_blocks(block_q, block_k, q.dtype,
                                       q.shape)
    pallas = _resolve_backend(use_pallas, q.dtype, q.shape)
    if pallas:
        from veles_tpu.config import root
        o, lse = _flash_fwd(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=bool(root.common.engine.get("interpret", False)))
        return o, lse
    return _mha_jnp(q, k, v, causal)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, use_pallas):
    o, lse = _fwd_impl(q, k, v, causal, block_q, block_k, use_pallas)
    # backward expects lse as (b, h, s)
    return o, (q, k, v, o, lse)


def _resolve_bwd(block_q, block_k, use_pallas, dtype, shape):
    """Backward backend + blocks: explicit arg > the DB's measured
    ``flash_attention_bwd`` winner > the forward's choice (the
    backward kernels share the forward's tiling structure, so its
    measured blocks are the best available prior) > Pallas-on-TPU."""
    choice = _db_choice(dtype, shape, kernel="flash_attention_bwd")
    if choice is None:
        choice = _db_choice(dtype, shape)
    if use_pallas is None:
        pallas = _on_tpu() if choice is None \
            else (choice[0] == "pallas" and _on_tpu())
    else:
        pallas = use_pallas
    if block_q is None or block_k is None:
        db = choice[1] if choice else None
        if db:
            block_q = block_q or int(db[0])
            block_k = block_k or int(db[1])
    return pallas, block_q or 128, block_k or 128


def _flash_vjp_bwd(causal, block_q, block_k, use_pallas, res, do):
    pallas, block_q, block_k = _resolve_bwd(
        block_q, block_k, use_pallas, res[0].dtype, res[0].shape)
    if pallas:
        from veles_tpu.config import root
        q, k, v, o, lse = res
        return _flash_bwd(
            q, k, v, o, lse, do, causal=causal, block_q=block_q,
            block_k=block_k,
            interpret=bool(root.common.engine.get("interpret", False)))
    return _bwd_blockwise(res, do, causal, block_k)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)
