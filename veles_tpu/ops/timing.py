"""Trustworthy on-device timing.

Round-2 post-mortem: through some PJRT transports (e.g. a tunneled
remote-TPU plugin) ``jax.block_until_ready`` returns as soon as the
*dispatch* is acknowledged, not when execution finishes — timing with it
measures dispatch latency and produced physically impossible MFU > 1
numbers.  Rules enforced here:

1. **Synchronize by fetching real bytes.**  ``host_fetch`` does a
   ``jax.device_get`` of a small array *derived from the result* — the
   D2H copy cannot complete before the producing program does, whatever
   the transport claims about readiness.
2. **Amortize the round trip inside the program.**  ``make_multi_step``
   loops K train steps inside ONE jitted program via ``lax.fori_loop``,
   threading the params carry, and returns a probe vector that depends
   on both the final metric and the final params — so the fetched bytes
   prove the whole chain executed.
3. **Cancel fixed overhead exactly.**  ``marginal_time`` times the work
   at two different call counts and reports the *marginal* seconds per
   call; the constant dispatch+fetch overhead (~tens of ms over a
   tunnel) subtracts out instead of inflating short measurements.

Reference discipline: the in-situ device benchmark
``/root/reference/veles/accelerated_units.py:706-825`` (min-of-N timed
kernel chain) and the ``--sync-run`` timing-accuracy note
(``accelerated_units.py:294-297``).
"""

import time

import jax
import jax.numpy as jnp
import numpy


def host_fetch(x):
    """Force true device synchronization by copying ``x``'s bytes to the
    host.  Unlike ``block_until_ready`` this cannot be acked early: the
    returned numpy values physically cannot exist before the program
    that produces them has run."""
    return numpy.asarray(jax.device_get(x))


def _first_scalar(tree):
    """A float32 scalar depending on the first array leaf of ``tree``."""
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = jnp.asarray(leaf)
        return arr.astype(jnp.float32).ravel()[0]
    return jnp.float32(0.0)


def probe_of(params, metric):
    """A small vector whose bytes depend on the final params AND the
    final metric — stacked (not summed-with-*0, which an optimizer could
    fold away) so neither dependency can be eliminated."""
    return jnp.stack([_first_scalar(metric), _first_scalar(params)])


def cost_flops(compiled):
    """Total FLOPs of a compiled executable per XLA's own cost
    analysis, or None when unavailable."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def make_multi_step(step_fn, k=None):
    """Wrap ``step_fn(params, x, labels) -> (params, metric)`` into a
    function running several steps inside one XLA program.

    With ``k`` given, the trip count is baked in.  Without it, the
    wrapper takes a fourth *runtime* ``n_steps`` argument, so ONE
    compiled program can be timed at two different step counts — the
    basis of :func:`measure_fused_step`'s in-program marginal timing.

    The first step runs inline (establishing the carry structure, since
    the metric pytree's shapes/dtypes are only known by tracing one
    step); the rest run under ``lax.fori_loop``.  Returns
    ``(params, probe)`` with ``probe`` from :func:`probe_of`.
    """
    if k is not None and k < 1:
        raise ValueError("k must be >= 1, got %d" % k)

    def multi(params, x, labels, *n_steps):
        carry = step_fn(params, x, labels)

        def body(_i, carry):
            p, _m = carry
            return step_fn(p, x, labels)

        hi = (k - 1) if k is not None else n_steps[0] - 1
        params, metric = jax.lax.fori_loop(0, hi, body, carry)
        return params, probe_of(params, metric)

    return multi


def inprogram_marginal(unit_fn, init_carry, k1=8, k2=64, repeats=3,
                       max_retries=2, target_signal=0.25, max_k=100000,
                       stats=None):
    """Marginal seconds per ``unit_fn`` application, measured INSIDE one
    XLA program.

    ``unit_fn(carry) -> carry`` is looped ``n`` times under
    ``lax.fori_loop`` with ``n`` a *runtime* argument, so ONE compiled
    executable is timed at two trip counts and the marginal
    ``(t(k2) - t(k1)) / (k2 - k1)`` cancels the per-program
    dispatch + fetch overhead exactly.  This is the only timing shape
    that survives the tunneled-PJRT transport: timing across program
    launches (even with async dispatch and marginal correction) was
    measured reading ~11 % *above* chip peak — see round-3 notes —
    while the in-program marginal lands at 98 % of peak on the same
    workload.

    Sync per measurement is a host fetch of a carry-derived scalar
    (:func:`host_fetch` — real bytes, cannot be acked early).

    The trip count is a runtime argument, so after a rough first
    marginal the long point is widened (no recompile) until the timing
    signal ``(k2 - k1) * marginal`` reaches ``target_signal`` seconds —
    tiny units (a 1024³ matmul is ~20 µs) would otherwise drown in the
    multi-ms tunnel jitter.
    """
    if not k2 > k1 >= 1:
        raise ValueError("need k2 > k1 >= 1, got %r %r" % (k1, k2))

    def prog(carry, n):
        carry = jax.lax.fori_loop(0, n, lambda _i, c: unit_fn(c), carry)
        return _first_scalar(carry)

    # device-resident carry: host numpy leaves would be re-uploaded on
    # every timed launch (see measure_fused_step's identical guard)
    init_carry = jax.device_put(init_carry)
    compiled = jax.jit(prog).lower(
        init_carry, numpy.int32(k1)).compile()
    host_fetch(compiled(init_carry, jax.device_put(numpy.int32(k2))))

    def timed(n):
        best = float("inf")
        arg = jax.device_put(numpy.int32(n))
        for _ in range(repeats):
            tic = time.perf_counter()
            host_fetch(compiled(init_carry, arg))
            best = min(best, time.perf_counter() - tic)
        return best

    return _two_point_marginal(timed, k1, k2, target_signal, max_k,
                               attempts=max_retries + 2,
                               label="inprogram_marginal", stats=stats)


def _two_point_marginal(timed, k1, k2, target_signal, max_k,
                        attempts=4, label="two_point_marginal",
                        stats=None):
    """Shared widen/retry core of the two-trip-count stopwatch.

    ``timed(n)`` = best-of-repeats wall seconds of ONE program doing
    ``n`` work units.  Widens ``k2`` (no recompile — the trip count is
    a runtime arg) until the signal ``(k2 - k1) * marginal`` reaches
    ``target_signal``; doubles it when noise swamps the gap.  A
    ``FloatingPointError`` from a widened run (weights gone non-finite
    at the longer horizon) falls back to the last positive marginal,
    which is still a valid measurement.

    The short point anchors EVERY marginal, so it is sampled twice up
    front, re-timed on every retry, and always taken as the min — one
    transient transport stall in a single ``t1`` sample would
    otherwise skew all subsequent marginals (round-4 hardening).

    ``stats``, when a dict, receives the measurement's provenance:
    final ``k1/k2/t1/t2/marginal``, ``t1_samples`` count, and
    ``t1_rel_spread`` = (max−min)/min over the short-point samples — a
    noise signature persisted next to DB ratings so stale/noisy
    entries are detectable."""
    best = None
    best_pt = None          # the exact (t1, t2, k2) that produced best
    t1_samples = [timed(k1), timed(k1)]

    def _record(marginal, pt):
        if stats is not None:
            t1_used, t2_used, k2_used = pt
            lo, hi = min(t1_samples), max(t1_samples)
            stats.update({
                "k1": k1, "k2": k2_used, "t1": t1_used, "t2": t2_used,
                "t1_samples": len(t1_samples),
                "t1_rel_spread": ((hi - lo) / lo) if lo > 0 else None,
                "marginal": marginal})
        return marginal

    for _attempt in range(attempts):
        try:
            if _attempt:
                # paranoid short point: re-time on retry, min wins
                t1_samples.append(timed(k1))
            t1 = min(t1_samples)
            t2 = timed(k2)
        except FloatingPointError:
            # weights gone non-finite at a longer horizon (either
            # point): the last positive marginal is still valid
            if best is not None:
                return _record(best, best_pt)
            raise
        marginal = (t2 - t1) / (k2 - k1)
        if marginal > 0:
            best, best_pt = marginal, (t1, t2, k2)
            if (k2 - k1) * marginal >= target_signal or k2 >= max_k:
                return _record(marginal, best_pt)
            k2 = min(k1 + int(numpy.ceil(target_signal / marginal)),
                     max_k)
        else:
            k2 = min(k2 * 2, max_k)   # noise swamped the gap — widen it
    if best is not None:
        return _record(best, best_pt)
    raise RuntimeError(
        "%s: non-positive marginal (%.6fs at k2=%d) — timing "
        "environment too noisy" % (label, marginal, k2))


def marginal_time(call, min_seconds=2.0, max_calls=10000):
    """Marginal seconds per ``call()``.

    ``call`` must dispatch the work asynchronously; ``call(sync=True)``
    must additionally block until everything dispatched so far has
    truly finished (host fetch).  Times ``n1`` calls and ``n2 > n1``
    calls (scaled so the long run spans ``min_seconds``) and returns
    ``(t2 - t1) / (n2 - n1)`` — the fixed per-measurement overhead
    cancels.
    """
    call(sync=True)                      # warm (compile paths already hot)

    def run(n):
        tic = time.perf_counter()
        for _ in range(n - 1):
            call()
        call(sync=True)
        return time.perf_counter() - tic

    n1 = 1
    for attempt in range(3):
        t1 = run(n1)
        per = max(t1 / n1, 1e-9)
        n2 = int(min(max(n1 * 2, min_seconds / per), max_calls))
        t2 = run(n2)
        marginal = (t2 - t1) / (n2 - n1)
        if marginal > 0:
            return marginal
        # t1 noise exceeded t2 — a failed measurement, never a result
        # (clamping here once published 1.8e21 GFLOPs downstream);
        # lengthen the long run and retry
        min_seconds *= 2.0
    raise RuntimeError(
        "marginal_time: non-positive marginal (%.6fs over %d calls) "
        "after 3 attempts — timing environment too noisy" % (
            marginal, n2 - n1))


def measure_fused_step(step_fn, params, x, labels, k=20,
                       min_seconds=None, donate=False, repeats=3,
                       flops_override=None, stats=None):
    """Measure honest seconds per single ``step_fn`` application.

    ONE program loops the step with a *runtime* trip count
    (:func:`make_multi_step` with ``k=None``); it is timed at trip
    counts ``k1 = max(1, k // 4)`` and ``k2 = k`` and the marginal
    ``(t2 - t1) / (k2 - k1)`` is the per-step time — the per-program
    dispatch/fetch overhead of the tunneled transport cancels exactly
    (timing across program launches measured ~11 % above chip peak;
    see ``inprogram_marginal``).  Sync is a host fetch of a
    result-derived probe; non-finite probes abort the measurement.

    Returns ``(sec_per_step, flops_per_step)``.  ``flops_per_step`` is
    XLA's cost analysis of the loop program divided by 2: XLA counts a
    while-loop body ONCE regardless of trip count, so the program's
    total is the inline first step + the body = exactly two steps'
    FLOPs (dividing by K, as before round 3, under-reported FLOPs — and
    MFU — by ~K/2×).

    CAVEAT: the same counted-once rule applies to loops INSIDE the
    step.  A step containing an inner ``lax.scan``/``while_loop`` (an
    LSTM's T-step sequence scan, the grad-accum microbatch scan) has
    its inner body counted once, so cost-analysis FLOPs — and the MFU
    derived from them — underreport by roughly the inner trip count.
    For such steps pass ``flops_override`` with an analytic per-step
    count (e.g. :func:`veles_tpu.znicz.rnn.lstm_train_flops`); it is
    returned as ``flops_per_step`` in place of the cost-analysis value.
    ``min_seconds`` is accepted for backward compatibility and ignored:
    the two-trip-count marginal replaces wall-clock budgeting.
    """
    if donate:
        raise ValueError(
            "measure_fused_step: donation is incompatible with the "
            "two-trip-count timing, which re-runs the program from the "
            "same params buffers; pass donate=False")
    k = max(int(k), 2)
    # Pin every operand on device BEFORE timing: host-resident numpy
    # params (lower_specs returns them) would otherwise be re-uploaded
    # on EVERY timed launch — ~0.5 GB/launch for AlexNet over the
    # tunneled transport, whose multi-second transfer jitter swamps the
    # two-point marginal (r4 window 3: bench said 141 ms/step while the
    # device_put-ing profiler measured the same step at 20.6 ms).
    params, x, labels = jax.device_put((params, x, labels))
    multi = make_multi_step(step_fn)          # dynamic trip count
    jitted = jax.jit(multi)
    compiled = jitted.lower(params, x, labels,
                            numpy.int32(k)).compile()
    if flops_override:
        flops = float(flops_override)
    else:
        total = cost_flops(compiled)
        flops = (total / 2.0) if total else None

    k1, k2 = max(1, k // 4), k

    def timed(n):
        best = float("inf")
        arg = jax.device_put(numpy.int32(n))
        for _ in range(repeats):
            tic = time.perf_counter()
            _p, probe = compiled(params, x, labels, arg)
            vals = host_fetch(probe)
            elapsed = time.perf_counter() - tic
            if not numpy.all(numpy.isfinite(vals)):
                raise FloatingPointError(
                    "non-finite probe during timing: %r" % (vals,))
            best = min(best, elapsed)
        return best

    host_fetch(compiled(params, x, labels,
                        jax.device_put(numpy.int32(k1)))[1])     # warm
    # 0.5 s of signal over the tunnel jitter; widening capped at 20·k
    # steps (more steps = more weight drift on synthetic data = NaN
    # risk, which _two_point_marginal absorbs by falling back)
    marginal = _two_point_marginal(timed, k1, k2, target_signal=0.5,
                                   max_k=max(k2, 20 * k),
                                   label="measure_fused_step",
                                   stats=stats)
    return marginal, flops
