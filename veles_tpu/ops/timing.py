"""Trustworthy on-device timing.

Round-2 post-mortem: through some PJRT transports (e.g. a tunneled
remote-TPU plugin) ``jax.block_until_ready`` returns as soon as the
*dispatch* is acknowledged, not when execution finishes — timing with it
measures dispatch latency and produced physically impossible MFU > 1
numbers.  Rules enforced here:

1. **Synchronize by fetching real bytes.**  ``host_fetch`` does a
   ``jax.device_get`` of a small array *derived from the result* — the
   D2H copy cannot complete before the producing program does, whatever
   the transport claims about readiness.
2. **Amortize the round trip inside the program.**  ``make_multi_step``
   loops K train steps inside ONE jitted program via ``lax.fori_loop``,
   threading the params carry, and returns a probe vector that depends
   on both the final metric and the final params — so the fetched bytes
   prove the whole chain executed.
3. **Cancel fixed overhead exactly.**  ``marginal_time`` times the work
   at two different call counts and reports the *marginal* seconds per
   call; the constant dispatch+fetch overhead (~tens of ms over a
   tunnel) subtracts out instead of inflating short measurements.

Reference discipline: the in-situ device benchmark
``/root/reference/veles/accelerated_units.py:706-825`` (min-of-N timed
kernel chain) and the ``--sync-run`` timing-accuracy note
(``accelerated_units.py:294-297``).
"""

import time

import jax
import jax.numpy as jnp
import numpy


def host_fetch(x):
    """Force true device synchronization by copying ``x``'s bytes to the
    host.  Unlike ``block_until_ready`` this cannot be acked early: the
    returned numpy values physically cannot exist before the program
    that produces them has run."""
    return numpy.asarray(jax.device_get(x))


def _first_scalar(tree):
    """A float32 scalar depending on the first array leaf of ``tree``."""
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = jnp.asarray(leaf)
        return arr.astype(jnp.float32).ravel()[0]
    return jnp.float32(0.0)


def probe_of(params, metric):
    """A small vector whose bytes depend on the final params AND the
    final metric — stacked (not summed-with-*0, which an optimizer could
    fold away) so neither dependency can be eliminated."""
    return jnp.stack([_first_scalar(metric), _first_scalar(params)])


def cost_flops(compiled):
    """Total FLOPs of a compiled executable per XLA's own cost
    analysis, or None when unavailable."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def make_multi_step(step_fn, k):
    """Wrap ``step_fn(params, x, labels) -> (params, metric)`` into a
    function running ``k`` steps inside one XLA program.

    The first step runs inline (establishing the carry structure, since
    the metric pytree's shapes/dtypes are only known by tracing one
    step); the remaining ``k-1`` run under ``lax.fori_loop``.  Returns
    ``(params, probe)`` with ``probe`` from :func:`probe_of`.
    """
    if k < 1:
        raise ValueError("k must be >= 1, got %d" % k)

    def multi(params, x, labels):
        carry = step_fn(params, x, labels)

        def body(_i, carry):
            p, _m = carry
            return step_fn(p, x, labels)

        params, metric = jax.lax.fori_loop(0, k - 1, body, carry)
        return params, probe_of(params, metric)

    return multi


def marginal_time(call, min_seconds=2.0, max_calls=10000):
    """Marginal seconds per ``call()``.

    ``call`` must dispatch the work asynchronously; ``call(sync=True)``
    must additionally block until everything dispatched so far has
    truly finished (host fetch).  Times ``n1`` calls and ``n2 > n1``
    calls (scaled so the long run spans ``min_seconds``) and returns
    ``(t2 - t1) / (n2 - n1)`` — the fixed per-measurement overhead
    cancels.
    """
    call(sync=True)                      # warm (compile paths already hot)

    def run(n):
        tic = time.perf_counter()
        for _ in range(n - 1):
            call()
        call(sync=True)
        return time.perf_counter() - tic

    n1 = 1
    for attempt in range(3):
        t1 = run(n1)
        per = max(t1 / n1, 1e-9)
        n2 = int(min(max(n1 * 2, min_seconds / per), max_calls))
        t2 = run(n2)
        marginal = (t2 - t1) / (n2 - n1)
        if marginal > 0:
            return marginal
        # t1 noise exceeded t2 — a failed measurement, never a result
        # (clamping here once published 1.8e21 GFLOPs downstream);
        # lengthen the long run and retry
        min_seconds *= 2.0
    raise RuntimeError(
        "marginal_time: non-positive marginal (%.6fs over %d calls) "
        "after 3 attempts — timing environment too noisy" % (
            marginal, n2 - n1))


def measure_fused_step(step_fn, params, x, labels, k=20, min_seconds=2.0,
                       donate=True):
    """Compile a K-step loop of ``step_fn`` once and measure honest
    seconds per single step.

    Returns ``(sec_per_step, flops_per_step)``; ``flops_per_step`` is
    XLA's own cost analysis of the K-step program divided by K (None if
    unavailable).
    """
    multi = make_multi_step(step_fn, k)
    jitted = jax.jit(multi, donate_argnums=(0,) if donate else ())
    compiled = jitted.lower(params, x, labels).compile()
    total = cost_flops(compiled)
    flops = (total / k) if total else None

    state = {"params": params}

    def call(sync=False):
        state["params"], probe = compiled(state["params"], x, labels)
        if sync:
            vals = host_fetch(probe)
            if not numpy.all(numpy.isfinite(vals)):
                raise FloatingPointError(
                    "non-finite probe during timing: %r" % (vals,))

    sec_per_call = marginal_time(call, min_seconds=min_seconds)
    return sec_per_call / k, flops
