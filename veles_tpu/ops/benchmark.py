"""Device rating + Pallas tile autotuner.

Parity target: the reference's in-situ benchmark — 13 chained 4096×4096
matmuls, min of 3 runs, producing the "computing power" rating used for
master-side load balancing (``ocl/benchmark.cl:1-11``,
``DeviceBenchmark`` ``accelerated_units.py:706-825``,
``workflow.py:618-624``) — and the OpenCL block-size autotune that fills
``devices/device_infos.json`` (``backends.py:623-744``).

TPU re-design: the same chained-matmul rating (so powers are comparable
across the fleet for job balancing) plus a tile search over the Pallas
GEMM, persisted in the same DB schema.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.backends import DEVICE_INFOS_JSON, DeviceInfo
from veles_tpu.ops.gemm import matmul
from veles_tpu.ops.timing import host_fetch, marginal_time

BENCH_SIZE = 4096
BENCH_CHAIN = 13

#: candidate (bm, bk, bn) tiles — MXU-aligned sweep
TILE_CANDIDATES = (
    (128, 128, 128),
    (128, 256, 128),
    (256, 256, 256),
    (256, 512, 256),
    (512, 512, 512),
    (512, 1024, 256),
    (256, 1024, 512),
)


def estimate_device_power(device=None, size=BENCH_SIZE, chain=BENCH_CHAIN,
                          runs=3, dtype=jnp.bfloat16, use_pallas=None,
                          min_seconds=0.5):
    """Marginal wall time of ``chain`` chained size² matmuls (min of
    ``runs`` measurements) → (seconds, gflops) — the "computing power"
    number (ref ``workflow.py:618-624``).

    Timing honesty (round-2 post-mortem, see ``ops/timing.py``): the
    chain returns a scalar probe, sync is a host fetch of its bytes, and
    the reported time is the *marginal* cost per chain call so dispatch
    and fetch overhead cancel instead of dominating."""
    key = jax.random.key(0)
    a = jax.random.normal(key, (size, size), jnp.float32).astype(dtype)
    b = jnp.eye(size, dtype=dtype) * 1.0001

    def chained(x, w):
        for _ in range(chain):
            x = matmul(x, w, use_pallas=use_pallas)
        # full matrix stays a program output so XLA cannot sink a
        # scalar slice through the dot chain and elide the work being
        # timed; only the probe's bytes cross to the host
        return x, x[0, 0].astype(jnp.float32)

    fn = jax.jit(chained)
    host_fetch(fn(a, b)[1])              # compile + warm

    def call(sync=False):
        _out, probe = fn(a, b)
        if sync:
            host_fetch(probe)

    best = min(marginal_time(call, min_seconds=min_seconds)
               for _ in range(max(runs, 1)))
    gflops = 2.0 * chain * size ** 3 / best / 1e9
    return best, gflops


def autotune_gemm(shapes=((4096, 4096, 4096),), dtypes=("bfloat16",
                                                        "float32"),
                  candidates=TILE_CANDIDATES, runs=2, save=True,
                  db_path=None):
    """Measure each Pallas tile candidate AND the plain-XLA dot on the
    attached backend; store the winner per dtype in the DeviceInfo DB
    (ref ``_find_optimal_bs_vo`` ``backends.py:672``).

    The stored entry decides dispatch:
    ``{"backend": "pallas"|"xla", "tiles": [...]|None, "sec_per_flop"}``
    — consulted by :func:`gemm_choice` / ``ops.gemm.matmul``."""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    # None = the XLA baseline (jnp.dot path) competing with every tiling
    all_candidates = tuple(candidates) + (None,)
    for dtype_name in dtypes:
        dtype = jnp.dtype(dtype_name)
        # Aggregate flops-normalized time per candidate over ALL shapes —
        # raw elapsed would let the smallest shape decide the winner.
        totals = {}
        for m, k, n in shapes:
            a = jnp.ones((m, k), dtype)
            b = jnp.ones((k, n), dtype)
            flops = 2.0 * m * k * n
            for tiles in all_candidates:
                try:
                    # full product stays a program output so XLA cannot
                    # sink the probe slice through the dot and elide the
                    # baseline's work (same guard as
                    # estimate_device_power); sync = host fetch of the
                    # probe's bytes (see ops/timing.py)
                    def work(x, y, t=tiles):
                        out = matmul(x, y, tiles=t,
                                     use_pallas=t is not None)
                        return out, out[0, 0].astype(jnp.float32)

                    fn = jax.jit(work)
                    host_fetch(fn(a, b)[1])    # compile + warm

                    def call(sync=False, _fn=fn):
                        _out, probe = _fn(a, b)
                        if sync:
                            host_fetch(probe)

                    elapsed = min(
                        marginal_time(call, min_seconds=0.25)
                        for _ in range(max(runs, 1)))
                except Exception:
                    totals.pop(tiles, None)
                    continue
                if tiles in totals or (m, k, n) == shapes[0]:
                    totals[tiles] = totals.get(tiles, 0.0) \
                        + elapsed / flops
        if totals:
            best = min(totals, key=totals.get)
            info.ratings.setdefault("gemm", {})[dtype_name] = {
                "sec_per_flop": totals[best] / len(shapes),
                "backend": "xla" if best is None else "pallas",
                "tiles": None if best is None else list(best)}
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info


@functools.lru_cache(maxsize=64)
def _choice_cached(kernel, model, dtype_name, db_path, _mtime):
    db = DeviceInfo.load_db(db_path)
    info = db.get(model)
    if info is None:
        return None
    entry = info.ratings.get(kernel, {}).get(dtype_name)
    if not entry:
        return None
    tiles = entry.get("tiles")
    # entries written before the sweep included the XLA baseline carry
    # no "backend": their tiles were only compared against other Pallas
    # tilings, so they must NOT flip dispatch away from XLA — the tiles
    # remain available for a config-forced Pallas run
    return (entry.get("backend", "xla"),
            tuple(tiles) if tiles else None)


def gemm_choice(dtype, db_path=None, kernel="gemm"):
    """Autotuned dispatch decision for the current device:
    ``("pallas", (bm, bk, bn))`` / ``("xla", None)`` / ``None`` when the
    DB has no entry for this device generation.  Cached on the DB
    file's mtime so training steps never re-read JSON."""
    db_path = db_path or DEVICE_INFOS_JSON
    try:
        model = jax.devices()[0].device_kind
    except RuntimeError:
        return None
    try:
        mtime = os.path.getmtime(db_path)
    except OSError:
        return None
    return _choice_cached(kernel, model, numpy.dtype(dtype).name,
                          db_path, mtime)


gemm_choice.cache_clear = _choice_cached.cache_clear


def tiles_for_gemm(dtype, db_path=None):
    """Look up autotuned Pallas tiles for the current device, or None."""
    choice = gemm_choice(dtype, db_path=db_path)
    return choice[1] if choice else None


#: (block_q, block_k) flash-attention sweep — VMEM-bounded MXU tilings
ATTN_BLOCK_CANDIDATES = (
    (128, 128), (128, 256), (256, 128), (256, 256),
    (512, 256), (256, 512), (512, 512),
)


def autotune_flash_attention(shape=(4, 2048, 8, 128),
                             dtypes=("bfloat16",),
                             candidates=ATTN_BLOCK_CANDIDATES, runs=2,
                             causal=True, save=True, db_path=None):
    """Sweep flash-attention block sizes (plus the XLA-fused baseline)
    on the attached chip; persist the winner under kernel
    ``flash_attention`` so :func:`veles_tpu.ops.attention.flash_attention`
    picks it up by default."""
    from veles_tpu.ops.attention import flash_attention

    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    b, s, h, d = shape
    flops = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
    all_candidates = tuple(candidates) + (None,)   # None = XLA baseline
    for dtype_name in dtypes:
        dtype = jnp.dtype(dtype_name)
        key = jax.random.key(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, shape, jnp.float32).astype(dtype)
        k = jax.random.normal(kk, shape, jnp.float32).astype(dtype)
        v = jax.random.normal(kv, shape, jnp.float32).astype(dtype)
        totals = {}
        for blocks in all_candidates:
            try:
                bq, bk = blocks if blocks else (None, None)

                # full output stays a program output so XLA cannot
                # slice the baseline down to one attention row
                def work(a, c, e, _bq=bq, _bk=bk,
                         _p=blocks is not None):
                    o = flash_attention(a, c, e, causal=causal,
                                        block_q=_bq, block_k=_bk,
                                        use_pallas=_p)
                    return o, o[0, 0, 0, 0].astype(jnp.float32)

                fn = jax.jit(work)
                host_fetch(fn(q, k, v)[1])       # compile + warm

                def call(sync=False, _fn=fn):
                    _o, probe = _fn(q, k, v)
                    if sync:
                        host_fetch(probe)

                totals[blocks] = min(
                    marginal_time(call, min_seconds=0.25)
                    for _ in range(max(runs, 1)))
            except Exception:
                totals.pop(blocks, None)
        if totals:
            best = min(totals, key=totals.get)
            info.ratings.setdefault("flash_attention", {})[dtype_name] \
                = {"sec_per_flop": totals[best] / flops,
                   "backend": "xla" if best is None else "pallas",
                   "tiles": None if best is None else list(best)}
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info
