"""Device rating + Pallas tile autotuner.

Parity target: the reference's in-situ benchmark — 13 chained 4096×4096
matmuls, min of 3 runs, producing the "computing power" rating used for
master-side load balancing (``ocl/benchmark.cl:1-11``,
``DeviceBenchmark`` ``accelerated_units.py:706-825``,
``workflow.py:618-624``) — and the OpenCL block-size autotune that fills
``devices/device_infos.json`` (``backends.py:623-744``).

TPU re-design: the same chained-matmul rating (so powers are comparable
across the fleet for job balancing) plus a tile search over the Pallas
GEMM, persisted in the same DB schema.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.backends import DEVICE_INFOS_JSON, DeviceInfo
from veles_tpu.ops.gemm import matmul
from veles_tpu.ops.timing import inprogram_marginal

BENCH_SIZE = 4096
BENCH_CHAIN = 13

#: candidate (bm, bk, bn) tiles — MXU-aligned sweep
TILE_CANDIDATES = (
    (128, 128, 128),
    (128, 256, 128),
    (256, 256, 256),
    (256, 512, 256),
    (512, 512, 512),
    (512, 1024, 256),
    (256, 1024, 512),
)

#: production GEMM regimes, one representative (m, k, n) each — the
#: reference DB stores per-shape measurements
#: (``/root/reference/devices/device_infos.json:2-30``); these classes
#: are its TPU analogue, keyed so dispatch can distinguish the MXU
#: workloads that actually occur in training:
SHAPE_CLASSES = {
    # compute-bound square block (LM MLP / 4096-class chains)
    "square_large": (4096, 4096, 4096),
    # batch-rows × modest feature dims (fused MLP stacks, conv heads)
    "tall_skinny": (16384, 1024, 1024),
    # attention qkv projection: B·S rows, d → 3d
    "proj_wide": (8192, 512, 1536),
}


def classify_shape(m, k, n):
    """Nearest :data:`SHAPE_CLASSES` name in log space — how dispatch
    buckets an actual GEMM onto a measured shape class."""
    import math

    def dist(rep):
        return sum((math.log2(max(int(v), 1)) - math.log2(r)) ** 2
                   for v, r in zip((m, k, n), rep))

    return min(SHAPE_CLASSES, key=lambda c: dist(SHAPE_CLASSES[c]))


def _peak_guard(marginal, flops_per_unit, remeasure, label):
    """Reject a marginal implying more FLOPs than the chip's peak.

    Round-3 post-mortem: timing across program launches on the tunneled
    transport measured ~11 % ABOVE peak — physically impossible — while
    the in-program marginal landed at 98 %.  Two re-measurements are
    allowed; if the violation persists, fail rather than persist a
    number faster than the hardware."""
    from veles_tpu.backends import peak_bf16_flops
    try:
        peak = peak_bf16_flops(jax.devices()[0].device_kind)
    except Exception:
        peak = None
    if not peak:
        return marginal
    attempts = 0
    while flops_per_unit / marginal > peak * 1.05:
        if attempts >= 2:
            raise RuntimeError(
                "%s: measured %.1f TFLOPs exceeds the %s peak %.1f — "
                "broken stopwatch, refusing to record" % (
                    label, flops_per_unit / marginal / 1e12,
                    jax.devices()[0].device_kind, peak / 1e12))
        marginal = remeasure()
        attempts += 1
    return marginal


def estimate_device_power(device=None, size=BENCH_SIZE, chain=BENCH_CHAIN,
                          runs=3, dtype=jnp.bfloat16, use_pallas=None,
                          min_seconds=None):
    """Wall time of one ``chain``-long size² matmul chain →
    (seconds, gflops) — the "computing power" number (ref
    ``workflow.py:618-624``).

    Timing (round-3 discipline, see ``ops/timing.py``): N chains are
    looped INSIDE one XLA program with a runtime trip count and the
    per-chain time is the marginal between two trip counts — the only
    shape that cancels the tunneled transport's per-program overhead
    without undercounting (cross-launch marginal measured ~11 % above
    chip peak).  Sync is a host fetch of a chain-derived scalar.
    ``min_seconds`` is accepted for backward compatibility and ignored.
    """
    key = jax.random.key(0)
    a = jax.random.normal(key, (size, size), jnp.float32).astype(dtype)
    b = jnp.eye(size, dtype=dtype) * 1.0001

    def one_chain(x):
        for _ in range(chain):
            x = matmul(x, b, use_pallas=use_pallas)
        return x

    def run():
        return inprogram_marginal(one_chain, a, k1=2, k2=10,
                                  repeats=max(runs, 2))

    flops = 2.0 * chain * float(size) ** 3
    best = _peak_guard(run(), flops, run, "estimate_device_power")
    return best, flops / best / 1e9


def _sweep_gemm_shape(m, k, n, dtype, candidates, runs, dtype_name):
    """One (shape, dtype) sweep on the attached backend: returns
    ``({candidate: (sec_per_chain, t1_rel_spread)}, flops)`` with
    candidate ``None`` = the XLA baseline competing with every
    tiling."""
    key = jax.random.key(m + n)
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (m, k), jnp.float32).astype(dtype)
    b = jax.random.normal(kb, (k, n), jnp.float32).astype(dtype)
    flops = 2.0 * m * k * n
    out = {}
    for tiles in candidates:
        try:
            # the loop body carries a scalar taken FROM the previous
            # product back into one element of ``a`` — a serial
            # dependency XLA cannot hoist or CSE away (iterations
            # would otherwise be loop-invariant).  The scalar is
            # abs().sum() over the WHOLE product: a plain out[0,0]
            # probe lets algsimp sink the slice through the dot and
            # elide the baseline's work (round-2's guard); the abs()
            # blocks the sum(dot)=dot(sums) factorization
            def unit(carry, t=tiles):
                x, s = carry
                x = jax.lax.dynamic_update_slice(
                    x, (x[0:1, 0:1] +
                        (s * 1e-30).astype(x.dtype)), (0, 0))
                out_ = matmul(x, b, tiles=t, use_pallas=t is not None)
                # fused reduce (f32 accumulator, no f32 copy)
                return x, jnp.sum(jnp.abs(out_), dtype=jnp.float32)

            init = (a, jnp.float32(0.0))
            stats = {}

            def run(_unit=unit, _init=init, _stats=stats):
                return inprogram_marginal(_unit, _init, k1=4, k2=32,
                                          repeats=max(runs, 2),
                                          stats=_stats)

            elapsed = _peak_guard(
                run(), flops, run,
                "autotune_gemm %s %s %s" % ((m, k, n), dtype_name,
                                            tiles))
        except Exception:
            continue
        out[tiles] = (elapsed, stats.get("t1_rel_spread"))
    return out, flops


def autotune_gemm(shapes=None, dtypes=("bfloat16", "float32"),
                  candidates=TILE_CANDIDATES, runs=2, save=True,
                  db_path=None, shape_classes=None,
                  precision_levels=(0,)):
    """Measure each Pallas tile candidate AND the plain-XLA dot on the
    attached backend; store winners in the DeviceInfo DB
    (ref ``_find_optimal_bs_vo`` ``backends.py:672``).

    Two generations of entries are written:

    - ``ratings["gemm"][dtype]`` — the legacy aggregate winner over
      all swept shapes (flops-normalized), written at precision level
      0 only: the fallback for dispatch without shape info.
    - ``ratings["gemm_v2"][dtype]["p{L}"][shape_class]`` — one entry
      per shape class per precision level (the reference DB stores
      per-shape, per-precision measurements,
      ``/root/reference/devices/device_infos.json:2-30``).  Each entry
      carries the measured shape and the stopwatch's short-point
      ``t1_rel_spread`` so noisy/stale entries are detectable.

    ``shapes``: explicit (m, k, n) list — classified into
    :data:`SHAPE_CLASSES` buckets for the v2 entries.  ``shape_classes``:
    ``{name: (m, k, n)}`` overriding the bucket names outright (default
    :data:`SHAPE_CLASSES` when ``shapes`` is not given).
    ``precision_levels``: reference precision levels to measure
    (``config.py:246-249``); the sweep sets
    ``root.common.engine.precision_level`` while measuring because the
    MXU pass count is read at trace time (``ops/gemm.py``)."""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    # None = the XLA baseline (jnp.dot path) competing with every tiling
    all_candidates = tuple(candidates) + (None,)
    if shape_classes:
        worklist = [(cls, tuple(s)) for cls, s in shape_classes.items()]
    elif shapes:
        worklist = [(classify_shape(*s), tuple(s)) for s in shapes]
    else:
        worklist = list(SHAPE_CLASSES.items())
    from veles_tpu.config import root
    orig_level = root.common.engine.get("precision_level", 0)
    # the MXU pass count is baked into jit caches at trace time:
    # track which level the caches were traced under and clear on
    # every switch — keying off orig_level alone would let a later
    # sweep (or the caller's next matmul) silently reuse kernels
    # traced at the wrong precision
    active_level = orig_level
    try:
        for level in precision_levels:
            # _precision() saturates at 2; clamp the DB key to match
            # or rows above p2 could never be read back
            level = min(int(level), 2)
            root.common.engine.precision_level = level
            if level != active_level:
                jax.clear_caches()
                active_level = level
            for dtype_name in dtypes:
                dtype = jnp.dtype(dtype_name)
                # Aggregate flops-normalized time per candidate over
                # ALL shapes — raw elapsed would let the smallest
                # shape decide the winner.  Candidates must survive
                # every shape to stay in the aggregate.
                totals = {c: 0.0 for c in all_candidates}
                for cls, (m, k, n) in worklist:
                    res, flops = _sweep_gemm_shape(
                        m, k, n, dtype, all_candidates, runs,
                        dtype_name)
                    for cand in list(totals):
                        if cand in res:
                            totals[cand] += res[cand][0] / flops
                        else:
                            totals.pop(cand)
                    if not res:
                        continue
                    best = min(res, key=lambda c: res[c][0])
                    sec, spread = res[best]
                    v2 = (info.ratings.setdefault("gemm_v2", {})
                          .setdefault(dtype_name, {})
                          .setdefault("p%d" % level, {}))
                    v2[cls] = {
                        "sec_per_flop": sec / flops,
                        "backend": "xla" if best is None else "pallas",
                        "tiles": None if best is None else list(best),
                        "shape": [m, k, n],
                        "t1_rel_spread": spread}
                if totals and level == 0:
                    best = min(totals, key=totals.get)
                    info.ratings.setdefault("gemm", {})[dtype_name] = {
                        "sec_per_flop": totals[best] / len(worklist),
                        "backend": "xla" if best is None else "pallas",
                        "tiles": None if best is None else list(best)}
    finally:
        root.common.engine.precision_level = orig_level
        if active_level != orig_level:
            # later same-process traces (estimate_device_power's 4096
            # chain, the caller's training step) must not hit kernels
            # traced at the sweep's last precision level
            jax.clear_caches()
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info


def _sweep_qgemm_shape(m, k, n, dtype, candidates, runs, dtype_name):
    """One (shape, dtype) int8-weight sweep: int8 weights + per-channel
    scales stay fixed, the activation carries the serial dependency
    (same hoisting/CSE defeat as ``_sweep_gemm_shape``).  Candidate
    ``None`` = the dense-jnp dequant baseline (XLA) competing with
    every Pallas tiling."""
    from veles_tpu.ops.qgemm import qmatmul

    key = jax.random.key(m + n)
    ka, kb, ks = jax.random.split(key, 3)
    a = jax.random.normal(ka, (m, k), jnp.float32).astype(dtype)
    q = jax.random.randint(kb, (k, n), -127, 128, jnp.int8)
    scale = (jax.random.uniform(ks, (n,), jnp.float32) + 0.5) / 127.0
    flops = 2.0 * m * k * n
    out = {}
    for tiles in candidates:
        try:
            def unit(carry, t=tiles):
                x, s = carry
                x = jax.lax.dynamic_update_slice(
                    x, (x[0:1, 0:1] +
                        (s * 1e-30).astype(x.dtype)), (0, 0))
                out_ = qmatmul(x, q, scale, None, None, tiles=t,
                               use_pallas=t is not None)
                return x, jnp.sum(jnp.abs(out_), dtype=jnp.float32)

            init = (a, jnp.float32(0.0))
            stats = {}

            def run(_unit=unit, _init=init, _stats=stats):
                return inprogram_marginal(_unit, _init, k1=4, k2=32,
                                          repeats=max(runs, 2),
                                          stats=_stats)

            elapsed = _peak_guard(
                run(), flops, run,
                "autotune_gemm_int8 %s %s %s" % ((m, k, n),
                                                 dtype_name, tiles))
        except Exception:
            continue
        out[tiles] = (elapsed, stats.get("t1_rel_spread"))
    return out, flops


def autotune_gemm_int8(shapes=None, dtypes=("bfloat16", "float32"),
                       candidates=TILE_CANDIDATES, runs=2, save=True,
                       db_path=None, shape_classes=None):
    """Race each Pallas tile candidate of the int8-weight GEMM
    (:func:`veles_tpu.ops.qgemm.qmatmul`) against the dense dequant
    baseline on the attached backend; persist the flops-normalized
    aggregate winner under ``ratings["gemm_int8"][dtype]`` — the row
    ``qmatmul``'s dispatch consults (``gemm_choice(...,
    kernel="gemm_int8")``), exactly like ``ops.gemm.matmul`` reads
    its own entries.  ``dtype`` keys the ACTIVATION dtype; the weight
    side is int8 by construction.  The row is written AND served at
    precision level 0 only (``_choice_cached`` refuses it at higher
    levels), so the sweep PINS level 0 while racing — an ambient
    level-1/2 config must not bake its MXU pass count into a level-0
    verdict (the ``autotune_gemm`` cross-precision guard, same
    hazard)."""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    all_candidates = tuple(candidates) + (None,)
    if shape_classes:
        worklist = [(cls, tuple(s)) for cls, s in shape_classes.items()]
    elif shapes:
        worklist = [(classify_shape(*s), tuple(s)) for s in shapes]
    else:
        worklist = list(SHAPE_CLASSES.items())
    from veles_tpu.config import root
    orig_level = root.common.engine.get("precision_level", 0)
    try:
        root.common.engine.precision_level = 0
        if orig_level != 0:
            # the pass count is baked into jit caches at trace time
            jax.clear_caches()
        for dtype_name in dtypes:
            dtype = jnp.dtype(dtype_name)
            totals = {c: 0.0 for c in all_candidates}
            shape_of = {}
            for cls, (m, k, n) in worklist:
                res, flops = _sweep_qgemm_shape(
                    m, k, n, dtype, all_candidates, runs, dtype_name)
                for cand in list(totals):
                    if cand in res:
                        totals[cand] += res[cand][0] / flops
                        shape_of[cand] = [m, k, n]
                    else:
                        totals.pop(cand)
            if not totals:
                continue
            best = min(totals, key=totals.get)
            info.ratings.setdefault("gemm_int8", {})[dtype_name] = {
                "sec_per_flop": totals[best] / len(worklist),
                "backend": "xla" if best is None else "pallas",
                "tiles": None if best is None else list(best),
                "shape": shape_of.get(best)}
    finally:
        root.common.engine.precision_level = orig_level
        if orig_level != 0:
            # the caller's next trace must not reuse level-0 kernels
            jax.clear_caches()
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info


def measure_s2d_ab(batch=256, spatial=227, dtype_name="bfloat16",
                   k1=4, k2=32):
    """Forward A/B of the AlexNet-conv1-shaped strided conv with and
    without the space-to-depth rewrite, in-program marginal each.
    Returns ``{"base_sec": ..., "s2d_sec": ...}``.  Iterations are
    serialized by feeding a result scalar back into one input element
    (hoisting/CSE defeat, same trick as the attention sweep)."""
    from veles_tpu.znicz.conv import Conv

    dtype = jnp.dtype(dtype_name)
    rng = numpy.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, spatial, spatial, 3)),
                    dtype)
    w = jnp.asarray(rng.standard_normal((11, 11, 3, 96)) * 0.01, dtype)
    secs = {}
    for s2d in (False, True):
        def unit(carry, _s2d=s2d):
            xx, s = carry
            xx = jax.lax.dynamic_update_slice(
                xx, (xx[0:1, 0:1, 0:1, 0:1]
                     + (s * 1e-30).astype(xx.dtype)), (0, 0, 0, 0))
            out = Conv.pure({"w": w}, xx, sliding=(4, 4), s2d=_s2d)
            return xx, jnp.sum(jnp.abs(out), dtype=jnp.float32)

        secs[s2d] = inprogram_marginal(unit, (x, jnp.float32(0.0)),
                                       k1=k1, k2=k2)
    return {"base_sec": secs[False], "s2d_sec": secs[True]}


def _persist_ab_entry(rating_key, dtype_name, entry, save, db_path):
    """Shared write path of the boolean-A/B autotunes (s2d, gather):
    load the DB, set ``ratings[rating_key][dtype_name]``, save, and
    invalidate the verdict cache."""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    info.ratings.setdefault(rating_key, {})[dtype_name] = entry
    if save:
        DeviceInfo.save_db(db, db_path)
    _verdict_cached.cache_clear()
    return info


def autotune_s2d(batch=256, spatial=227, dtype_name="bfloat16",
                 save=True, db_path=None):
    """Measure the space-to-depth conv rewrite A/B on the attached
    chip and persist the winner under ``ratings["s2d_conv"]`` so
    :meth:`veles_tpu.znicz.conv.Conv.pure_config` dispatches from a
    measurement instead of the lane-occupancy heuristic (r4 window 3:
    the heuristic said s2d, the chip said 0.51x)."""
    secs = measure_s2d_ab(batch=batch, spatial=spatial,
                          dtype_name=dtype_name)
    return _persist_ab_entry("s2d_conv", dtype_name, {
        "enabled": secs["s2d_sec"] < secs["base_sec"],
        "base_ms": round(secs["base_sec"] * 1e3, 4),
        "s2d_ms": round(secs["s2d_sec"] * 1e3, 4),
        "shape": [batch, spatial, spatial, 3]}, save, db_path)


def measure_gather_ab(n=4096, row=(227, 227, 3), dtype_name="uint8",
                      batch=256, k1=4, k2=64):
    """A/B of the resident-dataset minibatch row gather: XLA's native
    gather vs the Pallas scalar-prefetch DMA kernel, ImageNet-conv
    shaped by default (the ~12 ms/step e2e-vs-synthetic gap of r4's
    banked AlexNet ladder).  Returns ``{"xla_sec": ..., "pallas_sec":
    sec | None, "pallas_error": str | None}`` — the Pallas kernel may
    be unsupported for a shape/generation, which is a recorded verdict,
    not a crash."""
    from veles_tpu.ops.gather import _gather_jnp, _gather_pallas

    dtype = jnp.dtype(dtype_name)
    f = int(numpy.prod(row))
    rng = numpy.random.default_rng(0)
    # generate the FLAT (n, f) array directly in its storage dtype
    # (an (n,)+row int64 intermediate would be ~5 GB host for the
    # default ImageNet shape)
    if dtype.kind in "ui":
        flat = jnp.asarray(rng.integers(0, 256, (n, f),
                                        dtype=numpy.uint8).astype(dtype))
    else:
        flat = jnp.asarray(
            rng.random((n, f), dtype=numpy.float32).astype(dtype))
    idx0 = jnp.asarray(rng.integers(0, n, batch), jnp.int32)

    def run(fn):
        def unit(carry):
            # the dataset rides the CARRY — closing over it would bake
            # 633 MB into the program as a CONSTANT and the remote
            # compile request then exceeds the relay's body limit
            # (observed: HTTP 413 / 25-min hang, r4 session 4).  The
            # serialized idx leads the tuple: the stopwatch's probe is
            # derived from the FIRST carry leaf, and a probe on the
            # pass-through dataset would let XLA DCE the whole loop.
            idx, s, data_ = carry
            # serialize iterations: the next gather's indices depend
            # on the previous result's bytes
            idx = (idx + (s * 0).astype(jnp.int32)) % n
            out = fn(data_, idx)
            # reduce the WHOLE output: a sliced probe would let XLA
            # commute the slice into the gather and time a narrowed
            # per-row fetch while the opaque Pallas arm moves full
            # rows (the gemm sweep's round-2 guard, same hazard)
            return (idx, jnp.sum(jnp.abs(out.astype(jnp.float32))),
                    data_)

        return inprogram_marginal(
            unit, (idx0, jnp.float32(0.0), flat), k1=k1, k2=k2)

    # both arms gather the same flat array and reduce the same full
    # output, so the A/B isolates the gather backend itself
    res = {"xla_sec": run(_gather_jnp), "pallas_sec": None,
           "pallas_error": None}
    try:
        res["pallas_sec"] = run(lambda d, i: _gather_pallas(d, i))
    except Exception as exc:   # unsupported shape/generation = verdict
        res["pallas_error"] = "%s: %s" % (type(exc).__name__, exc)
    return res


def autotune_gather(n=4096, row=(227, 227, 3), dtype_name="uint8",
                    batch=256, save=True, db_path=None):
    """Measure the minibatch-gather A/B on the attached chip and
    persist the winner under ``ratings["gather"]`` so
    :func:`veles_tpu.ops.gather.take_rows` dispatches the resident-
    dataset gather from a measurement."""
    res = measure_gather_ab(n=n, row=row, dtype_name=dtype_name,
                            batch=batch)
    pallas_wins = (res["pallas_sec"] is not None
                   and res["pallas_sec"] < res["xla_sec"])
    entry = {
        "backend": "pallas" if pallas_wins else "xla",
        "xla_ms": round(res["xla_sec"] * 1e3, 4),
        "pallas_ms": (None if res["pallas_sec"] is None
                      else round(res["pallas_sec"] * 1e3, 4)),
        "shape": [n] + list(row), "batch": batch}
    if res["pallas_error"]:
        entry["pallas_error"] = res["pallas_error"][:200]
    return _persist_ab_entry("gather", dtype_name, entry, save,
                             db_path)


@functools.lru_cache(maxsize=64)
def _verdict_cached(rating_key, model, dtype_name, db_path, _mtime):
    db = DeviceInfo.load_db(db_path)
    info = db.get(model)
    if info is None:
        return None
    entry = info.ratings.get(rating_key, {}).get(dtype_name)
    if entry is None:
        return None            # unmeasured dtype: caller falls back
    if rating_key == "s2d_conv":
        return bool(entry.get("enabled"))
    # gather: the verdict plus the row size it was measured at
    shape = entry.get("shape") or []
    row_elems = int(numpy.prod(shape[1:])) if len(shape) > 1 else None
    return (entry.get("backend") == "pallas", row_elems)


def _device_db_verdict(rating_key, dtype_name, db_path):
    """Shared mtime-cached boolean-verdict reader for per-device A/B
    entries (``s2d_conv``, ``gather``): True/False from the DB, or
    None when this (device generation, dtype) was never measured."""
    db_path = db_path or DEVICE_INFOS_JSON
    try:
        model = jax.devices()[0].device_kind
    except RuntimeError:
        return None
    try:
        mtime = os.path.getmtime(db_path)
    except OSError:
        return None
    return _verdict_cached(rating_key, model, dtype_name, db_path,
                           mtime)


def gather_choice(dtype_name="uint8", db_path=None, row_elems=None):
    """Measured gather-backend verdict for the current device
    generation: True (Pallas DMA) / False (XLA) from the DB's
    ``gather`` A/B entry, or None when unmeasured (callers fall back
    to the XLA path).

    ``row_elems``: the caller's flattened row size.  A Pallas verdict
    only transfers to the row size it was measured at — the kernel's
    shape support (and its win) is not generic, and an unmeasured
    shape that Mosaic rejects would fail at compile time of the
    enclosing program, beyond any fallback — so a mismatch returns
    False (XLA), never the measured True."""
    verdict = _device_db_verdict("gather", dtype_name, db_path)
    if verdict is None:
        return None
    is_pallas, measured_elems = verdict
    if is_pallas and row_elems is not None \
            and measured_elems != row_elems:
        # a missing measured shape (legacy/hand-edited DB entry) is
        # NON-transferable too: trusting it re-exposes the Mosaic
        # compile-time failure this gate exists to prevent (ADVICE r4)
        return False
    return is_pallas


gather_choice.cache_clear = _verdict_cached.cache_clear


def s2d_choice(dtype_name="bfloat16", db_path=None):
    """Measured space-to-depth verdict for the current device
    generation: True/False from the DB's ``s2d_conv`` A/B entry, or
    None when this device was never measured (callers fall back to
    the heuristic)."""
    return _device_db_verdict("s2d_conv", dtype_name, db_path)


s2d_choice.cache_clear = _verdict_cached.cache_clear


@functools.lru_cache(maxsize=256)
def _choice_cached(kernel, model, dtype_name, level, shape_cls,
                   db_path, _mtime):
    db = DeviceInfo.load_db(db_path)
    info = db.get(model)
    if info is None:
        return None
    entry = None
    if kernel == "gemm":
        v2 = (info.ratings.get("gemm_v2", {}).get(dtype_name, {})
              .get("p%d" % level, {}))
        if v2:
            # same-precision measurement: exact class hit, else any
            # measured class (still beats a wrong-precision row)
            entry = (v2.get(shape_cls) if shape_cls else None) \
                or v2.get("square_large") \
                or v2[sorted(v2)[0]]
        if entry is None and level != 0:
            # NEVER reuse precision-0 winners at a higher level: a
            # Kahan/multipartial user must not silently get tiles
            # raced under bf16 MXU passes — XLA is the safe default
            return None
    elif kernel == "gemm_int8":
        # the int8 sweep races at level 0 (its MXU pass count reads
        # the same _precision() knob as the float kernel); the same
        # no-cross-precision-reuse rule applies — a HIGHEST-precision
        # deploy falls back to the dense path rather than trusting a
        # verdict raced under bf16 passes
        if level != 0:
            return None
    elif kernel in ("flash_attention", "flash_attention_bwd"):
        v2 = info.ratings.get(kernel + "_v2", {}).get(
            dtype_name, {})
        if v2:
            entry = (v2.get(shape_cls) if shape_cls else None) \
                or v2.get("seq_2k") or v2[sorted(v2)[0]]
    elif kernel == "gd":
        # fused backward-GD family: not precision-keyed (both arms
        # accumulate f32 at default MXU precision by construction)
        v2 = info.ratings.get("gd_v2", {}).get(dtype_name, {})
        if v2:
            entry = (v2.get(shape_cls) if shape_cls else None) \
                or v2.get("fc_wide") or v2[sorted(v2)[0]]
    if entry is None:
        entry = info.ratings.get(kernel, {}).get(dtype_name)
    if not entry:
        return None
    tiles = entry.get("tiles")
    # entries written before the sweep included the XLA baseline carry
    # no "backend": their tiles were only compared against other Pallas
    # tilings, so they must NOT flip dispatch away from XLA — the tiles
    # remain available for a config-forced Pallas run
    return (entry.get("backend", "xla"),
            tuple(tiles) if tiles else None)


def gemm_choice(dtype, db_path=None, kernel="gemm", shape=None):
    """Autotuned dispatch decision for the current device:
    ``("pallas", (bm, bk, bn))`` / ``("xla", None)`` / ``None`` when the
    DB has no entry for this device generation.  Cached on the DB
    file's mtime so training steps never re-read JSON.

    ``shape``: the actual (m, k, n), bucketed via
    :func:`classify_shape` onto the per-shape-class ``gemm_v2`` entries;
    the lookup is also keyed on the configured
    ``root.common.engine.precision_level`` — a level with no measured
    entry falls back to XLA, never to tiles raced at another
    precision."""
    db_path = db_path or DEVICE_INFOS_JSON
    try:
        model = jax.devices()[0].device_kind
    except RuntimeError:
        return None
    try:
        mtime = os.path.getmtime(db_path)
    except OSError:
        return None
    from veles_tpu.config import root
    level = min(int(root.common.engine.get("precision_level", 0)), 2)
    if shape is None:
        shape_cls = None
    elif kernel.startswith("flash_attention"):
        shape_cls = classify_attn_shape(*shape)
    elif kernel == "gd":
        shape_cls = classify_gd_shape(*shape)
    else:
        shape_cls = classify_shape(*shape)
    return _choice_cached(kernel, model, numpy.dtype(dtype).name,
                          level, shape_cls, db_path, mtime)


gemm_choice.cache_clear = _choice_cached.cache_clear


def tiles_for_gemm(dtype, db_path=None):
    """Look up autotuned Pallas tiles for the current device, or None."""
    choice = gemm_choice(dtype, db_path=db_path)
    return choice[1] if choice else None


#: (bf, bn, bk) = (fan-in, neurons, batch) tile triples raced by
#: :func:`autotune_gd` — bf/bn lane-aligned (128), bk sublane-aligned
GD_TILE_CANDIDATES = (
    (256, 256, 256), (512, 256, 256), (256, 512, 256),
    (512, 512, 256), (128, 128, 512), (512, 512, 512),
    (128, 256, 128),
)

#: fused-GD shape classes as (batch, fan_in, neurons) — the FC layers
#: a stitched GD chain actually runs (AlexNet-ish fc6 / classifier head
#: / thin-MLP hidden)
GD_SHAPE_CLASSES = {
    "fc_small": (128, 1024, 256),
    "fc_wide": (128, 9216, 4096),
    "fc_out": (128, 4096, 1000),
}


def classify_gd_shape(batch, f, n):
    """Nearest :data:`GD_SHAPE_CLASSES` name in log space; the layer
    dims dominate the tile choice, batch only weakly (it is the
    sequential grid axis)."""
    import math

    def dist(rep):
        return ((math.log2(max(int(f), 1)) - math.log2(rep[1])) ** 2
                + (math.log2(max(int(n), 1)) - math.log2(rep[2])) ** 2
                + 0.25 * (math.log2(max(int(batch), 1))
                          - math.log2(rep[0])) ** 2)

    return min(GD_SHAPE_CLASSES,
               key=lambda c: dist(GD_SHAPE_CLASSES[c]))


def _sweep_gd_shape(batch, f, n, dtype, candidates, runs, dtype_name):
    """One (shape, dtype) fused-GD sweep: races the Pallas dW/db/dX +
    epilogue family (``ops.gemm.gd_fused_pallas``) at each (bf, bn, bk)
    against the dense reference (``znicz.gd._gd_math``, candidate
    ``None``).  Returns ``({tiles: (sec, t1_rel_spread)}, flops)``."""
    from veles_tpu.ops.gemm import gd_fused_pallas
    from veles_tpu.znicz.gd import _gd_math

    key = jax.random.key(f + n)
    kx, ky, ke, kw, kv = jax.random.split(key, 5)
    x = jax.random.normal(kx, (batch, f), jnp.float32).astype(dtype)
    y = jax.random.normal(ky, (batch, n), jnp.float32).astype(dtype)
    eo = jax.random.normal(ke, (batch, n), jnp.float32).astype(dtype)
    w = jax.random.normal(kw, (f, n), jnp.float32) * 0.1
    vw = jax.random.normal(kv, (f, n), jnp.float32) * 0.01
    b = jnp.zeros((n,), jnp.float32)
    vb = jnp.zeros((n,), jnp.float32)
    hp = (0.01, 0.01, 0.0005, 0.0, 0.9, 0.9)
    # dW (2BFN) + err_input (2BFN) + the elementwise epilogues
    flops = 4.0 * batch * f * n
    out = {}
    for tiles in candidates:
        try:
            def unit(carry, t=tiles):
                xx, s = carry
                xx = jax.lax.dynamic_update_slice(
                    xx, (xx[0:1, 0:1] +
                         (s * 1e-30).astype(xx.dtype)), (0, 0))
                fn = _gd_math if t is None else functools.partial(
                    gd_fused_pallas, tiles=t)
                w2, _b2, vw2, _vb2, err = fn(
                    xx, y, eo, w, b, vw, vb, *hp, activation="tanh",
                    need_err_input=True, has_bias=True)
                # reduce over BOTH products so neither the update nor
                # the err_input pass can be DCE'd out of either arm
                return xx, (jnp.sum(jnp.abs(err), dtype=jnp.float32)
                            + jnp.sum(jnp.abs(w2 + vw2),
                                      dtype=jnp.float32))

            init = (x, jnp.float32(0.0))
            stats = {}

            def run(_unit=unit, _init=init, _stats=stats):
                return inprogram_marginal(_unit, _init, k1=4, k2=32,
                                          repeats=max(runs, 2),
                                          stats=_stats)

            elapsed = _peak_guard(
                run(), flops, run,
                "autotune_gd %s %s %s" % ((batch, f, n), dtype_name,
                                          tiles))
        except Exception:
            continue
        out[tiles] = (elapsed, stats.get("t1_rel_spread"))
    return out, flops


def autotune_gd(shape=None, dtypes=("float32",),
                candidates=GD_TILE_CANDIDATES, runs=2, save=True,
                db_path=None, shape_classes=None):
    """Sweep the fused backward-GD kernel family (dW+epilogue / db /
    dX, ``ops.gemm.gd_fused_pallas``) against the dense ``_gd_math``
    reference per :data:`GD_SHAPE_CLASSES` regime; persist winners
    under ``gd_v2`` plus the legacy flat ``gd`` entry (the ``fc_wide``
    canonical shape) consumed by ``ops.gemm.gd_kernel_choice`` when
    ``root.common.engine.kernels=auto``.  Entries are not
    precision-keyed: both arms accumulate float32 at default MXU
    precision by construction (the dense reference sets no precision
    either)."""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    all_candidates = tuple(candidates) + (None,)   # None = dense _gd_math
    if shape is not None:
        worklist = [(classify_gd_shape(*shape), tuple(shape))]
    else:
        worklist = list((shape_classes or GD_SHAPE_CLASSES).items())
    for dtype_name in dtypes:
        dtype = jnp.dtype(dtype_name)
        for cls, shp in worklist:
            res, flops = _sweep_gd_shape(
                shp[0], shp[1], shp[2], dtype, all_candidates, runs,
                dtype_name)
            if not res:
                continue
            best = min(res, key=lambda c: res[c][0])
            sec, spread = res[best]
            entry = {"sec_per_flop": sec / flops,
                     "backend": "xla" if best is None else "pallas",
                     "tiles": None if best is None else list(best),
                     "shape": list(shp),
                     "t1_rel_spread": spread}
            (info.ratings.setdefault("gd_v2", {})
             .setdefault(dtype_name, {}))[cls] = entry
            if cls == "fc_wide" or len(worklist) == 1:
                info.ratings.setdefault("gd", {})[dtype_name] = {
                    k: entry[k] for k in
                    ("sec_per_flop", "backend", "tiles")}
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info


#: (block_q, block_k) flash-attention sweep — VMEM-bounded MXU tilings
ATTN_BLOCK_CANDIDATES = (
    (128, 128), (128, 256), (256, 128), (256, 256),
    (512, 256), (256, 512), (512, 512),
)

#: attention shape classes by sequence-length regime (the block choice
#: is dominated by S and head dim): representative (b, s, h, d) each —
#: round-3's DB held a single (4, 2048, 8, 128) measurement
ATTN_SHAPE_CLASSES = {
    "seq_short": (16, 512, 8, 64),
    "seq_2k": (4, 2048, 8, 128),
    "seq_8k": (1, 8192, 8, 128),
}


def classify_attn_shape(b, s, h, d):
    """Bucket an actual (b, s, h, d) attention call onto the nearest
    measured :data:`ATTN_SHAPE_CLASSES` sequence regime."""
    import math

    def dist(rep):
        return (math.log2(max(int(s), 1)) - math.log2(rep[1])) ** 2 \
            + 0.25 * (math.log2(max(int(d), 1)) - math.log2(rep[3])) ** 2

    return min(ATTN_SHAPE_CLASSES,
               key=lambda c: dist(ATTN_SHAPE_CLASSES[c]))


def _race_attn_candidates(candidates, carrier, step_of, flops, runs,
                          tag):
    """Shared attention-sweep timing harness: serial scalar feedback
    into ``carrier[0,0,0,0]`` so loop iterations can't be hoisted/
    CSE'd (see autotune_gemm); the scalar is an abs-sum over the WHOLE
    output so an XLA baseline can't be sliced down to one position.
    ``step_of(blocks)`` returns ``fn(tensor) -> scalar``; a candidate
    that raises is skipped.  Returns ``{blocks: (sec, spread)}``."""
    out = {}
    for blocks in candidates:
        try:
            fn = step_of(blocks)

            def unit(carry, _fn=fn):
                t, sc = carry
                t = jax.lax.dynamic_update_slice(
                    t, (t[0:1, 0:1, 0:1, 0:1] +
                        (sc * 1e-30).astype(t.dtype)),
                    (0, 0, 0, 0))
                return t, _fn(t)

            init = (carrier, jnp.float32(0.0))
            stats = {}

            def run(_unit=unit, _init=init, _stats=stats):
                return inprogram_marginal(_unit, _init, k1=4, k2=32,
                                          repeats=max(runs, 2),
                                          stats=_stats)

            elapsed = _peak_guard(run(), flops, run,
                                  "%s %s" % (tag, blocks))
        except Exception:
            continue
        out[blocks] = (elapsed, stats.get("t1_rel_spread"))
    return out


def _sweep_attention_shape(shape, dtype, candidates, runs, causal,
                           dtype_name):
    """One (shape, dtype) flash-attention sweep: returns
    ``({blocks: (sec, t1_rel_spread)}, flops)``; blocks ``None`` = the
    XLA-fused baseline."""
    from veles_tpu.ops.attention import flash_attention

    b, s, h, d = shape
    flops = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, shape, jnp.float32).astype(dtype)
    k = jax.random.normal(kk, shape, jnp.float32).astype(dtype)
    v = jax.random.normal(kv, shape, jnp.float32).astype(dtype)

    def step_of(blocks):
        bq, bk = blocks if blocks else (None, None)

        def fn(qq, _bq=bq, _bk=bk, _p=blocks is not None):
            o = flash_attention(qq, k, v, causal=causal, block_q=_bq,
                                block_k=_bk, use_pallas=_p)
            return jnp.sum(jnp.abs(o), dtype=jnp.float32)

        return fn

    out = _race_attn_candidates(
        candidates, q, step_of, flops, runs,
        "autotune_flash_attention %s %s" % (shape, dtype_name))
    return out, flops


def _sweep_attention_bwd_shape(shape, dtype, candidates, runs, causal,
                               dtype_name):
    """One (shape, dtype) flash-attention BACKWARD sweep: times the
    Pallas two-kernel backward (``_flash_bwd``) at each block pair
    against the XLA scan fallback (``None``), from a fixed saved
    forward.  Returns ``({blocks: (sec, t1_rel_spread)}, flops)``."""
    from veles_tpu.ops.attention import (_bwd_blockwise, _flash_bwd,
                                         _flash_vjp_fwd)

    b, s, h, d = shape
    # 5 block matmuls (score recompute, dp, dq, dk, dv) vs the
    # forward's 2 — causal halves the visited blocks
    flops = 10.0 * b * h * s * s * d * (0.5 if causal else 1.0)
    key = jax.random.key(0)
    kq, kk_, kv, kd = jax.random.split(key, 4)
    q = jax.random.normal(kq, shape, jnp.float32).astype(dtype)
    k = jax.random.normal(kk_, shape, jnp.float32).astype(dtype)
    v = jax.random.normal(kv, shape, jnp.float32).astype(dtype)
    do = jax.random.normal(kd, shape, jnp.float32).astype(dtype)
    o, res = _flash_vjp_fwd(q, k, v, causal, None, None, None)

    def step_of(blocks):
        def fn(dd, _blocks=blocks):
            if _blocks is None:
                dq, dk, dv = _bwd_blockwise(res, dd, causal, 128)
            else:
                from veles_tpu.config import root
                dq, dk, dv = _flash_bwd(
                    res[0], res[1], res[2], res[3], res[4], dd,
                    causal=causal, block_q=_blocks[0],
                    block_k=_blocks[1],
                    interpret=bool(root.common.engine.get(
                        "interpret", False)))
            return sum(jnp.sum(jnp.abs(g), dtype=jnp.float32)
                       for g in (dq, dk, dv))

        return fn

    out = _race_attn_candidates(
        candidates, do, step_of, flops, runs,
        "autotune_flash_attention_bwd %s %s" % (shape, dtype_name))
    return out, flops


def autotune_flash_attention_bwd(shape=None, dtypes=("bfloat16",),
                                 candidates=ATTN_BLOCK_CANDIDATES,
                                 runs=2, causal=True, save=True,
                                 db_path=None, shape_classes=None):
    """Sweep the flash-attention BACKWARD block sizes (plus the XLA
    scan fallback) per sequence regime; persist winners under
    ``flash_attention_bwd_v2`` (+ a legacy flat entry) consumed by
    ``ops.attention._resolve_bwd``.  The forward sweep cannot stand in
    for this: the backward's 5-matmul blocks have a different VMEM
    footprint and arithmetic intensity than the forward's 2 (VERDICT
    r4 next-round item 2)."""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    all_candidates = tuple(candidates) + (None,)   # None = XLA scan
    if shape is not None:
        worklist = [(classify_attn_shape(*shape), tuple(shape))]
    else:
        worklist = list((shape_classes or ATTN_SHAPE_CLASSES).items())
    for dtype_name in dtypes:
        dtype = jnp.dtype(dtype_name)
        for cls, shp in worklist:
            res, flops = _sweep_attention_bwd_shape(
                shp, dtype, all_candidates, runs, causal, dtype_name)
            if not res:
                continue
            best = min(res, key=lambda c: res[c][0])
            sec, spread = res[best]
            entry = {"sec_per_flop": sec / flops,
                     "backend": "xla" if best is None else "pallas",
                     "tiles": None if best is None else list(best),
                     "shape": list(shp),
                     "t1_rel_spread": spread}
            (info.ratings.setdefault("flash_attention_bwd_v2", {})
             .setdefault(dtype_name, {}))[cls] = entry
            if cls == "seq_2k" or len(worklist) == 1:
                info.ratings.setdefault("flash_attention_bwd", {})[
                    dtype_name] = {k: entry[k] for k in
                                   ("sec_per_flop", "backend", "tiles")}
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info


def autotune_flash_attention(shape=None, dtypes=("bfloat16",),
                             candidates=ATTN_BLOCK_CANDIDATES, runs=2,
                             causal=True, save=True, db_path=None,
                             shape_classes=None):
    """Sweep flash-attention block sizes (plus the XLA-fused baseline)
    on the attached chip over the sequence-length regimes of
    :data:`ATTN_SHAPE_CLASSES`; persist per-class winners under
    ``flash_attention_v2`` plus the legacy ``flash_attention`` entry
    (the ``seq_2k`` canonical shape) so
    :func:`veles_tpu.ops.attention.flash_attention` routes by actual
    sequence length.  Round-3's DB held one shape's measurement —
    VERDICT r3 item 3.  (Attention entries are not precision-keyed:
    the Pallas kernel is bf16/f32-accumulate by construction.)"""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    all_candidates = tuple(candidates) + (None,)   # None = XLA baseline
    if shape is not None:
        worklist = [(classify_attn_shape(*shape), tuple(shape))]
    else:
        worklist = list((shape_classes or ATTN_SHAPE_CLASSES).items())
    for dtype_name in dtypes:
        dtype = jnp.dtype(dtype_name)
        for cls, shp in worklist:
            res, flops = _sweep_attention_shape(
                shp, dtype, all_candidates, runs, causal, dtype_name)
            if not res:
                continue
            best = min(res, key=lambda c: res[c][0])
            sec, spread = res[best]
            entry = {"sec_per_flop": sec / flops,
                     "backend": "xla" if best is None else "pallas",
                     "tiles": None if best is None else list(best),
                     "shape": list(shp),
                     "t1_rel_spread": spread}
            (info.ratings.setdefault("flash_attention_v2", {})
             .setdefault(dtype_name, {}))[cls] = entry
            if cls == "seq_2k" or len(worklist) == 1:
                # legacy flat entry: the canonical-regime winner
                info.ratings.setdefault("flash_attention", {})[
                    dtype_name] = {k: entry[k] for k in
                                   ("sec_per_flop", "backend", "tiles")}
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info
