"""Device rating + Pallas tile autotuner.

Parity target: the reference's in-situ benchmark — 13 chained 4096×4096
matmuls, min of 3 runs, producing the "computing power" rating used for
master-side load balancing (``ocl/benchmark.cl:1-11``,
``DeviceBenchmark`` ``accelerated_units.py:706-825``,
``workflow.py:618-624``) — and the OpenCL block-size autotune that fills
``devices/device_infos.json`` (``backends.py:623-744``).

TPU re-design: the same chained-matmul rating (so powers are comparable
across the fleet for job balancing) plus a tile search over the Pallas
GEMM, persisted in the same DB schema.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.backends import DEVICE_INFOS_JSON, DeviceInfo
from veles_tpu.ops.gemm import matmul
from veles_tpu.ops.timing import inprogram_marginal

BENCH_SIZE = 4096
BENCH_CHAIN = 13

#: candidate (bm, bk, bn) tiles — MXU-aligned sweep
TILE_CANDIDATES = (
    (128, 128, 128),
    (128, 256, 128),
    (256, 256, 256),
    (256, 512, 256),
    (512, 512, 512),
    (512, 1024, 256),
    (256, 1024, 512),
)


def _peak_guard(marginal, flops_per_unit, remeasure, label):
    """Reject a marginal implying more FLOPs than the chip's peak.

    Round-3 post-mortem: timing across program launches on the tunneled
    transport measured ~11 % ABOVE peak — physically impossible — while
    the in-program marginal landed at 98 %.  Two re-measurements are
    allowed; if the violation persists, fail rather than persist a
    number faster than the hardware."""
    from veles_tpu.backends import peak_bf16_flops
    try:
        peak = peak_bf16_flops(jax.devices()[0].device_kind)
    except Exception:
        peak = None
    if not peak:
        return marginal
    attempts = 0
    while flops_per_unit / marginal > peak * 1.05:
        if attempts >= 2:
            raise RuntimeError(
                "%s: measured %.1f TFLOPs exceeds the %s peak %.1f — "
                "broken stopwatch, refusing to record" % (
                    label, flops_per_unit / marginal / 1e12,
                    jax.devices()[0].device_kind, peak / 1e12))
        marginal = remeasure()
        attempts += 1
    return marginal


def estimate_device_power(device=None, size=BENCH_SIZE, chain=BENCH_CHAIN,
                          runs=3, dtype=jnp.bfloat16, use_pallas=None,
                          min_seconds=None):
    """Wall time of one ``chain``-long size² matmul chain →
    (seconds, gflops) — the "computing power" number (ref
    ``workflow.py:618-624``).

    Timing (round-3 discipline, see ``ops/timing.py``): N chains are
    looped INSIDE one XLA program with a runtime trip count and the
    per-chain time is the marginal between two trip counts — the only
    shape that cancels the tunneled transport's per-program overhead
    without undercounting (cross-launch marginal measured ~11 % above
    chip peak).  Sync is a host fetch of a chain-derived scalar.
    ``min_seconds`` is accepted for backward compatibility and ignored.
    """
    key = jax.random.key(0)
    a = jax.random.normal(key, (size, size), jnp.float32).astype(dtype)
    b = jnp.eye(size, dtype=dtype) * 1.0001

    def one_chain(x):
        for _ in range(chain):
            x = matmul(x, b, use_pallas=use_pallas)
        return x

    def run():
        return inprogram_marginal(one_chain, a, k1=2, k2=10,
                                  repeats=max(runs, 2))

    flops = 2.0 * chain * float(size) ** 3
    best = _peak_guard(run(), flops, run, "estimate_device_power")
    return best, flops / best / 1e9


def autotune_gemm(shapes=((4096, 4096, 4096),), dtypes=("bfloat16",
                                                        "float32"),
                  candidates=TILE_CANDIDATES, runs=2, save=True,
                  db_path=None):
    """Measure each Pallas tile candidate AND the plain-XLA dot on the
    attached backend; store the winner per dtype in the DeviceInfo DB
    (ref ``_find_optimal_bs_vo`` ``backends.py:672``).

    The stored entry decides dispatch:
    ``{"backend": "pallas"|"xla", "tiles": [...]|None, "sec_per_flop"}``
    — consulted by :func:`gemm_choice` / ``ops.gemm.matmul``."""
    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    # None = the XLA baseline (jnp.dot path) competing with every tiling
    all_candidates = tuple(candidates) + (None,)
    for dtype_name in dtypes:
        dtype = jnp.dtype(dtype_name)
        # Aggregate flops-normalized time per candidate over ALL shapes —
        # raw elapsed would let the smallest shape decide the winner.
        totals = {}
        for m, k, n in shapes:
            key = jax.random.key(m + n)
            ka, kb = jax.random.split(key)
            a = jax.random.normal(ka, (m, k), jnp.float32).astype(dtype)
            b = jax.random.normal(kb, (k, n), jnp.float32).astype(dtype)
            flops = 2.0 * m * k * n
            for tiles in all_candidates:
                try:
                    # the loop body carries a scalar taken FROM the
                    # previous product back into one element of ``a`` —
                    # a serial dependency XLA cannot hoist or CSE away
                    # (iterations would otherwise be loop-invariant).
                    # The scalar is abs().sum() over the WHOLE product:
                    # a plain out[0,0] probe lets algsimp sink the
                    # slice through the dot and elide the baseline's
                    # work (round-2's guard, re-established here); the
                    # abs() blocks the sum(dot)=dot(sums) factorization
                    def unit(carry, t=tiles):
                        x, s = carry
                        x = jax.lax.dynamic_update_slice(
                            x, (x[0:1, 0:1] +
                                (s * 1e-30).astype(x.dtype)), (0, 0))
                        out = matmul(x, b, tiles=t,
                                     use_pallas=t is not None)
                        # fused reduce (f32 accumulator, no f32 copy)
                        return x, jnp.sum(jnp.abs(out),
                                          dtype=jnp.float32)

                    init = (a, jnp.float32(0.0))

                    def run(_unit=unit, _init=init):
                        return inprogram_marginal(
                            _unit, _init, k1=4, k2=32,
                            repeats=max(runs, 2))

                    elapsed = _peak_guard(
                        run(), flops, run,
                        "autotune_gemm %s %s %s" % ((m, k, n),
                                                    dtype_name, tiles))
                except Exception:
                    totals.pop(tiles, None)
                    continue
                if tiles in totals or (m, k, n) == shapes[0]:
                    totals[tiles] = totals.get(tiles, 0.0) \
                        + elapsed / flops
        if totals:
            best = min(totals, key=totals.get)
            info.ratings.setdefault("gemm", {})[dtype_name] = {
                "sec_per_flop": totals[best] / len(shapes),
                "backend": "xla" if best is None else "pallas",
                "tiles": None if best is None else list(best)}
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info


@functools.lru_cache(maxsize=64)
def _choice_cached(kernel, model, dtype_name, db_path, _mtime):
    db = DeviceInfo.load_db(db_path)
    info = db.get(model)
    if info is None:
        return None
    entry = info.ratings.get(kernel, {}).get(dtype_name)
    if not entry:
        return None
    tiles = entry.get("tiles")
    # entries written before the sweep included the XLA baseline carry
    # no "backend": their tiles were only compared against other Pallas
    # tilings, so they must NOT flip dispatch away from XLA — the tiles
    # remain available for a config-forced Pallas run
    return (entry.get("backend", "xla"),
            tuple(tiles) if tiles else None)


def gemm_choice(dtype, db_path=None, kernel="gemm"):
    """Autotuned dispatch decision for the current device:
    ``("pallas", (bm, bk, bn))`` / ``("xla", None)`` / ``None`` when the
    DB has no entry for this device generation.  Cached on the DB
    file's mtime so training steps never re-read JSON."""
    db_path = db_path or DEVICE_INFOS_JSON
    try:
        model = jax.devices()[0].device_kind
    except RuntimeError:
        return None
    try:
        mtime = os.path.getmtime(db_path)
    except OSError:
        return None
    return _choice_cached(kernel, model, numpy.dtype(dtype).name,
                          db_path, mtime)


gemm_choice.cache_clear = _choice_cached.cache_clear


def tiles_for_gemm(dtype, db_path=None):
    """Look up autotuned Pallas tiles for the current device, or None."""
    choice = gemm_choice(dtype, db_path=db_path)
    return choice[1] if choice else None


#: (block_q, block_k) flash-attention sweep — VMEM-bounded MXU tilings
ATTN_BLOCK_CANDIDATES = (
    (128, 128), (128, 256), (256, 128), (256, 256),
    (512, 256), (256, 512), (512, 512),
)


def autotune_flash_attention(shape=(4, 2048, 8, 128),
                             dtypes=("bfloat16",),
                             candidates=ATTN_BLOCK_CANDIDATES, runs=2,
                             causal=True, save=True, db_path=None):
    """Sweep flash-attention block sizes (plus the XLA-fused baseline)
    on the attached chip; persist the winner under kernel
    ``flash_attention`` so :func:`veles_tpu.ops.attention.flash_attention`
    picks it up by default."""
    from veles_tpu.ops.attention import flash_attention

    db_path = db_path or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    db = DeviceInfo.load_db(db_path)
    info = db.setdefault(model, DeviceInfo(model))
    b, s, h, d = shape
    flops = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
    all_candidates = tuple(candidates) + (None,)   # None = XLA baseline
    for dtype_name in dtypes:
        dtype = jnp.dtype(dtype_name)
        key = jax.random.key(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, shape, jnp.float32).astype(dtype)
        k = jax.random.normal(kk, shape, jnp.float32).astype(dtype)
        v = jax.random.normal(kv, shape, jnp.float32).astype(dtype)
        totals = {}
        for blocks in all_candidates:
            try:
                bq, bk = blocks if blocks else (None, None)

                # serial scalar feedback into q[0,0,0,0] so loop
                # iterations can't be hoisted/CSE'd; the scalar is an
                # abs-sum over the WHOLE output so the XLA baseline
                # can't be sliced down to one query position (see
                # autotune_gemm)
                def unit(carry, _bq=bq, _bk=bk, _p=blocks is not None):
                    qq, s = carry
                    qq = jax.lax.dynamic_update_slice(
                        qq, (qq[0:1, 0:1, 0:1, 0:1] +
                             (s * 1e-30).astype(qq.dtype)),
                        (0, 0, 0, 0))
                    o = flash_attention(qq, k, v, causal=causal,
                                        block_q=_bq, block_k=_bk,
                                        use_pallas=_p)
                    return qq, jnp.sum(jnp.abs(o), dtype=jnp.float32)

                init = (q, jnp.float32(0.0))

                def run(_unit=unit, _init=init):
                    return inprogram_marginal(_unit, _init, k1=4, k2=32,
                                              repeats=max(runs, 2))

                totals[blocks] = _peak_guard(
                    run(), flops, run,
                    "autotune_flash_attention %s %s" % (dtype_name,
                                                        blocks))
            except Exception:
                totals.pop(blocks, None)
        if totals:
            best = min(totals, key=totals.get)
            info.ratings.setdefault("flash_attention", {})[dtype_name] \
                = {"sec_per_flop": totals[best] / flops,
                   "backend": "xla" if best is None else "pallas",
                   "tiles": None if best is None else list(best)}
    if save:
        DeviceInfo.save_db(db, db_path)
    gemm_choice.cache_clear()
    return info
