"""HOG (histogram of oriented gradients) features.

Parity target: the reference's vendored ``veles/external/hog.py``
(scikit-image lineage) used for classical feature extraction ahead of
MLP workflows.  TPU re-design: pure jnp — gradients, soft binning and
cell pooling express as reshapes + matmuls XLA fuses; jit/vmap-able so
a loader can run it on device for the whole batch.
"""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("orientations", "cell",
                                             "block", "eps"))
def hog(image, orientations=9, cell=8, block=2, eps=1e-6):
    """HOG descriptor of one grayscale image (H, W) → flat features.

    ``cell``: pixels per cell side; ``block``: cells per block side
    (L2-normalized, sliding by one cell).  H and W are truncated to
    whole cells like the reference implementation.
    """
    image = jnp.asarray(image, jnp.float32)
    h, w = image.shape
    # centered gradients (zero at the border, like external/hog.py)
    gx = jnp.zeros_like(image).at[:, 1:-1].set(
        image[:, 2:] - image[:, :-2])
    gy = jnp.zeros_like(image).at[1:-1, :].set(
        image[2:, :] - image[:-2, :])
    # eps inside the sqrt and the double-where on arctan2 keep grads
    # finite on flat regions (gx = gy = 0 would give 0/0 → NaN)
    sq = gx * gx + gy * gy
    magnitude = jnp.sqrt(sq + 1e-12)
    flat_px = sq == 0.0
    gx_safe = jnp.where(flat_px, 1.0, gx)
    # unsigned orientation in [0, π)
    angle = jnp.mod(jnp.where(flat_px, 0.0,
                              jnp.arctan2(gy, gx_safe)), jnp.pi)

    n_cy, n_cx = h // cell, w // cell
    hy, wx = n_cy * cell, n_cx * cell
    magnitude = magnitude[:hy, :wx]
    angle = angle[:hy, :wx]

    # soft-assign each pixel's magnitude to the two nearest bins
    bin_width = jnp.pi / orientations
    pos = angle / bin_width - 0.5
    lo = jnp.floor(pos)
    frac = pos - lo
    lo_bin = jnp.mod(lo, orientations).astype(jnp.int32)
    hi_bin = jnp.mod(lo + 1, orientations).astype(jnp.int32)
    one_hot_lo = jax.nn.one_hot(lo_bin, orientations) * \
        (magnitude * (1.0 - frac))[..., None]
    one_hot_hi = jax.nn.one_hot(hi_bin, orientations) * \
        (magnitude * frac)[..., None]
    votes = one_hot_lo + one_hot_hi            # (hy, wx, orientations)

    cells = votes.reshape(n_cy, cell, n_cx, cell, orientations) \
        .sum(axis=(1, 3))                      # (n_cy, n_cx, o)

    if n_cy < block or n_cx < block:   # image smaller than one block
        blocks = cells[None, None]
    else:
        # block == 1 flows through here too: per-cell normalization,
        # the reference semantics (a global normalize would lose
        # illumination invariance)
        n_by, n_bx = n_cy - block + 1, n_cx - block + 1
        rows = jnp.arange(n_by)[:, None] + jnp.arange(block)[None, :]
        cols = jnp.arange(n_bx)[:, None] + jnp.arange(block)[None, :]
        blocks = cells[rows[:, None, :, None], cols[None, :, None, :]]
        # (n_by, n_bx, block, block, o)
    flat = blocks.reshape(blocks.shape[0], blocks.shape[1], -1)
    norm = jnp.sqrt((flat * flat).sum(-1, keepdims=True) + eps * eps)
    return (flat / norm).reshape(-1)


def hog_batch(images, **kwargs):
    """vmap'd HOG over (B, H, W) (grayscale) or (B, H, W, C) (channels
    averaged first, like luminance pre-pooling)."""
    images = jnp.asarray(images, jnp.float32)
    if images.ndim == 4:
        images = images.mean(axis=-1)
    fn = functools.partial(hog, **kwargs)
    return jax.vmap(fn)(images)
