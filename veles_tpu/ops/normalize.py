"""Mean-dispersion normalization (ref ``ocl/mean_disp_normalizer.cl:1-20``
and unit ``veles/mean_disp_normalizer.py:50``): ``(x - mean) * disp``
elementwise, broadcast over the batch.

Pure jnp: XLA fuses this into whatever consumes it (usually the first
matmul), which is strictly better than the reference's standalone kernel
— a separate Pallas kernel would force an extra HBM round-trip.
"""

import jax.numpy as jnp


def mean_disp_normalize(x, mean, disp, dtype=None):
    """x: (B, ...features); mean/disp: (...features)."""
    out = (x.astype(jnp.float32) - mean.astype(jnp.float32)) \
        * disp.astype(jnp.float32)
    return out.astype(dtype or x.dtype)
