"""Device-side random fills.

Parity target: the reference's xorshift1024* kernels
(``ocl/random.cl:1-125``, ``cuda/random.cu:1-128``) which stream uniform
bits from persistent per-thread states, consumed by
``veles/prng/uniform.py:49`` for dropout masks and stochastic pooling.

TPU re-design: *counter-based* generation — each call derives its stream
from (seed, counter) instead of mutating device state, so results are
reproducible under jit/vmap/pjit and across topology changes (the hard
part flagged in SURVEY §7).  Two paths:

* ``uniform``/``normal`` — ``jax.random`` (threefry), the default;
* ``uniform_pallas`` — the TPU core PRNG (``pltpu.prng_seed`` +
  ``prng_random_bits``) for in-kernel mask generation where a separate
  threefry pass would cost an HBM round-trip (dropout fuses this way).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def uniform(key, shape, dtype=jnp.float32, low=0.0, high=1.0):
    return jax.random.uniform(key, shape, dtype=dtype, minval=low,
                              maxval=high)


def normal(key, shape, dtype=jnp.float32, mean=0.0, stddev=1.0):
    return jax.random.normal(key, shape, dtype=dtype) * stddev + mean


def _uniform_kernel(seed_ref, o_ref, *, low, high):
    # Distinct stream per grid cell: golden-ratio hash of the program id
    # keeps (seed, block) pairs from colliding across *consecutive* seeds
    # the way plain ``seed + i`` would.  uint32 math — the constant
    # overflows int32.
    mixed = (pl.program_id(0).astype(jnp.uint32)
             * jnp.uint32(0x9E3779B9)) \
        ^ pltpu.bitcast(seed_ref[0], jnp.uint32)
    pltpu.prng_seed(pltpu.bitcast(mixed, jnp.int32))
    bits = pltpu.bitcast(pltpu.prng_random_bits(o_ref.shape), jnp.uint32)
    # 24 high bits → [0, 1) float32 (the reference maps its 64-bit output
    # the same way, ocl/random.cl:96-110)
    u01 = (bits >> jnp.uint32(8)).astype(jnp.float32) * (1.0 / (1 << 24))
    o_ref[:] = (u01 * (high - low) + low).astype(o_ref.dtype)


def uniform_pallas(seed, shape, dtype=jnp.float32, low=0.0, high=1.0):
    """Uniform fill via the TPU hardware PRNG.  ``seed`` is an int32
    scalar; same (seed, shape) → same bits.

    The hardware PRNG has no interpret-mode lowering, so off-TPU this
    transparently falls back to threefry (different bits, same
    distribution) — callers get one API everywhere."""
    from veles_tpu.ops import on_tpu
    if not on_tpu():
        key = jax.random.fold_in(jax.random.key(0), jnp.asarray(
            seed, jnp.int32))
        return uniform(key, shape, dtype=dtype, low=low, high=high)
    return _uniform_pallas_tpu(seed, shape, dtype, low, high)


@functools.partial(jax.jit,
                   static_argnames=("shape", "dtype", "low", "high"))
def _uniform_pallas_tpu(seed, shape, dtype=jnp.float32, low=0.0,
                        high=1.0):
    if len(shape) == 1:
        shape2 = (1, shape[0])
    else:
        shape2 = shape
    rows = max(1, shape2[0] // 512)
    bm = shape2[0] // rows if shape2[0] % rows == 0 else shape2[0]
    rows = shape2[0] // bm
    out = pl.pallas_call(
        functools.partial(_uniform_kernel, low=low, high=high),
        grid=(rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((bm,) + shape2[1:],
                               lambda i: (i,) + (0,) * (len(shape2) - 1)),
        out_shape=jax.ShapeDtypeStruct(shape2, dtype),
    )(jnp.asarray(seed, jnp.int32).reshape(1))
    return out.reshape(shape)


def dropout_mask(key, shape, keep_prob, dtype=jnp.float32):
    """Inverted-dropout multiplier: 0 with prob (1-keep), else 1/keep
    (ref Znicz ``dropout.DropoutForward`` semantics)."""
    keep = jax.random.bernoulli(key, keep_prob, shape)
    return keep.astype(dtype) / jnp.asarray(keep_prob, dtype)
