"""Automatic segment stitching: the eager unit-chain fast path.

The eager trainer — the only path the elastic master–slave job layer
can use — historically dispatched one XLA program per unit per
minibatch, plus a host round-trip in the evaluator.  This module closes
that gap without changing graph semantics: at ``Workflow.initialize()``
the linked unit chain is walked and every maximal contiguous run of
*pure jitted* units (the forward chain, the GD chain) is compiled into
ONE XLA program, dispatched as a single call when the run's first unit
fires.  Impure/host units (Loader, Decision, plotters, stream units)
stay as barriers; the gate protocol is untouched — a stitched member
still opens its gate and propagates control normally, its ``run()``
body just becomes a no-op because the segment head already computed it.

Unit protocol: a unit opts in by returning a :class:`StitchStage` from
``stitch_stage()`` (default ``None``).  A stage declares, by Vector
identity, what it consumes, produces, which parameter buffers it reads
and which it DONATES (updated in place on HBM, mirroring the eager GD
units' ``donate_argnums``), plus host scalars fetched per call (traced,
so an LRAdjuster changing learning rates never retraces) and device
metrics (published back as async device scalars — fetched deferred, see
``root.common.engine.metrics_every``).

Loader-headed segments: a stage may additionally carry a ``prelude`` —
a host callable the segment runs *before* fetching scalars/inputs at
every dispatch.  This is how the device-resident input pipeline fuses
into the first forward program: ``FullBatchLoader.stitch_stage()``
keeps the serving bookkeeping (offset advance, epoch flags, retry
accounting) as the prelude and turns the minibatch fill into an
in-program gather over the HBM-resident dataset, so
``minibatch_data``/``minibatch_labels`` are produced directly on
device with zero per-step host→device traffic (see
``docs/engine_fast_path.md`` § Input pipeline).

Segment eligibility (checked per chain link ``u → v``):

* ``u.links_to == {v}`` and ``v.links_from == {u}`` — strictly linear
  control flow inside the segment (head may have any fan-in, tail any
  fan-out);
* ``v`` neither ignores its gate nor wants a thread;
* ``v.gate_block`` is a constant-False cell and ``v.gate_skip`` is
  either constant-False or the SAME shared cell as the head's (the
  GD chain's per-class skip gate), so a skipped head implies skipped
  members and vice versa.

``root.common.engine.stitch = off`` restores the seed per-unit
execution path byte for byte (segments are simply not built).

Pod mode (:mod:`veles_tpu.pod`): a segment's fused program can be
recompiled for a device mesh via :meth:`StitchSegment.set_shardings`
— same plan, explicit in/out shardings, gradient aggregation becomes
an in-program ``psum`` — with the bound :class:`~veles_tpu.pod
.runtime.PodRuntime` consulted before every dispatch (elastic
chip-kill reshard) and supplying the ledger's shard/psum columns.

Epoch mode (:mod:`veles_tpu.epoch_scan`): with
``root.common.engine.epoch_scan`` set, a loader-headed segment's head
hands whole K-step windows to the bound
:class:`~veles_tpu.epoch_scan.EpochScanRunner` instead of dispatching
per step — the segments' stages become a ``lax.scan`` body and this
module's per-step programs stay the fallback (and the ``off`` shape).
"""

import time

import jax

from veles_tpu import prof, trace
from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.memory import Vector
from veles_tpu.mutable import Bool


def enabled():
    """The config switch, read at call time so ``run()`` honors flips
    between initialize and run."""
    value = root.common.engine.get("stitch", "on")
    if isinstance(value, str):
        return value.lower() not in ("off", "0", "false", "no")
    return bool(value)


class StitchStage(object):
    """One unit's contribution to a stitched program.

    ``fn(tensors)`` is a pure jax-traceable callable receiving a dict
    with every declared name (consumes + params + donated + scalars)
    and returning a dict with every ``produces`` name, every ``donated``
    name (the updated buffer) and every ``metrics`` name (device
    scalars assigned onto the unit after the call).
    """

    __slots__ = ("unit", "fn", "consumes", "produces", "params",
                 "donated", "scalars", "metrics", "prelude", "health",
                 "health_spec")

    def __init__(self, unit, fn, consumes=None, produces=None,
                 params=None, donated=None, scalars=None, metrics=(),
                 prelude=None, health=None):
        self.unit = unit
        self.fn = fn
        self.consumes = dict(consumes or {})
        self.produces = dict(produces or {})
        self.params = dict(params or {})
        self.donated = dict(donated or {})
        #: callable → {name: python scalar}, fetched at every dispatch
        self.scalars = scalars
        self.metrics = tuple(metrics)
        #: host callable run before every dispatch (serving bookkeeping
        #: of a loader-headed segment); runs BEFORE scalars are fetched
        self.prelude = prelude
        #: optional traced ``(tensors, out) -> {grad_norm, weight_norm,
        #: update_norm}`` declaring this stage's health stats — the
        #: unit-specific half of the ``engine.health`` instrumentation
        #: (veles_tpu.watch.health); stages without it get the generic
        #: donated-pair norms, grad_norm omitted
        self.health = health
        #: the HealthGroup attached by watch.health.instrument_stages
        #: (None on an uninstrumented build — i.e. health=off)
        self.health_spec = None

    def vectors(self):
        for group in (self.consumes, self.produces, self.params,
                      self.donated):
            for vec in group.values():
                yield vec


class EnforcedProgram(object):
    """The AOT compile-and-enforce idiom shared by per-step segments
    and epoch-scan window programs (:mod:`veles_tpu.epoch_scan`):
    the host keeps ``_compiled`` / ``_fingerprint`` /
    ``_compiled_cache`` and a ``_compile(args, steady=)`` that lowers,
    AOT-compiles and registers the cost profile.  A drifted call
    raises ``TypeError`` from the enforcing executable — exactly the
    silent steady-state retrace the jit path would have absorbed.  A
    signature seen BEFORE swaps its cached executable back in
    (alternation is not a recompile, and was flagged when it first
    appeared); a NEW one compiles + counts + flags (WARNING, or
    PreflightError under the strict knob — raised AFTER the ledger
    counted, so /metrics and bench recompile columns never contradict
    the error).  Either way correctness never depends on the sentinel
    mode; donated buffers were not consumed by the failed call."""

    def _recompile_site(self):
        """The site string a flagged steady-state recompile names."""
        raise NotImplementedError

    def _dispatch_enforced(self, args):
        """Run the enforcing executable (compiling on first use and
        recovering from signature drift).  Returns ``(result, tic)``
        where ``tic`` was read right before whichever call succeeded,
        so warmup/recovery compiles never pollute the dispatch
        clock."""
        if self._compiled is None:
            # first dispatch: trace+compile once, run the AOT
            # executable from here on — it enforces the signature
            self._compile(args)
            tic = time.perf_counter_ns()
            return self._compiled(*args), tic
        tic = time.perf_counter_ns()
        try:
            return self._compiled(*args), tic
        except TypeError as exc:
            self.debug("retrace detail: %s", exc)
            old_fp = self._fingerprint
            fp = prof.fingerprint(args)
            cached = self._compiled_cache.get(fp)
            if cached is not None:
                self._compiled = cached
                self._fingerprint = fp
            else:
                self._compile(args, steady=True)
                prof.flag_recompile(self._recompile_site(), old_fp,
                                    fp, logger=self)
            tic = time.perf_counter_ns()
            return self._compiled(*args), tic


class StitchSegment(Logger, EnforcedProgram):
    """A maximal run of stitchable units compiled into one program."""

    def __init__(self, units, stages):
        super(StitchSegment, self).__init__()
        self.units = list(units)
        self.stages = list(stages)
        self.head = self.units[0]
        self.dispatches = 0
        self._computed = set()
        self._head_absorbed_ = False
        #: epoch-scan binding (veles_tpu.epoch_scan.EpochScanRunner or
        #: None): a loader-headed segment consults it before every
        #: per-step dispatch — when the epoch_scan knob allows, the
        #: runner executes a whole K-step window in ONE dispatch and
        #: absorbs this pass (head included for the GD segment)
        self.epoch_runner = None
        self._member_ids = frozenset(id(u) for u in self.units[1:])
        #: the health groups riding this program's metrics (non-empty
        #: only when watch.health.instrument_stages ran over the
        #: stages before compile — i.e. engine.health != off)
        self._health_groups = [stage.health_spec for stage in stages
                               if stage.health_spec is not None]
        self._build_plan()
        self._jitted = jax.jit(self._program, donate_argnums=(2,))
        #: pod binding (veles_tpu.pod.runtime.PodRuntime or None):
        #: consulted before every dispatch (chip-kill / reshard hook),
        #: supplies the shard count + per-dispatch psum-byte estimate
        #: for the ledger's axis dimension and the per-shard lanes
        self.pod = None
        #: the AOT executable installed by the first dispatch; it
        #: ENFORCES the traced signature, so a drifted call raises
        #: (and the recompile sentinel flags it) instead of silently
        #: retracing into an unexplained slow step
        self._compiled = None
        self._fingerprint = None
        #: fingerprint -> executable, mirroring the jit cache the AOT
        #: path replaced: a segment legitimately ALTERNATING between
        #: known signatures swaps executables (flagged once when each
        #: new signature first appeared) instead of recompiling — and
        #: being re-flagged — on every flip
        self._compiled_cache = {}
        #: performance-ledger entry (veles_tpu.prof): cost_analysis
        #: flops/bytes from the compiled program + dispatch clocks
        self.prof_entry = prof.ledger.entry("segment",
                                            "+".join(self.names))
        #: static span args, allocated once (the dispatch hot path
        #: must not build a dict per call)
        self._trace_args = {"segment": "+".join(self.names)}

    @property
    def names(self):
        return [u.name for u in self.units]

    def __repr__(self):
        return "<StitchSegment %s>" % "+".join(self.names)

    # -- plan ---------------------------------------------------------------
    def _build_plan(self):
        produced = {}                 # id(vec) -> producing stage index
        input_vecs = []               # segment-external reads, ordered
        input_ids = {}
        ro_vecs, don_vecs = [], []
        ro_slots, don_slots, scalar_slots = [], [], []
        scalar_fetchers = []
        metric_spec = []
        for si, stage in enumerate(self.stages):
            for vec in stage.consumes.values():
                if id(vec) not in produced and id(vec) not in input_ids:
                    input_ids[id(vec)] = len(input_vecs)
                    input_vecs.append(vec)
            ro_slots.append([])
            for name, vec in sorted(stage.params.items()):
                ro_slots[si].append((len(ro_vecs), name))
                ro_vecs.append(vec)
            don_slots.append([])
            for name, vec in sorted(stage.donated.items()):
                don_slots[si].append((len(don_vecs), name))
                don_vecs.append(vec)
            scalar_slots.append(None)
            if stage.scalars is not None:
                names = tuple(sorted(stage.scalars()))
                base = sum(len(n) for _stage, n in scalar_fetchers)
                scalar_slots[si] = [(base + i, n)
                                    for i, n in enumerate(names)]
                scalar_fetchers.append((stage, names))
            for name, vec in stage.produces.items():
                produced[id(vec)] = si
            for name in stage.metrics:
                metric_spec.append((stage.unit, name))
        # Donation soundness: a donated buffer must be owned by exactly
        # ONE stage and must not double as an env input / read-only
        # param / produced value anywhere in the segment — the call
        # would pass the same jax.Array as a donated leaf AND a live
        # alias (donation invalidates the alias), or a later stage
        # would read a stale pre-update buffer.  Reject loudly;
        # build_segments falls back to per-unit dispatch.
        don_ids = [id(vec) for vec in don_vecs]
        aliased = (len(don_ids) != len(set(don_ids))
                   or any(i in input_ids for i in don_ids)
                   or any(i in produced for i in don_ids)
                   or any(id(vec) in don_ids for vec in ro_vecs))
        if aliased:
            raise ValueError(
                "segment %s aliases a donated Vector with another "
                "read/write slot — not stitchable" % "+".join(
                    u.name for u in self.units))
        # publish EVERY produced vector: downstream host units (plotters,
        # image saver, the next segment) read through Vector coherence
        output_vecs, seen = [], set()
        for stage in self.stages:
            for vec in stage.produces.values():
                if id(vec) not in seen:
                    seen.add(id(vec))
                    output_vecs.append(vec)
        self._input_vecs = input_vecs
        self._ro_vecs, self._don_vecs = ro_vecs, don_vecs
        self._ro_slots, self._don_slots = ro_slots, don_slots
        self._scalar_slots = scalar_slots
        self._scalar_fetchers = scalar_fetchers
        self._output_vecs = output_vecs
        self._metric_spec = metric_spec

    def _program(self, inputs, ro, don, scalars):
        env = {id(vec): arr
               for vec, arr in zip(self._input_vecs, inputs)}
        new_don = list(don)
        metrics = []
        for si, stage in enumerate(self.stages):
            tensors = {name: env[id(vec)]
                       for name, vec in stage.consumes.items()}
            for pos, name in self._ro_slots[si]:
                tensors[name] = ro[pos]
            for pos, name in self._don_slots[si]:
                tensors[name] = don[pos]
            if self._scalar_slots[si]:
                for pos, name in self._scalar_slots[si]:
                    tensors[name] = scalars[pos]
            out = stage.fn(tensors)
            for name, vec in stage.produces.items():
                env[id(vec)] = out[name]
            for pos, name in self._don_slots[si]:
                new_don[pos] = out[name]
            for name in stage.metrics:
                metrics.append(out[name])
        outputs = [env[id(vec)] for vec in self._output_vecs]
        return outputs, new_don, metrics

    def _recompile_site(self):
        return "segment:%s" % "+".join(self.names)

    @property
    def recompiles(self):
        """Steady-state recompiles of THIS segment's program (ledger
        entry; the sentinel flags each one as it happens)."""
        return self.prof_entry.recompiles

    @property
    def has_prelude(self):
        """True for loader-headed segments (a stage carries host
        serving bookkeeping executed before each dispatch)."""
        return any(stage.prelude is not None for stage in self.stages)

    # -- pod sharding (veles_tpu.pod) ---------------------------------------
    def set_shardings(self, in_shardings, out_shardings):
        """Rebuild the fused program's jit wrapper with explicit mesh
        shardings (the pod runtime's one-pod-one-program install / a
        chip-kill reshard).  The pytrees must match the ``_program``
        signature: ``in_shardings = (inputs, ro, don, scalars-prefix)``
        and ``out_shardings = (outputs, new_don, metrics)``.

        Every AOT executable compiled for the OLD placement is
        dropped: a resharded mesh is a new program by definition, and
        the stale executables would reject (ValueError, not the
        retrace TypeError) the newly-placed arguments."""
        self._jitted = jax.jit(self._program, donate_argnums=(2,),
                               in_shardings=in_shardings,
                               out_shardings=out_shardings)
        self._compiled = None
        self._fingerprint = None
        self._compiled_cache = {}

    def clear_shardings(self):
        """Back to the implicit single-device jit (pod uninstall)."""
        self._jitted = jax.jit(self._program, donate_argnums=(2,))
        self._compiled = None
        self._fingerprint = None
        self._compiled_cache = {}
        self.pod = None

    # -- compilation --------------------------------------------------------
    def _compile(self, args, steady=False):
        """Lower + AOT-compile the fused program for ``args``'
        signature, fingerprint it, and register the executable's cost
        profile (``cost_analysis`` flops / bytes,
        ``memory_analysis``) with the performance ledger.  The
        ``compile`` instant carries the cost in its args so an
        exported trace stays a self-contained perf report
        (``python -m veles_tpu.prof run.json``)."""
        lowered = self._jitted.lower(*args)
        compiled = lowered.compile()
        self._fingerprint = prof.fingerprint(args)
        self._compiled = compiled
        self._compiled_cache[self._fingerprint] = compiled
        cost, span_args = prof.span_cost_args(compiled,
                                              self._trace_args)
        prof.ledger.record_compile(self.prof_entry, cost=cost,
                                   steady=steady)
        if steady:
            # in-band steadiness: the offline report must not have to
            # guess which compile events were legitimate warmup (a
            # rebuild_stitching re-walk) vs flagged retraces
            span_args["recompile"] = True
        # the instant marks warmup (or a flagged retrace) on the
        # timeline so a report never mistakes it for steady state
        trace.instant("segment", "compile", span_args)
        return compiled

    # -- execution ----------------------------------------------------------
    def execute(self):
        """Dispatch the whole segment as one program and publish."""
        if self.pod is not None:
            # pod pre-dispatch: the chaos ``pod_chip`` site — a
            # chip_kill here shrinks the mesh, reshards every resident
            # buffer and swaps THIS segment's program before the args
            # below are gathered, so the dispatch proceeds on the
            # surviving chips from the last in-HBM-consistent step
            self.pod.pre_dispatch(self)
        with trace.span("segment", "dispatch", self._trace_args):
            # the nested host_prep span breaks out the host share of a
            # turnaround (preludes + devmem gathering + scalar
            # fetches) from the jitted call, so a prelude-heavy run is
            # visible in the span leaderboard — the inter-dispatch
            # "host gap" in trace_report() deliberately measures only
            # the time BETWEEN turnarounds
            with trace.span("segment", "host_prep", self._trace_args):
                # host preludes first (a loader head advances its
                # serving state here — the scalars fetched below must
                # see the NEW offsets)
                for stage in self.stages:
                    if stage.prelude is not None:
                        stage.prelude()
                inputs = tuple(vec.devmem for vec in self._input_vecs)
                ro = tuple(vec.devmem for vec in self._ro_vecs)
                don = tuple(vec.devmem for vec in self._don_vecs)
                scalars = []
                for stage, names in self._scalar_fetchers:
                    values = stage.scalars()
                    # ints stay ints: a python int traces as (weak)
                    # int32, so index-like scalars (the loader's
                    # offset/size) keep exact integer semantics —
                    # float32 would silently round offsets beyond
                    # 2**24.  Per-name types are stable across calls,
                    # so this never retraces.
                    scalars.extend(
                        values[n] if isinstance(values[n], int)
                        else float(values[n]) for n in names)
            args = (inputs, ro, don, tuple(scalars))
            (outputs, new_don, metrics), tic = \
                self._dispatch_enforced(args)
            for vec, arr in zip(self._output_vecs, outputs):
                vec.devmem = arr
            for vec, arr in zip(self._don_vecs, new_don):
                vec.devmem = arr
            for (unit, name), value in zip(self._metric_spec, metrics):
                setattr(unit, name, value)
            self.dispatches += 1
            toc = time.perf_counter_ns()
            pod = self.pod
            prof.ledger.record_dispatch(
                self.prof_entry, toc - tic,
                psum_bytes=pod.segment_psum_bytes(self)
                if pod is not None else 0,
                all_to_all_bytes=pod.segment_all_to_all_bytes(self)
                if pod is not None else 0)
            if pod is not None and trace.enabled():
                # per-shard lanes: the host turnaround mirrored onto
                # one synthetic tid per mesh shard under the "pod"
                # role, so the merged Perfetto timeline renders one
                # pod as ONE pid with a lane per chip (host clocks —
                # per-chip device timelines need the jax.profiler
                # bridge, trace.device_trace())
                for shard in range(pod.shards):
                    trace.complete("pod", "shard_dispatch", tic,
                                   toc - tic, self._trace_args,
                                   role="pod", tid=shard)
            if self._health_groups:
                # one instrumented dispatch = one train step's stats
                # landed (strict mode fetches at its cadence there —
                # a HealthError propagates out of this dispatch)
                from veles_tpu.watch import health as _health
                _health.monitor.observe(steps=1)
            self._computed = set(self._member_ids)

    def member_run(self, unit):
        """The per-unit hook: the head dispatches the program, members
        are no-ops for the pass the head computed.  A member firing
        without a preceding head dispatch (out-of-band scheduling)
        falls back to its own eager ``run()`` — correctness first."""
        if unit is self.head:
            if self._head_absorbed_:
                # an epoch-scan window already ran this segment's K
                # steps in-program (absorb_pass(include_head=True))
                self._head_absorbed_ = False
                return
            runner = self.epoch_runner
            if runner is not None and runner.try_window(self):
                return
            self.execute()
            return
        if id(unit) in self._computed:
            self._computed.discard(id(unit))
            return
        unit.run()

    def absorb_pass(self, include_head=False):
        """Mark one whole graph pass of this segment as computed by an
        epoch-scan window: members no-op, and with ``include_head``
        the head's next firing no-ops too (the GD segment, whose K
        steps the window's scan body already ran)."""
        self._computed = set(self._member_ids)
        if include_head:
            self._head_absorbed_ = True

    def reset_pass(self):
        """Forget any half-consumed pass (an interrupted run left
        members unconsumed): the next member firing without a fresh
        head dispatch must take the eager fallback, not a stale
        no-op.  Workflow.run() calls this before each drain."""
        self._computed = set()
        self._head_absorbed_ = False

    def detach(self):
        for unit in self.units:
            unit.attach_stitch_segment(None)


# -- builders ---------------------------------------------------------------

def _constant_false(cell):
    """A plain Bool(False) with no expression: the gate can never flip
    under this segment's feet."""
    return (type(cell) is Bool and cell._expr is None
            and cell._value is False)


def _gate_compatible(head, unit):
    if unit.ignores_gate or unit.wants_thread:
        return False
    if not _constant_false(unit.gate_block):
        return False
    return (_constant_false(unit.gate_skip)
            or unit.gate_skip is head.gate_skip)


def _stage_of(unit, cache):
    if id(unit) not in cache:
        maker = getattr(unit, "stitch_stage", None)
        stage = None
        if callable(maker):
            try:
                stage = maker()
            except Exception:
                unit.exception("stitch_stage() of %r failed; unit "
                               "stays on the per-unit path", unit)
                stage = None
        cache[id(unit)] = stage
    return cache[id(unit)]


def _vectors_ready(stage, device):
    for vec in stage.vectors():
        if not isinstance(vec, Vector) or not vec:
            return False
        if vec.device is None:
            vec.initialize(device)
    return True


def build_segments(workflow):
    """Walk the control graph and return the list of compiled
    :class:`StitchSegment`\\ s (empty when stitching is off, the device
    is interpret/absent, or no chain qualifies).  Members get their
    segment attached via the public ``Unit.attach_stitch_segment``."""
    from veles_tpu.watch import health as watch_health
    # every (re)build owns the process-wide health monitor: disarm it
    # FIRST, so a knob flip to off, a stitch-off rebuild or an
    # interpret-device fallback can never leave the PREVIOUS build's
    # groups armed (a stale strict monitor would read dead units'
    # attrs — or raise — at the next Decision class close);
    # monitor.install() below re-arms when this build instruments
    watch_health.monitor.reset()
    if not enabled():
        return []
    device = getattr(workflow, "device", None)
    if device is None or getattr(device, "is_interpret", True):
        return []
    health_mode = watch_health.health_mode()
    health_groups = []
    cache = {}
    assigned = set()
    segments = []
    for unit in workflow.units_in_dependency_order():
        if id(unit) in assigned or unit is workflow:
            continue
        head_stage = _stage_of(unit, cache)
        if head_stage is None or unit.wants_thread \
                or getattr(unit, "force_numpy", False):
            continue
        chain = [unit]
        stages = [head_stage]
        cur = unit
        while True:
            targets = list(cur.links_to)
            if len(targets) != 1:
                break
            nxt = targets[0]
            if id(nxt) in assigned or len(nxt.links_from) != 1 \
                    or not _gate_compatible(unit, nxt):
                break
            stage = _stage_of(nxt, cache)
            if stage is None:
                break
            chain.append(nxt)
            stages.append(stage)
            cur = nxt
        if len(chain) < 2:
            continue
        blocked = [s.unit.name for s in stages
                   if not _vectors_ready(s, device)]
        if blocked:
            workflow.info(
                "not stitching %s: %s exposes an empty/unallocated "
                "Vector (initialize() the unit first); chain stays on "
                "per-unit dispatch",
                "+".join(u.name for u in chain), ", ".join(blocked))
            continue
        groups = []
        if health_mode != "off":
            # fold the health stats into the stage fns BEFORE the
            # segment compiles — they become extra outputs of the same
            # program (zero extra dispatches); health=off skips this
            # entirely, leaving the build byte-identical
            groups = watch_health.instrument_stages(stages)
        try:
            segment = StitchSegment(chain, stages)
        except Exception:
            workflow.exception(
                "failed to stitch segment %s; falling back to "
                "per-unit dispatch", [u.name for u in chain])
            continue
        health_groups.extend(groups)
        for member in chain:
            member.attach_stitch_segment(segment)
            assigned.add(id(member))
        segments.append(segment)
    if health_mode != "off" and health_groups:
        watch_health.monitor.install(health_groups, health_mode)
        workflow.info(
            "health telemetry %s: %d param group(s): %s",
            health_mode, len(health_groups),
            ", ".join(g.name for g in health_groups))
    if segments:
        workflow.info(
            "stitched %d segment(s): %s",
            len(segments),
            "; ".join("+".join(s.names) for s in segments))
    return segments
