"""Unit: the dataflow + controlflow node of a workflow graph.

Parity target: reference ``veles/units.py`` —

* ``IUnit`` protocol ``initialize()/run()/stop()`` (``units.py:59-77``);
* control-flow edges via ``link_from`` (``units.py:554``) with gate
  semantics ``open_gate`` / ``gate_block`` / ``gate_skip``
  (``units.py:524-545, 281-305``): a unit runs when ALL of its incoming
  control links have fired; ``gate_block`` suppresses run+propagation,
  ``gate_skip`` suppresses run but propagates;
* data edges via ``link_attrs`` (``units.py:638``) backed by
  :class:`veles_tpu.mutable.LinkableAttribute`;
* ``demand()`` declared-dependency checking (``units.py:682``);
* per-unit wall-time accounting (``units.py:166-196``);
* class auto-registration (``veles/unit_registry.py:51``).

TPU re-design: the reference trampolines ``_check_gate_and_run`` through a
Twisted thread pool (``units.py:496-505``) because each unit's ``run()``
blocks on an eager OpenCL/CUDA queue.  Under JAX, device work is
asynchronously dispatched and the host side is cheap, so the scheduler is an
*iterative work queue* owned by the workflow: ``run_dependent`` enqueues
ready units, the workflow loop pops-and-runs.  This is deterministic
(stable, FIFO ordering), cannot blow the stack on million-iteration
Repeater loops, and keeps the graph semantics bit-identical.  Host-blocking
units (loaders doing disk IO, plotters) may opt into background execution
via ``wants_thread = True``.
"""

import sys
import threading
import time

from veles_tpu import trace
from veles_tpu.config import root
from veles_tpu.distributable import Distributable
from veles_tpu.mutable import Bool, LinkableAttribute


class MissingDemandedAttributes(AttributeError):
    """A demanded attribute is not yet available.  Distinct from plain
    AttributeError so Workflow.initialize's partial-init requeue does not
    mask genuine bugs inside unit ``initialize()`` bodies."""


class UnitRegistry(type):
    """Metaclass auto-registering every Unit subclass
    (ref ``veles/unit_registry.py:51``)."""

    units = {}
    #: Optional name→class mapping used by MappedUnitRegistry clients
    #: (package export/import, frontend generation).
    mapped = {}

    def __init__(cls, name, bases, namespace):
        super(UnitRegistry, cls).__init__(name, bases, namespace)
        if name != "Unit" and not namespace.get("hide_from_registry", False):
            UnitRegistry.units[name] = cls
            mapping = namespace.get("MAPPING")
            if mapping:
                UnitRegistry.mapped[mapping] = cls
            # reference-doc spellings (e.g. "conv_str", "norm") map to
            # the same class (manualrst_veles_workflow_parameters.rst
            # :467-505 uses both long and short names)
            for alias in namespace.get("MAPPING_ALIASES", ()):
                UnitRegistry.mapped[alias] = cls


class IUnit(object):
    """The unit contract (ref ``units.py:59-77``).  Documented here; duck
    typing is verified by :meth:`Unit.verify_interface` at initialize time
    (replacing the reference's zope.interface machinery,
    ``veles/verified.py:45``)."""

    def initialize(self, **kwargs):
        """Allocate buffers / compile; may be re-called after re-linking."""

    def run(self):
        """Do one step of work."""

    def stop(self):
        """Called once when the workflow is shutting down."""


class Unit(Distributable, metaclass=UnitRegistry):
    """Dataflow+controlflow graph node."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.name = kwargs.get("name", self.__class__.__name__)
        self.view_group = kwargs.get("view_group", "PLUMBING")
        #: incoming control edges: {unit: fired?}
        self.links_from = {}
        #: outgoing control edges: {unit: True}
        self.links_to = {}
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        #: set by initialize(); cleared when links change
        self._is_initialized = False
        #: declared-required attribute names (ref demand units.py:682)
        self._demanded = set()
        self.ignores_gate = False
        #: wants_thread: host-blocking units may run in the workflow's
        #: background executor instead of the main scheduler loop.
        self.wants_thread = False
        #: accumulated run() wall-time (ref ``units.py:166-196`` kept the
        #: equivalent in a class-level ``timers`` dict keyed by id; an
        #: instance float avoids the id-reuse/leak hazard and pickles with
        #: the unit so stats survive snapshots)
        self.total_run_time = 0.0
        super(Unit, self).__init__(**kwargs)
        self._workflow_ref_ = None
        if workflow is not None:
            workflow.add_ref(self)

    def init_unpickled(self):
        super(Unit, self).init_unpickled()
        self._gate_lock_ = threading.Lock()
        self._run_lock_ = threading.Lock()
        # stitched-segment membership is transient (segments hold jitted
        # programs); Workflow.initialize rebuilds it after unpickling
        self._stitch_segment_ = None
        if not hasattr(self, "_workflow_ref_"):
            # standalone unpickle; Workflow.__setstate__ re-links members
            self._workflow_ref_ = None
        # data-link descriptors are class-level and process-local
        LinkableAttribute.reinstall(self)

    def __repr__(self):
        return '<%s "%s">' % (self.__class__.__name__, self.name)

    # -- workflow membership ----------------------------------------------
    @property
    def workflow(self):
        return self._workflow_ref_

    @workflow.setter
    def workflow(self, value):
        # Strong ref (the workflow↔unit cycle is collectable); trailing
        # underscore keeps it out of pickles — Workflow.__setstate__
        # re-links its members.
        self._workflow_ref_ = value

    @property
    def is_initialized(self):
        return self._is_initialized

    @property
    def is_master(self):
        wf = self.workflow
        return wf.is_master if wf is not None else False

    @property
    def is_slave(self):
        wf = self.workflow
        return wf.is_slave if wf is not None else False

    @property
    def is_standalone(self):
        wf = self.workflow
        return wf.is_standalone if wf is not None else True

    # -- graph construction -------------------------------------------------
    def link_from(self, *src_units):
        """Add control edges ``src → self`` (ref ``units.py:554``)."""
        for src in src_units:
            self.links_from[src] = False
            src.links_to[self] = True
        self._is_initialized = False
        return self

    def unlink_from(self, *src_units):
        for src in src_units:
            self.links_from.pop(src, None)
            src.links_to.pop(self, None)
        return self

    def unlink_all(self):
        for src in list(self.links_from):
            self.unlink_from(src)
        for dst in list(self.links_to):
            dst.unlink_from(self)
        return self

    def link_attrs(self, other, *names, two_way=False):
        """Add data edges: alias ``self.<dst>`` to ``other.<src>``
        (ref ``units.py:638``).  Each name is either a string (same name on
        both sides) or a ``(dst_name, src_name)`` pair."""
        for name in names:
            if isinstance(name, tuple):
                dst_name, src_name = name
            else:
                dst_name = src_name = name
            LinkableAttribute.link(self, dst_name, other, src_name,
                                   two_way=two_way)
        return self

    def demand(self, *names):
        """Declare attributes that must be linked/set before initialize
        (ref ``units.py:682``)."""
        self._demanded.update(names)

    # -- static introspection (consumed by veles_tpu.analyze) ---------------
    def unlinked_demands(self):
        """Demanded attribute names that are neither link_attrs()-linked
        nor already set — what the graph doctor reports as V-G01 and
        what initialize() would requeue on forever."""
        linked = self.__dict__.get("_linked_attrs", {})
        out = []
        for name in sorted(self._demanded):
            if name in linked:
                continue    # producer may fill the value at init time
            try:
                if getattr(self, name) is not None:
                    continue
            except AttributeError:
                pass
            out.append(name)
        return out

    def gate_topology(self):
        """Static gate picture: incoming/outgoing edge names, the gate
        mode, and current gate expressions — describe() builds on it
        and the graph doctor's report mirrors it."""
        return {
            "incoming": [u.name for u in self.links_from],
            "outgoing": [u.name for u in self.links_to],
            "ignores_gate": bool(self.ignores_gate),
            "gate_block": bool(self.gate_block),
            "gate_skip": bool(self.gate_skip),
        }

    @classmethod
    def reload(cls):
        """Hot-patch this unit's class from its edited source file —
        live-patching a long training run (parity:
        ``/root/reference/veles/units.py:672``, pydev xreload).

        Re-design without the vendored xreload: ``importlib.reload``
        of the defining module, then every LIVE instance of each class
        the module re-defines is re-pointed (``__class__``
        reassignment) at the reloaded class object, so edited method
        bodies take effect on the very next ``run()``.  Already-traced
        jitted programs keep running the old trace until rebuilt —
        state (attributes, links, gates) is untouched.  Returns the
        number of re-pointed instances."""
        import gc
        import importlib

        module = sys.modules[cls.__module__]
        old_classes = {name: obj for name, obj in vars(module).items()
                       if isinstance(obj, type)
                       and obj.__module__ == module.__name__}
        new_module = importlib.reload(module)
        old_to_new = {}
        for name, old in old_classes.items():
            new = getattr(new_module, name, None)
            if isinstance(new, type) and new is not old:
                old_to_new[old] = new
        if not old_to_new:
            return 0
        remapped = 0
        # ONE heap traversal for every re-defined class — the heap can
        # hold millions of objects mid-training-run
        for obj in gc.get_objects():
            new = old_to_new.get(type(obj))
            if new is not None:
                try:
                    obj.__class__ = new
                    remapped += 1
                except TypeError:
                    # incompatible layout (__slots__ change): leave
                    # the instance on the old class rather than
                    # corrupt it
                    pass
        return remapped

    # -- segment stitching (the eager fast path, veles_tpu.stitch) ----------
    def stitch_stage(self):
        """Return this unit's pure :class:`veles_tpu.stitch.StitchStage`
        for segment stitching, or ``None`` (the default: the unit is a
        barrier — host work, dynamic control, or no pure form)."""
        return None

    def attach_stitch_segment(self, segment):
        """Public face of the segment-membership bookkeeping (the lint
        pack's V-L02 rule keeps the builder from reaching into
        ``_stitch_segment_`` directly)."""
        self._stitch_segment_ = segment

    @property
    def stitch_segment(self):
        return self._stitch_segment_

    # -- interface verification (replaces zope.interface, verified.py:45) --
    def verify_interface(self):
        missing = [n for n in self._demanded
                   if getattr(self, n, None) is None]
        if missing:
            raise MissingDemandedAttributes(
                "%r is missing demanded attributes: %s — link_attrs() them "
                "from a producer unit" % (self, ", ".join(sorted(missing))))

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, **kwargs):
        self.verify_interface()
        self._is_initialized = True

    def run(self):
        pass

    def stop(self):
        pass

    # -- gate semantics (ref units.py:524-545, 782-803) ---------------------
    def open_gate(self, src):
        """Mark the edge from ``src`` as fired; return True when ALL
        incoming edges have fired (and reset them)."""
        with self._gate_lock_:
            if src is not None and src in self.links_from:
                self.links_from[src] = True
            if not all(self.links_from.values()):
                return False
            for key in self.links_from:
                self.links_from[key] = False
            return True

    def reset_gate(self):
        """Re-arm this unit's gate: mark every incoming edge unfired.

        The public face of the gate bookkeeping — FireStarter re-arms
        loop members through it and Repeater's any-edge gate resets
        through it, instead of either reaching into ``_gate_lock_``/
        ``links_from`` directly (the lint pack's V-L02/V-L04 rules
        enforce this)."""
        with self._gate_lock_:
            for key in self.links_from:
                self.links_from[key] = False

    def _check_gate_and_run(self, src):
        """The hot loop body (ref ``units.py:782``)."""
        if not self.open_gate(src) and not self.ignores_gate:
            return
        if bool(self.gate_block):
            return
        # Duplicate concurrent triggers are discarded, not queued —
        # including their downstream propagation, exactly like the
        # reference ("If previous run has not yet finished, discard
        # notification", ``units.py:793-801``, which returns before
        # run_dependent).  Only reachable when background (wants_thread)
        # units fire the same unit from two threads.
        if not self._run_lock_.acquire(blocking=False):
            return
        try:
            if not bool(self.gate_skip):
                self.run_wrapped()
        finally:
            self._run_lock_.release()
        self.run_dependent()

    def run_wrapped(self):
        """run() with timing + stop-check (ref ``units.py:184-196``).

        When the workflow runs with segment stitching active, a
        stitched unit executes through its segment here: the head
        dispatches the whole fused program, members no-op for that
        pass.  Direct ``unit.run()`` calls (tests, manual drives)
        bypass this and keep the per-unit eager path."""
        wf = self.workflow
        if wf is not None and wf.stopped:
            return
        segment = self._stitch_segment_
        tic = time.time()
        try:
            if segment is not None and wf is not None \
                    and getattr(wf, "stitch_active", False):
                # the segment's own "segment" span covers the fused
                # dispatch; member no-ops are not worth events
                segment.member_run(self)
            else:
                with trace.span("unit", self.name):
                    self.run()
        except Exception:
            self.error("failed to run %r", self)
            if wf is not None:
                wf.on_unit_failed(self)
            raise
        finally:
            elapsed = time.time() - tic
            self.total_run_time += elapsed
            if self.__class__.__name__ in root.common.get("timings", set()):
                self.debug("%s ran in %.3f ms", self.name, elapsed * 1e3)

    def run_dependent(self):
        """Enqueue all downstream units on the workflow scheduler
        (ref ``units.py:485-505``, re-designed as an iterative queue)."""
        wf = self.workflow
        if wf is None or wf.stopped:
            return
        for dst in self.links_to:
            wf.schedule(dst, self)

    # -- misc ---------------------------------------------------------------
    @property
    def run_time(self):
        return self.total_run_time

    def describe(self):
        topo = self.gate_topology()
        return {
            "name": self.name,
            "class": type(self).__name__,
            "links_from": topo["incoming"],
            "links_to": topo["outgoing"],
            "gate_block": topo["gate_block"],
            "gate_skip": topo["gate_skip"],
        }
