"""Pickling protocol and master–slave data-exchange interface.

Parity target: reference ``veles/distributable.py`` —

* ``Pickleable`` (``distributable.py:48``): attributes whose names end with
  ``_`` are excluded from pickles; ``init_unpickled()`` recreates them after
  construction *and* after unpickling.  This single convention is what makes
  whole-workflow snapshots work: locks, device handles, compiled functions
  and loggers all live in ``_``-suffixed slots.
* ``Distributable`` (``distributable.py:136``): thread-safe wrappers around
  the master/slave data methods with a deadlock watchdog (DEADLOCK_TIME,
  ``:137``).
* ``IDistributable`` (``distributable.py:222``): the 6-method contract every
  unit implements to take part in distributed runs.

TPU re-design notes: on-pod gradient exchange does NOT go through these
methods (it is a ``psum`` inside the jitted step — see
:mod:`veles_tpu.parallel`); they remain the contract for the *job-level*
layer (ensembles, genetic optimization, elastic eval over DCN).
"""

import contextlib
import threading

from veles_tpu.logger import Logger


class Pickleable(Logger):
    """Base with the ``_``-suffix pickling convention."""

    def __init__(self, **kwargs):
        super(Pickleable, self).__init__(**kwargs)
        self.init_unpickled()

    def init_unpickled(self):
        """Create/recreate all transient (``_``-suffixed) state.

        Subclasses override and MUST call ``super().init_unpickled()``.
        """
        sup = super(Pickleable, self)
        if hasattr(sup, "init_unpickled"):
            sup.init_unpickled()
        self._pickle_lock_ = threading.Lock()

    def __getstate__(self):
        with getattr(self, "_pickle_lock_", threading.Lock()):
            state = {}
            for key, value in self.__dict__.items():
                if key.endswith("_"):
                    continue
                state[key] = value
            return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.init_unpickled()


class Distributable(Pickleable):
    """Thread-safe data exchange with a deadlock watchdog
    (ref ``distributable.py:136-205``)."""

    DEADLOCK_TIME = 4.0

    negotiates_on_connect = False

    def init_unpickled(self):
        super(Distributable, self).init_unpickled()
        self._data_lock_ = threading.RLock()

    @contextlib.contextmanager
    def data_lock(self):
        """Serialize master/slave data exchange on this unit with a
        deadlock watchdog (ref ``distributable.py:136-205``).  The job
        layer (:mod:`veles_tpu.parallel.server`) wraps every
        ``generate_/apply_`` call in this; unit code touching the same
        state from ``run()`` may take it too."""
        if not self._data_lock_.acquire(timeout=self.DEADLOCK_TIME):
            self.warning(
                "possible deadlock in %s (> %.0f s waiting on data lock)",
                type(self).__name__, self.DEADLOCK_TIME)
            self._data_lock_.acquire()
        try:
            yield
        finally:
            self._data_lock_.release()

    # -- IDistributable default (trivial) implementations ------------------
    # (ref TriviallyDistributable distributable.py:284)
    def generate_data_for_master(self):
        """Return the payload a slave sends to the master after a job."""
        return None

    def generate_data_for_slave(self, slave=None):
        """Master side: produce a job payload for ``slave``."""
        return None

    def apply_data_from_master(self, data):
        """Slave side: install job payload before running."""

    def apply_data_from_slave(self, data, slave=None):
        """Master side: merge a slave's update."""

    def drop_slave(self, slave=None):
        """Master side: slave died — requeue its outstanding work."""


class TriviallyDistributable(Distributable):
    """Explicit marker for units with no distributed state."""
