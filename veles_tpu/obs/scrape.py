"""Per-role scrape endpoints: every process Prometheus-scrapeable.

The reference platform's signature operational surface was its
always-on status plane — EVERY node fed the web status server
(PAPER.md §0).  The TPU build's equivalent before this module was
lopsided: only the serving HTTP server exposed ``/metrics``; the job
master, the slaves and the pod workers had rich in-process state
(per-slave latency histograms, exactly-once counters, the perf
ledger, the trace ring) with no scrape surface at all.

:class:`ScrapeServer` is the smallest fix that composes: a threaded
HTTP listener serving ``GET /metrics`` (the concatenation of a list
of text-producing sources, each guarded — one failing source must
not blank the page for the rest) and ``GET /healthz``.  Every role
mounts it with its own sources:

* ``JobServer.start_scrape()`` — master: per-slave send→update
  round-trip histograms, heartbeat-stall counters, exactly-once
  accounting (+ the hosted workflow's own ``metrics_text`` when it
  has one, which is how a :class:`~veles_tpu.pod.membership.PodMaster`
  surfaces its lease table);
* ``JobClient.start_scrape()`` — slaves / pod workers: job progress
  plus the shared process-wide sources;
* the process-wide base (:func:`default_sources`): the PR 6 perf
  ledger gauges always, the trace category counters when tracing is
  on, the ``veles_tpu.watch`` training-health gauges + bus counters
  when that plane is armed, a declared
  :class:`~veles_tpu.obs.slo.SLOEngine` when given.

The exposition text comes from the same renderers the serving
``/metrics`` page uses (``veles_tpu.metrics.emit_histogram``,
``prof.metrics_text``, ``trace.metrics_text``), so one Prometheus
config scrapes every role with identical families.
"""

import json
import threading

from veles_tpu.logger import Logger


def default_sources(slo=None, extra=()):
    """The process-wide base every role shares: perf-ledger gauges
    (always on — the ledger has no knob), trace counters when tracing
    is enabled, an optional SLO engine (sampled per scrape), plus any
    role-specific callables."""
    from veles_tpu import prof, trace

    sources = [prof.metrics_text]

    def trace_source():
        return trace.metrics_text() if trace.enabled() else ""

    sources.append(trace_source)

    def watch_source():
        # the training-health gauges + telemetry-bus counters; empty
        # when neither the health knob nor the bus is armed
        from veles_tpu import watch
        return watch.metrics_text()

    sources.append(watch_source)
    if slo is not None:
        def slo_source():
            slo.sample()
            return slo.metrics_text()

        sources.append(slo_source)
    sources.extend(extra)
    return sources


class ScrapeServer(Logger):
    """Threaded ``/metrics`` + ``/healthz`` listener over a list of
    text sources.  ``port=0`` binds an ephemeral port (read it back
    from ``self.port`` after :meth:`start`)."""

    def __init__(self, sources, host="127.0.0.1", port=0,
                 role="process", **kwargs):
        super(ScrapeServer, self).__init__(**kwargs)
        self.sources = list(sources)
        self.host = host
        self.port = int(port)
        self.role = str(role)
        self._httpd = None
        self._thread = None

    def render(self):
        """Concatenate every source, each guarded: a raising source
        contributes a comment line naming itself instead of killing
        the scrape (a half-closed engine mid-undeploy must degrade,
        not 500)."""
        parts = []
        for source in self.sources:
            try:
                text = source()
            except Exception as e:  # noqa: BLE001 - exposition edge
                text = "# scrape source %s failed: %s\n" % (
                    getattr(source, "__name__", source), e)
            if text:
                parts.append(text if text.endswith("\n")
                             else text + "\n")
        return "".join(parts)

    def start(self):
        if self._httpd is not None:
            return self
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, status, body, content_type):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._reply(200, server.render().encode(),
                                "text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    self._reply(200, json.dumps(
                        {"status": "ok",
                         "role": server.role}).encode(),
                        "application/json")
                else:
                    self._reply(404, json.dumps(
                        {"error": "no route %r" % self.path}).encode(),
                        "application/json")

            def log_message(self, fmt, *args):
                server.debug("scrape: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="obs-scrape-%s" % self.role)
        self._thread.start()
        self.info("%s scrape endpoint on http://%s:%d/metrics",
                  self.role, self.host, self.port)
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
