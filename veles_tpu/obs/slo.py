"""SLO engine: windowed objectives, burn rates, autoscaling signals.

ROADMAP item 3's tail names the consumer this module exists for:
*"replica autoscaling driven by the PR 6 ledger + Prometheus signals
(queue depth, batch fill, TTFT p99) instead of static weights"*.
Before any autoscaler can act on those signals they must exist as
**live, windowed, objective-evaluated time series** — a raw gauge
says what the value is now; an autoscaler needs *how fast are we
burning the error budget*.

Three layers, smallest possible:

* :class:`SeriesRing` — a fixed-capacity ``(t, value)`` ring per
  signal.  Appending is O(1) and lock-cheap (the sampler thread and a
  concurrent scrape never contend for more than a few instructions);
  windows are computed from a snapshot.
* :class:`Objective` — a declarative bound over one signal
  (``ttft_p99_ms < 200 over 60 s``), read from the
  ``root.common.obs.slo.<signal>`` config namespace.  Compliance over
  a window is the fraction of samples inside the bound; the **burn
  rate** is ``(1 - compliance) / (1 - target)`` — 1.0 means the error
  budget drains exactly at the sustainable pace, N means N× too fast.
* :class:`SLOEngine` — samples registered signal callables into
  rings, evaluates every objective over its **fast and slow windows**
  (the standard multi-window method: alert only when BOTH windows
  burn above threshold, so a single bad scrape cannot page and a
  slow leak still does), and renders the result as ``/metrics``
  gauges + a ``describe()`` dict.

The three named autoscaling signals
(:data:`AUTOSCALING_SIGNALS` = queue depth, batch fill, TTFT p99 burn
rate) are always exported, with or without declared objectives — the
autoscaler's inputs must not depend on an operator remembering to
configure alerting.
"""

import threading
import time

from veles_tpu.config import root

#: the ROADMAP autoscaling triple every serving deployment exports
AUTOSCALING_SIGNALS = ("queue_depth", "batch_fill",
                      "ttft_p99_burn_rate")

#: default multi-window pair (seconds) and burn threshold — the SRE
#: fast/slow-window shape scaled to serving horizons: the fast window
#: catches a cliff within seconds, the slow window confirms it is not
#: one bad scrape
DEFAULT_WINDOW_S = 60.0
DEFAULT_FAST_WINDOW_S = 5.0
DEFAULT_TARGET = 0.99
DEFAULT_BURN_THRESHOLD = 2.0


class SeriesRing(object):
    """Fixed-capacity time series: the newest ``capacity`` samples."""

    def __init__(self, capacity=1024):
        self.capacity = int(capacity)
        self._t = [0.0] * self.capacity
        self._v = [0.0] * self.capacity
        self._pos = 0
        self._lock = threading.Lock()

    def append(self, value, t=None):
        if t is None:
            t = time.time()
        with self._lock:
            idx = self._pos % self.capacity
            self._t[idx] = float(t)
            self._v[idx] = float(value)
            self._pos += 1

    def __len__(self):
        return min(self._pos, self.capacity)

    def last(self):
        """The newest ``(t, value)`` or ``None``."""
        with self._lock:
            if not self._pos:
                return None
            idx = (self._pos - 1) % self.capacity
            return (self._t[idx], self._v[idx])

    def window(self, seconds, now=None):
        """Samples with ``t >= now - seconds``, oldest→newest."""
        if now is None:
            now = time.time()
        cutoff = now - float(seconds)
        with self._lock:
            n = min(self._pos, self.capacity)
            start = self._pos - n
            items = [( self._t[i % self.capacity],
                       self._v[i % self.capacity])
                     for i in range(start, self._pos)]
        return [(t, v) for t, v in items if t >= cutoff]


class Objective(object):
    """One declared bound: ``signal`` ``op`` ``bound`` over
    ``window_s``, with a ``target`` compliance goal and a fast/slow
    burn-rate alert pair."""

    __slots__ = ("name", "signal", "op", "bound", "window_s",
                 "fast_window_s", "target", "burn_threshold")

    def __init__(self, signal, bound, op="<", window_s=DEFAULT_WINDOW_S,
                 fast_window_s=DEFAULT_FAST_WINDOW_S,
                 target=DEFAULT_TARGET,
                 burn_threshold=DEFAULT_BURN_THRESHOLD, name=None):
        if op not in ("<", ">"):
            raise ValueError("objective op must be '<' or '>', got %r"
                             % op)
        self.signal = str(signal)
        self.op = op
        self.bound = float(bound)
        self.window_s = float(window_s)
        self.fast_window_s = float(fast_window_s)
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1), got %r"
                             % target)
        self.burn_threshold = float(burn_threshold)
        self.name = name or "%s %s %g over %gs" % (
            self.signal, self.op, self.bound, self.window_s)

    def good(self, value):
        return value < self.bound if self.op == "<" \
            else value > self.bound

    def describe(self):
        return {"name": self.name, "signal": self.signal,
                "op": self.op, "bound": self.bound,
                "window_s": self.window_s,
                "fast_window_s": self.fast_window_s,
                "target": self.target,
                "burn_threshold": self.burn_threshold}


class SLOEngine(object):
    """Signals + objectives + evaluation, one instance per serving
    role (the :class:`~veles_tpu.serve.server.ServingServer` owns
    one wired to its :class:`~veles_tpu.serve.metrics.ServingMetrics`).

    Thread model: ``sample()`` is called by whoever scrapes (each
    ``/metrics`` GET) and by tests with explicit timestamps;
    ``evaluate()``/``metrics_text()``/``describe()`` read snapshots.
    """

    def __init__(self, ring_capacity=1024):
        self._signals = {}           # name -> (fn, SeriesRing)
        self._objectives = []
        self._ring_capacity = int(ring_capacity)
        #: objective name -> alert state (for alerts_total edges)
        self._alerting = {}
        self.alerts_total = 0
        #: extra exposition callable (the fleet autoscaler's gauges
        #: ride the same scrape) — see :meth:`attach_exposition`
        self._extra_text = None
        self._lock = threading.Lock()

    # -- declaration -------------------------------------------------------
    def add_signal(self, name, fn):
        """Register a 0-arg sampler; replaces any previous ``name``
        (rings survive replacement so a redeploy keeps history)."""
        with self._lock:
            old = self._signals.get(name)
            ring = old[1] if old else SeriesRing(self._ring_capacity)
            self._signals[name] = (fn, ring)
        return ring

    def ring(self, name):
        entry = self._signals.get(name)
        return entry[1] if entry else None

    def attach_exposition(self, fn):
        """Append an extra exposition source to this engine's
        ``/metrics`` text — the closed loop made visible: the fleet
        autoscaler CONSUMES :meth:`autoscaling_signals` and publishes
        its decisions (``veles_fleet_*`` gauges) back through the same
        scrape, so one endpoint shows signal and action side by side.
        ``fn`` returns exposition lines (or ``""``); a raising source
        is skipped, never poisoning the scrape."""
        self._extra_text = fn

    def add_objective(self, objective):
        if objective.signal not in self._signals:
            raise ValueError(
                "objective %r names unknown signal %r (registered: %s)"
                % (objective.name, objective.signal,
                   ", ".join(sorted(self._signals)) or "<none>"))
        self._objectives.append(objective)
        return objective

    @property
    def objectives(self):
        return list(self._objectives)

    def configure(self, node=None):
        """Read objectives from ``root.common.obs.slo.*`` (or a given
        config node / plain dict): each child is
        ``<signal>: {"max"|"min": bound, "window_s": ..., "target":
        ..., "fast_window_s": ..., "burn_threshold": ...}``.  Unknown
        signals are skipped with the declaration kept out (an SLO on
        a signal this role does not export cannot be evaluated
        honestly).  Returns the number of objectives installed."""
        if node is None:
            node = root.common.obs.get("slo")
        if node is None:
            return 0
        if hasattr(node, "to_dict"):
            node = node.to_dict()
        installed = 0
        for signal, spec in sorted((node or {}).items()):
            if not isinstance(spec, dict):
                continue
            if "max" in spec:
                op, bound = "<", spec["max"]
            elif "min" in spec:
                op, bound = ">", spec["min"]
            else:
                continue
            if signal not in self._signals:
                continue
            self.add_objective(Objective(
                signal, bound, op=op,
                window_s=spec.get("window_s", DEFAULT_WINDOW_S),
                fast_window_s=spec.get("fast_window_s",
                                       DEFAULT_FAST_WINDOW_S),
                target=spec.get("target", DEFAULT_TARGET),
                burn_threshold=spec.get("burn_threshold",
                                        DEFAULT_BURN_THRESHOLD)))
            installed += 1
        return installed

    # -- sampling / evaluation ----------------------------------------------
    def sample(self, now=None):
        """Poll every signal callable into its ring.  A sampler that
        raises contributes nothing this round (a half-closed scheduler
        mid-undeploy must not poison the scrape)."""
        with self._lock:
            items = list(self._signals.items())
        for _name, (fn, ring) in items:
            try:
                value = fn()
            except Exception:
                continue
            if value is None:
                continue
            ring.append(value, t=now)

    def compliance(self, signal, objective, window_s, now=None):
        """Fraction of the window's samples inside the bound, or
        ``None`` with no samples (no data is not the same as
        breaching)."""
        ring = self.ring(signal)
        if ring is None:
            return None
        samples = ring.window(window_s, now=now)
        if not samples:
            return None
        good = sum(1 for _t, v in samples if objective.good(v))
        return good / float(len(samples))

    def burn_rate(self, objective, window_s=None, now=None):
        """``(1 - compliance) / (1 - target)`` over the window; 0.0
        with no data (an idle service burns nothing)."""
        c = self.compliance(objective.signal, objective,
                            window_s or objective.window_s, now=now)
        if c is None:
            return 0.0
        return (1.0 - c) / (1.0 - objective.target)

    def evaluate(self, now=None):
        """Every objective → ``{objective, fast_burn, slow_burn,
        alerting}``.  ``alerting`` requires BOTH windows above the
        objective's burn threshold (the multi-window method);
        :attr:`alerts_total` counts raised edges only."""
        out = []
        for objective in self._objectives:
            fast = self.burn_rate(objective, objective.fast_window_s,
                                  now=now)
            slow = self.burn_rate(objective, objective.window_s,
                                  now=now)
            alerting = (fast >= objective.burn_threshold
                        and slow >= objective.burn_threshold)
            with self._lock:
                # edge detection under the lock: concurrent scrapes
                # (/metrics and /healthz both evaluate) must count ONE
                # raised edge per breach, not one per scraper
                was = self._alerting.get(objective.name, False)
                if alerting and not was:
                    self.alerts_total += 1
                self._alerting[objective.name] = alerting
            out.append({"objective": objective.name,
                        "signal": objective.signal,
                        "fast_burn": round(fast, 4),
                        "slow_burn": round(slow, 4),
                        "alerting": alerting})
        return out

    # -- the autoscaling triple ---------------------------------------------
    def autoscaling_signals(self, now=None):
        """The ROADMAP triple as current values: last queue depth,
        last batch fill, and the TTFT objective's fast-window burn
        rate (0.0 when no TTFT objective is declared or no data —
        an autoscaler reading zeros holds steady, which is the safe
        default)."""
        def last(name):
            ring = self.ring(name)
            sample = ring.last() if ring is not None else None
            return sample[1] if sample else 0.0

        ttft_burn = 0.0
        for objective in self._objectives:
            if objective.signal == "ttft_p99_ms":
                ttft_burn = self.burn_rate(
                    objective, objective.fast_window_s, now=now)
                break
        return {"queue_depth": last("queue_depth"),
                "batch_fill": last("batch_fill"),
                "ttft_p99_burn_rate": round(ttft_burn, 4)}

    # -- exposition ----------------------------------------------------------
    def metrics_text(self, now=None):
        """Prometheus gauges: the autoscaling triple (always), every
        signal's last sample, and per-objective burn rates + alert
        flags.  Families stay contiguous — one HELP/TYPE per name
        with label variants grouped (the exposition contract)."""
        signals = self.autoscaling_signals(now=now)
        lines = [
            "# HELP veles_slo_queue_depth autoscaling signal: queued "
            "rows + generation requests (last sample)",
            "# TYPE veles_slo_queue_depth gauge",
            "veles_slo_queue_depth %g" % signals["queue_depth"],
            "# HELP veles_slo_batch_fill autoscaling signal: decode/"
            "bucket row utilisation (last sample)",
            "# TYPE veles_slo_batch_fill gauge",
            "veles_slo_batch_fill %g" % signals["batch_fill"],
            "# HELP veles_slo_ttft_p99_burn_rate autoscaling signal: "
            "TTFT p99 objective fast-window burn rate (1.0 = budget "
            "drains at the sustainable pace)",
            "# TYPE veles_slo_ttft_p99_burn_rate gauge",
            "veles_slo_ttft_p99_burn_rate %g"
            % signals["ttft_p99_burn_rate"],
        ]
        with self._lock:
            names = sorted(self._signals)
        if names:
            lines.append("# HELP veles_slo_signal last sampled value "
                         "per registered SLO signal")
            lines.append("# TYPE veles_slo_signal gauge")
            for name in names:
                sample = self.ring(name).last()
                if sample is not None:
                    lines.append('veles_slo_signal{signal="%s"} %g'
                                 % (name, sample[1]))
        results = self.evaluate(now=now)
        if results:
            lines.append("# HELP veles_slo_burn_rate error-budget "
                         "burn rate per objective and window")
            lines.append("# TYPE veles_slo_burn_rate gauge")
            for res in results:
                for window in ("fast", "slow"):
                    lines.append(
                        'veles_slo_burn_rate{objective="%s",'
                        'window="%s"} %g'
                        % (res["objective"], window,
                           res["%s_burn" % window]))
            lines.append("# HELP veles_slo_alerting 1 when both burn "
                         "windows exceed the objective's threshold")
            lines.append("# TYPE veles_slo_alerting gauge")
            for res in results:
                lines.append('veles_slo_alerting{objective="%s"} %d'
                             % (res["objective"],
                                1 if res["alerting"] else 0))
        lines.append("# TYPE veles_slo_alerts_total counter")
        lines.append("veles_slo_alerts_total %d" % self.alerts_total)
        if self._extra_text is not None:
            try:
                extra = self._extra_text()
            except Exception:
                extra = ""
            if extra:
                lines.append(extra.rstrip("\n"))
        return "\n".join(lines) + "\n"

    def describe(self):
        """JSON-able digest for ``describe()``/``/healthz`` surfaces:
        the autoscaling triple, per-signal last samples, objective
        declarations and their current evaluation."""
        with self._lock:
            names = sorted(self._signals)
        signals = {}
        for name in names:
            sample = self.ring(name).last()
            if sample is not None:
                signals[name] = round(sample[1], 4)
        return {
            "autoscaling": self.autoscaling_signals(),
            "signals": signals,
            "objectives": [o.describe() for o in self._objectives],
            "evaluation": self.evaluate(),
            "alerts_total": self.alerts_total,
        }


def standard_engine(metrics, configure=True):
    """The serving wiring: an :class:`SLOEngine` whose signals read a
    :class:`~veles_tpu.serve.metrics.ServingMetrics` instance —

    * ``queue_depth``: the sum of every registered queue-depth gauge
      (request/response batchers AND generative schedulers);
    * ``batch_fill``: the generative schedulers' mean decode fill when
      any are deployed, else the batcher fill ratio;
    * ``ttft_p99_ms``: the worst per-model generative TTFT p99.

    Gauges register and unregister with deploys, so the samplers walk
    the CURRENT gauge table on every sample — a redeploy changes what
    is measured without rewiring the engine."""

    def gauge_values(prefix):
        out = []
        for name, fn in metrics._gauge_items():
            if name == prefix or name.startswith(prefix + "{"):
                try:
                    out.append(float(fn()))
                except Exception:
                    continue
        return out

    def queue_depth():
        depth = sum(gauge_values("queue_depth"))
        depth += sum(gauge_values("gen_queue_depth"))
        return depth

    def batch_fill():
        fills = gauge_values("gen_batch_fill")
        if fills:
            return sum(fills) / len(fills)
        return metrics.batch_fill_ratio()

    def ttft_p99_ms():
        values = gauge_values("gen_ttft_p99_ms")
        return max(values) if values else 0.0

    engine = SLOEngine()
    engine.add_signal("queue_depth", queue_depth)
    engine.add_signal("batch_fill", batch_fill)
    engine.add_signal("ttft_p99_ms", ttft_p99_ms)
    if configure:
        engine.configure()
    return engine
