"""veles_tpu.obs — the fleet observability plane.

PR 5–6 built the process-local substrate (span ring, perf ledger);
this package makes it FLEET-wide, the way the reference platform's
always-on status plane was (every node fed the web status server,
PAPER.md §0):

1. **Distributed request tracing** (:mod:`~veles_tpu.obs.context`) —
   a W3C-compatible trace context minted at the serving front door,
   carried across thread handoffs on request objects and across the
   ZMQ job wire in frame fields, so ``prof merge`` stitches ONE
   cross-process waterfall per request (queue wait, batch-fill wait,
   prefill chunks, decode steps, preemptions) with Perfetto flow
   arrows between role lanes.
2. **Per-role scrape endpoints** (:mod:`~veles_tpu.obs.scrape`) —
   a tiny shared ``/metrics`` listener mounted on the job master
   (per-slave latency histograms, heartbeat-stall counters,
   exactly-once accounting), slaves and pod workers — every role
   Prometheus-scrapeable, not just the serving server.
3. **An SLO engine** (:mod:`~veles_tpu.obs.slo`) — fixed-capacity
   time-series rings over the existing metric sources, windowed
   objectives from ``root.common.obs.slo.*``, multi-window burn-rate
   evaluation, and the three ROADMAP autoscaling signals (queue
   depth, batch fill, TTFT p99 burn rate) exported on ``/metrics``
   and in ``describe()``.
4. **A flight recorder** (:mod:`~veles_tpu.obs.blackbox`) — fatal
   exits dump the live trace ring + ledger summary to
   ``root.common.obs.blackbox_dir`` as a loadable post-mortem.

The disabled path keeps the PR 5 contract: with tracing off, every
context hook is one attribute check returning a shared no-op.

See ``docs/observability.md`` § Request tracing & SLOs.
"""

from veles_tpu.obs import blackbox, context, scrape, slo  # noqa: F401
from veles_tpu.obs.context import (  # noqa: F401
    NULL_CONTEXT, TraceContext, activate, current, current_trace_id,
    ingress, mint, parse, role_lanes, set_process, spans_of, tag,
    waterfall_text, wire_extract, wire_inject)
from veles_tpu.obs.scrape import ScrapeServer, default_sources  # noqa: F401
from veles_tpu.obs.slo import (  # noqa: F401
    AUTOSCALING_SIGNALS, Objective, SeriesRing, SLOEngine,
    standard_engine)


def configure():
    """Apply the ``root.common.obs.*`` knobs (re-read at the same
    boundaries trace/chaos re-read theirs — ``Workflow.initialize``,
    the launcher): currently arms the flight recorder when
    ``blackbox_dir`` is set.  Tracing itself stays under the PR 5
    ``root.common.engine.trace`` knob."""
    return blackbox.configure()
