"""Distributed request tracing: the identity that crosses processes.

PR 5's span ring answers *where did the time go inside this process*;
what it cannot answer is *which request was that* — no span carries an
identity that survives a thread hop (the batcher worker), let alone a
process hop (the ZMQ job wire).  This module adds the missing piece:
a compact **trace context** — a 128-bit trace id plus the 64-bit span
id of the emitting hop — minted at ingress (the serving HTTP front
door accepts and emits the W3C ``traceparent`` header, so external
tracers compose), carried on request objects across thread handoffs,
and serialized into ZMQ job/update/lease frames so a slave's spans
join the same waterfall.

Span args are the transport INTO the ring: a tagged span carries
``{"trace": <32 hex>, "span": <16 hex>, "parent": <16 hex>}`` next to
its ordinary args, and :func:`veles_tpu.trace.export.chrome_events`
turns those into Chrome flow events (``ph: s/t``) binding the spans of
one request into a single arrowed waterfall across every role lane of
a ``prof merge`` timeline.

Propagation model (cheapest thing that spans every topology here):

* a **thread-local** current context (``activate()`` context manager)
  for the request-scoped path — HTTP handler → scheduler submit;
* a **process default** (:func:`set_process`) behind it for session-
  scoped identity — a training session traced end-to-end stamps every
  job the master mints without touching per-thread state;
* explicit **wire fields** (:func:`wire_inject` / :func:`wire_extract`)
  for ZMQ frames: one ``tp`` key holding the ``traceparent`` string.

The disabled path is the PR 5 contract verbatim: every entry point
reads ``trace.recorder.enabled`` ONCE and returns a shared no-op
(``ingress``/``current``/``tag`` return ``None``/their argument,
``activate(None)`` returns the one :data:`NULL_CONTEXT` singleton) —
no allocation, no id generation, no locking
(``tests/test_obs.py::test_disabled_path_*``).
"""

import random
import threading

from veles_tpu.trace.core import recorder

#: W3C trace-context version prefix this module emits
_VERSION = "00"
#: sampled flag — everything we mint is recorded (the knob IS the
#: sampler: tracing off mints nothing at all)
_FLAGS = "01"


class TraceContext(object):
    """One hop of a distributed trace: ``trace_id`` names the request,
    ``span_id`` names THIS hop, ``parent_id`` the hop that caused it."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id=None, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id or _new_span_id()
        self.parent_id = parent_id

    def child(self):
        """A new hop of the same trace (fresh span id, this hop as
        parent) — what crosses each thread/process boundary."""
        return TraceContext(self.trace_id, _new_span_id(),
                            self.span_id)

    def traceparent(self):
        """The W3C header / wire encoding of this hop."""
        return "%s-%s-%s-%s" % (_VERSION, self.trace_id, self.span_id,
                                _FLAGS)

    def span_args(self, args=None):
        """``args`` (or a fresh dict) with the identity keys merged in
        — what tagged spans carry into the ring."""
        out = dict(args) if args else {}
        out["trace"] = self.trace_id
        out["span"] = self.span_id
        if self.parent_id:
            out["parent"] = self.parent_id
        return out

    def __repr__(self):
        return "<TraceContext %s span=%s parent=%s>" % (
            self.trace_id, self.span_id, self.parent_id)

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_id == other.parent_id)


def _new_trace_id():
    # uniqueness, not cryptography (the W3C format asks no more);
    # getrandbits avoids a syscall on every traced request
    return "%032x" % random.getrandbits(128)


def _new_span_id():
    value = random.getrandbits(64)
    return "%016x" % (value or 1)   # all-zero span ids are invalid


def mint():
    """A brand-new root context (no parent)."""
    return TraceContext(_new_trace_id())


def parse(header):
    """``traceparent`` header → :class:`TraceContext`, or ``None`` on
    anything malformed (a bad header must degrade to a fresh mint,
    never to a 500)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(version, 16)
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    # the incoming span is the PARENT of everything we do with it
    return TraceContext(trace_id, _new_span_id(), span_id)


def ingress(header=None):
    """The front-door mint: continue the caller's trace when a valid
    ``traceparent`` came in, start a new one otherwise.  Returns
    ``None`` when tracing is off — ONE attribute check, nothing
    allocated (the PR 5 disabled-path contract)."""
    if not recorder.enabled:
        return None
    return parse(header) or mint()


# -- propagation ------------------------------------------------------------

_local = threading.local()
#: the process-default context behind the thread-local (one slot, set
#: by set_process) — session-scoped identity for roles with no
#: per-request thread (the job master's pool workers)
_process = [None]


class _Activation(object):
    """Context manager installing a context as the thread-local
    current one (restoring the previous on exit)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_local, "ctx", None)
        _local.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _local.ctx = self._prev
        return False


class _NullActivation(object):
    """The shared no-op ``activate(None)`` returns — entering and
    exiting allocate nothing and touch no thread-local."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


#: the one disabled-path activation singleton
NULL_CONTEXT = _NullActivation()


def activate(ctx):
    """``with activate(ctx):`` makes ``ctx`` the current context on
    this thread.  ``activate(None)`` is the shared no-op singleton."""
    if ctx is None:
        return NULL_CONTEXT
    return _Activation(ctx)


def set_process(ctx):
    """Install (or clear, with ``None``) the process-default context —
    the fallback :func:`current` uses when the calling thread has no
    activation.  Returns the previous default."""
    previous = _process[0]
    _process[0] = ctx
    return previous


def current():
    """The context in effect: this thread's activation, else the
    process default, else ``None``.  One attribute check when tracing
    is off."""
    if not recorder.enabled:
        return None
    ctx = getattr(_local, "ctx", None)
    return ctx if ctx is not None else _process[0]


def current_trace_id():
    """``current().trace_id`` or ``None`` — for call sites that stash
    just the id (the gen engine's per-slot tags)."""
    ctx = current()
    return ctx.trace_id if ctx is not None else None


def tag(args=None):
    """Merge the current context's identity into span ``args``.
    Disabled (or no context): returns ``args`` UNCHANGED — same
    object, no copy.  Resolution is :func:`current`'s, by
    construction (one source for the thread-local/process-default
    chain); the early enabled check keeps the disabled path at one
    attribute read."""
    if not recorder.enabled:
        return args
    ctx = current()
    return args if ctx is None else ctx.span_args(args)


# -- the ZMQ wire -----------------------------------------------------------

#: the frame key job/update/lease/pod_epoch messages carry
WIRE_KEY = "tp"


def wire_inject(msg, ctx=None):
    """Stamp the current (or given) context's ``traceparent`` into a
    wire frame dict as a CHILD hop (the receiver's spans parent to the
    sender's).  No-op — same dict back, untouched — when tracing is
    off or no context is in effect."""
    if ctx is None:
        ctx = current()
    if ctx is not None:
        msg[WIRE_KEY] = ctx.child().traceparent()
    return msg


def wire_extract(msg):
    """The receiving half: a frame's ``tp`` field → a context to
    activate around the work it causes.  ``None`` when tracing is off
    here or the frame carries nothing parseable."""
    if not recorder.enabled:
        return None
    return parse(msg.get(WIRE_KEY))


# -- waterfall introspection ------------------------------------------------

def spans_of(events, trace_id):
    """Every normalized span/instant of one trace id, sorted by
    timestamp — the per-request waterfall over a live ring snapshot or
    a merged session bundle (``prof merge``).  Matches both the
    singular ``trace`` tag and membership in a shared dispatch's
    ``traces`` list (the batcher's coalesced call, the gen engine's
    decode step serving several co-residents at once)."""
    out = []
    for ev in events:
        args = ev.get("args") or {}
        if args.get("trace") == trace_id \
                or trace_id in (args.get("traces") or ()):
            out.append(ev)
    out.sort(key=lambda ev: ev.get("ts_us", 0.0))
    return out


def role_lanes(events, trace_id):
    """{role: [event names]} for one trace id — the acceptance probe:
    a traced ``/generate`` request must light the server, scheduler/
    engine and at least one ZMQ-remote lane in one timeline."""
    lanes = {}
    for ev in spans_of(events, trace_id):
        lanes.setdefault(ev.get("role") or "trainer", []).append(
            ev.get("name"))
    return lanes


def waterfall_text(events, trace_id):
    """Human rendering of one request's cross-process waterfall:
    every tagged span in time order with role, duration and phase
    name — queue wait / batch fill / prefill chunks / decode separate
    per request by construction (each phase is its own tagged span)."""
    spans = spans_of(events, trace_id)
    if not spans:
        return "no spans for trace %s\n" % trace_id
    t0 = spans[0].get("ts_us", 0.0)
    lines = ["trace %s — %d event(s) across %d role(s)"
             % (trace_id, len(spans),
                len({ev.get("role") for ev in spans}))]
    for ev in spans:
        lines.append(
            "  +%9.3f ms %8s %-10s %s:%s%s"
            % ((ev.get("ts_us", 0.0) - t0) / 1e3,
               ("%.3f ms" % (ev.get("dur_us", 0.0) / 1e3))
               if ev.get("ph") == "X" else "-",
               ev.get("role") or "trainer", ev.get("cat"),
               ev.get("name"),
               " [span %s]" % (ev.get("args") or {}).get("span", "")))
    return "\n".join(lines) + "\n"
