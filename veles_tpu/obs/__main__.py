"""``python -m veles_tpu.obs --smoke`` — the fleet-observability gate.

Wired into ``scripts/lint.sh`` next to the prof/chaos/gen/pod smokes.
Four phases, each a hard gate:

1. **Disabled-path contract** — with tracing off, every obs hook is
   the PR 5 no-op: ``ingress`` returns ``None``, ``activate(None)``
   is the shared singleton, ``tag``/``wire_inject`` hand their
   argument back untouched.
2. **End-to-end request identity** — ONE traced ``POST /generate``
   (W3C ``traceparent`` in, echoed back out) must stamp its trace id
   on spans from the HTTP server, the scheduler's phase spans
   (queue_wait / prefill / decode) and the engine dispatch.
3. **Cross-process stitch** — a scripted master–slave ZMQ session
   under the same trace id, merged via ``prof merge``, must show the
   id in ≥3 role lanes of ONE Perfetto timeline (server + master +
   slave-<sid>), with flow events binding them; the master scrape
   endpoint must serve the per-slave latency histograms.
4. **SLO engine** — a synthetic breaching TTFT series must fire
   EXACTLY the expected multi-window burn alerts (one raised edge,
   co-declared healthy objectives silent, recovery clears it).

Exit code 0 on success; any violation prints the failure and exits 1.
"""

import argparse
import json
import sys


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.obs",
        description="Fleet-observability smoke gate (request tracing "
                    "-> cross-process merge -> scrape endpoints -> "
                    "SLO burn alerts).")
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI smoke gate")
    return parser


class _ScriptedMaster(object):
    def __init__(self, n_jobs=3):
        self.n_jobs = n_jobs
        self.served = 0
        self.updates = []

    def checksum(self):
        return "obs-smoke-v1"

    def generate_data_for_slave(self, slave):
        if self.served >= self.n_jobs:
            return None
        self.served += 1
        return {"job_number": self.served}

    def apply_data_from_slave(self, data, slave):
        self.updates.append(data)

    def drop_slave(self, slave):
        pass


class _ScriptedSlave(object):
    def checksum(self):
        return "obs-smoke-v1"

    def do_job(self, data, callback):
        callback({"result": data["job_number"]})


def _check_disabled_path():
    from veles_tpu import obs, trace
    failed = 0
    if trace.enabled():
        print("FAIL[disabled]: tracing must start off")
        return 1
    if obs.ingress("00-" + "ab" * 16 + "-" + "cd" * 8 + "-01") \
            is not None:
        print("FAIL[disabled]: ingress must return None when "
              "tracing is off")
        failed += 1
    if obs.activate(None) is not obs.NULL_CONTEXT:
        print("FAIL[disabled]: activate(None) must be the shared "
              "no-op singleton")
        failed += 1
    args = {"k": 1}
    if obs.tag(args) is not args:
        print("FAIL[disabled]: tag() must hand its argument back "
              "untouched")
        failed += 1
    msg = {"op": "job"}
    if obs.wire_inject(msg) is not msg or "tp" in msg:
        print("FAIL[disabled]: wire_inject must not stamp disabled "
              "frames")
        failed += 1
    if obs.current() is not None:
        print("FAIL[disabled]: no current context when tracing is "
              "off")
        failed += 1
    return failed


def _traced_request(tmpdir):
    """Phases 2+3: the traced request + the scripted ZMQ session,
    one merged timeline.  Returns (failed, trace_id)."""
    import urllib.request

    from veles_tpu import obs, prof, trace
    from veles_tpu.gen import GenerativeEngine, TransformerGenModel
    from veles_tpu.parallel.jobs import JobClient, JobServer
    from veles_tpu.samples.transformer import TINY
    from veles_tpu.serve.registry import ModelRegistry
    from veles_tpu.serve.server import ServingServer
    from veles_tpu.trace import export

    failed = 0
    engine = GenerativeEngine(
        TransformerGenModel(dict(TINY, seq_len=64)), max_slots=2,
        max_seq=48, prefill_buckets=(8,), seed=0).warmup()
    registry = ModelRegistry()
    registry.deploy_generative("default", engine, warmup=False)
    server = ServingServer(registry=registry).start()
    inbound = obs.mint()
    try:
        req = urllib.request.Request(
            "http://%s:%d/generate" % (server.host, server.port),
            data=json.dumps({"tokens": [1, 2, 3],
                             "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": inbound.traceparent()})
        with urllib.request.urlopen(req, timeout=30) as resp:
            reply = json.loads(resp.read())
            echoed = resp.headers.get("traceparent")
    finally:
        server.stop(stop_registry=False)
    if len(reply.get("tokens", ())) != 4:
        print("FAIL[request]: wanted 4 tokens, got %r" % reply)
        failed += 1
    trace_id = inbound.trace_id
    if not echoed or trace_id not in echoed:
        print("FAIL[request]: traceparent not echoed (got %r)"
              % echoed)
        failed += 1

    # the request's in-process waterfall: server ingress span, the
    # scheduler's phase spans, the engine dispatch
    events = export.normalize()
    cats_names = {(ev["cat"], ev["name"])
                  for ev in obs.spans_of(events, trace_id)}
    for want in (("serve", "http"), ("gen", "queue_wait"),
                 ("gen", "prefill"), ("gen", "decode")):
        if want not in cats_names:
            print("FAIL[request]: span %s:%s missing from the "
                  "request waterfall (have %s)"
                  % (want[0], want[1], sorted(cats_names)))
            failed += 1

    # phase 3: the same trace id crosses the ZMQ job wire — a session
    # context (process default) stamps every job the master mints
    session_ctx = obs.parse(inbound.traceparent())
    obs.set_process(session_ctx)
    master = _ScriptedMaster(n_jobs=3)
    job_server = JobServer(master).start()
    scrape = job_server.start_scrape()
    try:
        client = JobClient(_ScriptedSlave(), job_server.endpoint)
        client.handshake()
        if not client.run():
            print("FAIL[session]: scripted slave did not complete")
            failed += 1
        # scrape while the slave is still a member: its send->update
        # round-trip histogram must render as a real Prometheus family
        scrape_url = "http://%s:%d/metrics" % (scrape.host,
                                               scrape.port)
        with urllib.request.urlopen(scrape_url, timeout=10) as resp:
            page = resp.read().decode()
        for needle in ("veles_jobs_job_latency_seconds_bucket",
                       "veles_jobs_heartbeat_stalls_total",
                       "veles_jobs_updates_applied_total 3",
                       "veles_prof_compiles_total"):
            if needle not in page:
                print("FAIL[scrape]: %r missing from the master "
                      "scrape endpoint" % needle)
                failed += 1
        client.close()
        bundle_path = tmpdir + "/session.json"
        job_server.save_session_profile(bundle_path,
                                        roles=("master", "server"))
    finally:
        obs.set_process(None)
        job_server.stop()
        registry.stop(drain=False)

    bundle = prof.merge.load(bundle_path)
    merged = prof.merge.merged_events(bundle)
    lanes = obs.role_lanes(merged, trace_id)
    if len(lanes) < 3:
        print("FAIL[merge]: trace id in %d role lane(s), want >=3: %r"
              % (len(lanes), sorted(lanes)))
        failed += 1
    if "master" not in lanes or "server" not in lanes \
            or not any(r.startswith("slave-") for r in lanes):
        print("FAIL[merge]: want server+master+slave lanes, got %r"
              % sorted(lanes))
        failed += 1
    merged_path = tmpdir + "/merged.json"
    prof.merge.save_merged(bundle, merged_path)
    with open(merged_path) as fin:
        raw = json.load(fin)["traceEvents"]
    flows = [ev for ev in raw if ev.get("ph") in ("s", "t")
             and ev.get("id") == trace_id]
    if len(flows) < 3:
        print("FAIL[merge]: %d flow event(s) for the trace, want the "
              "cross-lane waterfall arrows" % len(flows))
        failed += 1
    print("obs smoke: trace %s in %d role lanes (%s), %d flow "
          "arrows, master scrape ok"
          % (trace_id[:8], len(lanes),
             ", ".join(sorted(lanes)), len(flows)))
    print(obs.waterfall_text(merged, trace_id).rstrip())
    return failed, trace_id


def _check_slo():
    from veles_tpu.obs.slo import Objective, SLOEngine
    failed = 0
    engine = SLOEngine()
    ttft = engine.add_signal("ttft_p99_ms", lambda: 0.0)
    depth = engine.add_signal("queue_depth", lambda: 0.0)
    engine.add_objective(Objective(
        "ttft_p99_ms", 200.0, window_s=60.0, fast_window_s=5.0,
        target=0.9, burn_threshold=2.0))
    engine.add_objective(Objective(
        "queue_depth", 100.0, window_s=60.0, fast_window_s=5.0,
        target=0.9, burn_threshold=2.0))
    now = 10000.0
    for i in range(60):      # healthy minute, both signals
        ttft.append(100.0, t=now - 60 + i)
        depth.append(3.0, t=now - 60 + i)
    results = {r["objective"]: r for r in engine.evaluate(now=now)}
    if any(r["alerting"] for r in results.values()):
        print("FAIL[slo]: healthy series must not alert: %r"
              % results)
        failed += 1
    # breach: the last 30 s of TTFT blow the bound — slow-window
    # compliance 0.5 -> burn 5.0, fast window all bad -> burn 10.0
    now += 30
    for i in range(30):
        ttft.append(500.0, t=now - 30 + i)
        depth.append(3.0, t=now - 30 + i)
    results = {r["objective"]: r for r in engine.evaluate(now=now)}
    ttft_res = [r for name, r in results.items()
                if "ttft" in name][0]
    depth_res = [r for name, r in results.items()
                 if "queue_depth" in name][0]
    if not ttft_res["alerting"]:
        print("FAIL[slo]: breaching TTFT series must alert: %r"
              % ttft_res)
        failed += 1
    if abs(ttft_res["fast_burn"] - 10.0) > 1e-6 \
            or abs(ttft_res["slow_burn"] - 5.0) > 1e-6:
        print("FAIL[slo]: burn rates off: fast %r (want 10.0), "
              "slow %r (want 5.0)"
              % (ttft_res["fast_burn"], ttft_res["slow_burn"]))
        failed += 1
    if depth_res["alerting"]:
        print("FAIL[slo]: healthy queue-depth objective must stay "
              "silent: %r" % depth_res)
        failed += 1
    if engine.alerts_total != 1:
        print("FAIL[slo]: exactly one raised alert edge expected, "
              "got %d" % engine.alerts_total)
        failed += 1
    engine.evaluate(now=now)   # still alerting: no second edge
    if engine.alerts_total != 1:
        print("FAIL[slo]: re-evaluation must not re-count a standing "
              "alert (got %d)" % engine.alerts_total)
        failed += 1
    # recovery: a healthy minute clears it
    now += 90
    for i in range(60):
        ttft.append(100.0, t=now - 60 + i)
    results = {r["objective"]: r for r in engine.evaluate(now=now)}
    if any(r["alerting"] for r in results.values()):
        print("FAIL[slo]: recovered series must clear the alert")
        failed += 1
    text = engine.metrics_text(now=now)
    for needle in ("veles_slo_queue_depth", "veles_slo_batch_fill",
                   "veles_slo_ttft_p99_burn_rate",
                   "veles_slo_alerts_total 1"):
        if needle not in text:
            print("FAIL[slo]: %r missing from metrics_text" % needle)
            failed += 1
    if not failed:
        print("obs smoke[slo]: burn alerts fired exactly as "
              "expected (fast 10.0x / slow 5.0x, 1 edge, recovery "
              "clears)")
    return failed


def smoke():
    import tempfile

    from veles_tpu import trace
    from veles_tpu.config import root

    failed = _check_disabled_path()

    saved = root.common.engine.get("trace", "off")
    root.common.engine.trace = "on"
    trace.configure()
    trace.recorder.clear()
    try:
        with tempfile.TemporaryDirectory() as tmpdir:
            req_failed, _tid = _traced_request(tmpdir)
            failed += req_failed
    finally:
        root.common.engine.trace = saved
        trace.configure()
        trace.recorder.clear()

    failed += _check_slo()
    return 1 if failed else 0


def main(argv=None):
    args = make_parser().parse_args(argv)
    if not args.smoke:
        make_parser().print_help()
        return 2
    return smoke()


if __name__ == "__main__":
    sys.exit(main())
