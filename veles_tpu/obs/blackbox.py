"""Flight recorder: a loadable post-mortem from any traced role.

A crashed process takes its trace ring and perf ledger with it — the
two artifacts that would have said what it was doing.  This module is
the aviation fix: when ``root.common.obs.blackbox_dir`` is set, every
fatal exit path dumps a compact JSON post-mortem there —

* the live trace ring (normalized events, newest ``capacity`` of
  them) and its wraparound-proof per-category counts,
* the PR 6 perf-ledger summary (compiles, recompiles, per-program
  rows, HBM by category),
* the ``veles_tpu.watch`` tail: the last cached training-health
  snapshot (what the numerics looked like at death) and the newest
  telemetry-bus events — so a chaos kill's flight record carries the
  same live view an attached dashboard was seeing,
* the role, pid, reason and wall-clock time of death —

via three hooks: ``sys.excepthook`` (unhandled exception),
``atexit`` with a recorded reason (the chaos ``slave_kill`` /
``master_kill`` paths call :func:`dump` directly — a simulated
SIGKILL must leave the same evidence a real one would), and a
``SIGTERM`` handler (installed only when the knob is set AND the
process owns its signal disposition — never under pytest).

Writes are atomic (tmp + rename): a crash mid-dump leaves the
previous post-mortem intact, never a torn file.  :func:`load`
validates the kind tag so tooling can trust what it parses.
"""

import json
import os
import sys
import time

from veles_tpu.config import root

#: the post-mortem file's kind tag (load() validates it)
KIND = "veles_tpu.obs.blackbox"

#: how many newest trace events a post-mortem keeps (bounds the file;
#: the interesting events are the last ones by construction)
MAX_EVENTS = 8192

#: how many newest telemetry-bus events ride along in the "watch"
#: block (bounded by the bus's own history ring anyway)
MAX_BUS_EVENTS = 64

_installed = [False]
_prev_excepthook = [None]
_prev_thread_hook = [None]


def blackbox_dir():
    """The knob: a non-empty ``root.common.obs.blackbox_dir`` arms
    every dump site; empty/unset keeps them all no-ops."""
    node = root.common.get("obs")
    if node is None:
        return None
    value = node.get("blackbox_dir") if hasattr(node, "get") else None
    return str(value) if value else None


def dump(reason, directory=None, extra=None):
    """Write one post-mortem; returns its path, or ``None`` when no
    directory is configured (the disarmed no-op every crash site may
    call unconditionally).  Never raises — a flight recorder that
    crashes the crash handler recorded nothing."""
    directory = directory or blackbox_dir()
    if not directory:
        return None
    try:
        from veles_tpu import prof, trace
        from veles_tpu.trace import export
        events = export.normalize()
        if len(events) > MAX_EVENTS:
            events = events[-MAX_EVENTS:]
        payload = {
            "kind": KIND,
            "reason": str(reason),
            "role": trace.recorder.role,
            "pid": os.getpid(),
            "time": time.time(),
            "trace_enabled": trace.enabled(),
            "events": events,
            "event_counts": trace.recorder.category_counts(),
            "ledger": prof.summary(),
        }
        from veles_tpu import watch
        health = watch.last_health()
        bus_events = watch.recent_events(MAX_BUS_EVENTS)
        if health is not None or bus_events:
            payload["watch"] = {"health": health,
                                "events": bus_events}
        if extra:
            payload["extra"] = dict(extra)
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, "blackbox-%s-%d-%d.json"
            % (trace.recorder.role.replace("/", "_"), os.getpid(),
               int(time.time() * 1e3)))
        tmp = path + ".tmp"
        with open(tmp, "w") as fout:
            # default=repr: a single odd value anywhere in the
            # payload (a provider-returned numpy scalar riding a bus
            # event, an exotic ledger field) must degrade to its repr
            # — never cost the whole flight record
            json.dump(payload, fout, default=repr)
        os.replace(tmp, path)
        return path
    except Exception:  # pragma: no cover - the recorder must not crash
        return None


def load(path):
    """Read a post-mortem back; raises ``ValueError`` on anything
    that is not one (tooling must not misread arbitrary JSON as
    evidence)."""
    with open(path, "r") as fin:
        payload = json.load(fin)
    if not isinstance(payload, dict) or payload.get("kind") != KIND:
        raise ValueError("%s is not a %s post-mortem" % (path, KIND))
    return payload


def _excepthook(tp, value, tb):
    dump("unhandled exception: %s: %s" % (tp.__name__, value))
    prev = _prev_excepthook[0] or sys.__excepthook__
    prev(tp, value, tb)


def _thread_excepthook(hook_args):
    # every role here RUNS on a thread (the job server loop, client
    # compute/heartbeat, batcher/scheduler workers) — sys.excepthook
    # never sees those, threading.excepthook does
    dump("unhandled exception in thread %s: %s: %s"
         % (getattr(hook_args.thread, "name", "?"),
            hook_args.exc_type.__name__, hook_args.exc_value))
    prev = _prev_thread_hook[0]
    if prev is not None:
        prev(hook_args)


def install(directory=None, signals=True):
    """Arm the excepthooks (process AND thread — the job/serving
    roles all run on threads) plus ``SIGTERM`` when safe, once per
    process.  Idempotent; a no-op when no directory is configured."""
    import threading
    if not (directory or blackbox_dir()):
        return False
    if _installed[0]:
        return True
    _installed[0] = True
    _prev_excepthook[0] = sys.excepthook
    sys.excepthook = _excepthook
    _prev_thread_hook[0] = threading.excepthook
    threading.excepthook = _thread_excepthook
    if signals:
        try:
            import signal
            import threading
            if threading.current_thread() \
                    is threading.main_thread() \
                    and signal.getsignal(signal.SIGTERM) \
                    is signal.SIG_DFL:
                def _on_term(signum, frame):
                    dump("fatal signal SIGTERM")
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

                signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):  # non-main thread / odd platform
            pass
    return True


def uninstall():
    """Test hygiene: restore the previous excepthooks."""
    import threading
    if not _installed[0]:
        return
    _installed[0] = False
    if sys.excepthook is _excepthook:
        sys.excepthook = _prev_excepthook[0] or sys.__excepthook__
    _prev_excepthook[0] = None
    if threading.excepthook is _thread_excepthook:
        threading.excepthook = _prev_thread_hook[0] \
            or threading.__excepthook__
    _prev_thread_hook[0] = None


def configure():
    """Apply the knob (called from ``obs.configure()`` at the same
    boundaries trace/chaos re-read theirs): arm when the directory is
    set, leave everything untouched otherwise."""
    return install()
