"""Fill the per-device-generation performance DB on the attached chip.

One command on real hardware:

    python -m veles_tpu.scripts.autotune [--db PATH] [--quick]

runs the device-power rating (13-chain matmul, ref
``accelerated_units.py:706-825``), the Pallas-vs-XLA GEMM tile sweep,
the int8-weight serving GEMM sweep (``ratings["gemm_int8"]``,
``--skip-int8``), the flash-attention block sweep and the fused
backward-GD sweep (``ratings["gd_v2"]``, ``--skip-gd``), and persists
the winners to
``veles_tpu/devices/device_infos.json`` (ref
``/root/reference/devices/device_infos.json``, filled by
``backends.py:623-744``).  ``ops.gemm.matmul`` and
``ops.attention.flash_attention`` consult the DB by default; commit the
file so the whole fleet benefits.
"""

import argparse
import json
import sys
import time


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--db", default=None,
                        help="DB path (default: the packaged "
                             "devices/device_infos.json)")
    parser.add_argument("--quick", action="store_true",
                        help="smaller shapes / fewer runs (smoke test)")
    parser.add_argument("--precision-levels", default="0",
                        help="comma list of reference precision levels "
                             "(config.py:246-249) to sweep; levels > 0 "
                             "race a pruned candidate set (accuracy-"
                             "first modes only need the pallas-vs-xla "
                             "verdict)")
    parser.add_argument("--skip-power", action="store_true")
    parser.add_argument("--skip-gemm", action="store_true")
    parser.add_argument("--skip-int8", action="store_true")
    parser.add_argument("--skip-attention", action="store_true")
    parser.add_argument("--skip-gd", action="store_true")
    parser.add_argument("--skip-s2d", action="store_true")
    parser.add_argument("--skip-gather", action="store_true")
    args = parser.parse_args(argv)

    import jax

    from veles_tpu.backends import enable_compilation_cache
    enable_compilation_cache(platform=jax.devices()[0].platform)
    from veles_tpu.backends import DEVICE_INFOS_JSON, DeviceInfo
    from veles_tpu.ops import benchmark

    db_path = args.db or DEVICE_INFOS_JSON
    model = jax.devices()[0].device_kind
    print("autotuning on %r → %s" % (model, db_path), file=sys.stderr)

    if not args.skip_gemm:
        levels = tuple(int(s) for s in
                       args.precision_levels.split(","))
        base = [lvl for lvl in levels if lvl == 0]
        high = [lvl for lvl in levels if lvl != 0]
        shapes = ((1024, 1024, 1024),) if args.quick else None
        if base:
            # level 0: full candidate sweep over the production shape
            # classes (SHAPE_CLASSES) — or the quick toy shape
            info = benchmark.autotune_gemm(
                shapes=shapes, runs=1 if args.quick else 2,
                db_path=db_path)
        if high:
            pruned = ((256, 512, 256), (512, 512, 512),
                      (512, 1024, 256))
            info = benchmark.autotune_gemm(
                shapes=shapes, runs=1 if args.quick else 2,
                db_path=db_path, candidates=pruned,
                precision_levels=tuple(high))
        print("gemm: %s" % json.dumps(info.ratings.get("gemm", {})),
              file=sys.stderr)
        print("gemm_v2: %s" % json.dumps(
            info.ratings.get("gemm_v2", {})), file=sys.stderr)

    if not args.skip_int8:
        # int8-weight serving GEMM (veles_tpu.ops.qgemm): the Pallas
        # dequant-epilogue kernel vs the dense dequant baseline —
        # ratings["gemm_int8"] is the row qmatmul's dispatch consults
        # for quantized deploys (ModelRegistry quantize="int8")
        shapes = ((1024, 1024, 1024),) if args.quick else None
        info = benchmark.autotune_gemm_int8(
            shapes=shapes, runs=1 if args.quick else 2,
            db_path=db_path)
        print("gemm_int8: %s" % json.dumps(
            info.ratings.get("gemm_int8", {})), file=sys.stderr)

    if not args.skip_attention:
        # quick: one toy shape; full: every sequence regime in
        # ATTN_SHAPE_CLASSES (round-3's DB held a single shape)
        # quick measures a toy shape, so it must NOT overwrite the
        # production winners (the quick-pass-poisons-rating hazard,
        # same guard as s2d/gather below): measure + print only
        shape = (2, 512, 4, 64) if args.quick else None
        info = benchmark.autotune_flash_attention(
            shape=shape, runs=1 if args.quick else 2, db_path=db_path,
            save=not args.quick)
        print("flash_attention: %s" % json.dumps(
            info.ratings.get("flash_attention", {})), file=sys.stderr)
        print("flash_attention_v2: %s" % json.dumps(
            info.ratings.get("flash_attention_v2", {})),
            file=sys.stderr)
        # the backward has its own sweep: 5 block matmuls with a
        # different VMEM footprint than the forward's 2 (VERDICT r4
        # item 2 — the LM backward is 75% of the step)
        info = benchmark.autotune_flash_attention_bwd(
            shape=shape, runs=1 if args.quick else 2, db_path=db_path,
            save=not args.quick)
        print("flash_attention_bwd_v2: %s" % json.dumps(
            info.ratings.get("flash_attention_bwd_v2", {})),
            file=sys.stderr)

    if not args.skip_gd:
        # fused backward-GD family (dW+optimizer epilogue / db / dX,
        # ops.gemm.gd_fused_pallas) vs the dense _gd_math reference —
        # the winner is what znicz.gd consults when
        # root.common.engine.kernels=auto.  Quick mode measures a toy
        # shape: measure + print only, never overwrite production
        # winners (the quick-pass-poisons-rating hazard class).
        shape = (32, 512, 256) if args.quick else None
        info = benchmark.autotune_gd(
            shape=shape, runs=1 if args.quick else 2, db_path=db_path,
            save=not args.quick)
        print("gd_v2%s: %s" % (
            " (quick, NOT saved)" if args.quick else "",
            json.dumps(info.ratings.get("gd_v2", {}))),
            file=sys.stderr)

    if not args.skip_s2d:
        # conv1 space-to-depth A/B: Conv.pure_config dispatches the
        # rewrite from this measurement (the heuristic said s2d on
        # v5-lite; the chip said 0.51x — r4 window 3).  Quick mode
        # measures a toy shape, so it must NOT overwrite the
        # production verdict (the round-3 quick-pass-poisons-rating
        # hazard class): measure + print only.
        info = benchmark.autotune_s2d(
            batch=32 if args.quick else 256,
            spatial=67 if args.quick else 227, db_path=db_path,
            save=not args.quick)
        print("s2d_conv%s: %s" % (
            " (quick, NOT saved)" if args.quick else "",
            json.dumps(info.ratings.get("s2d_conv", {}))),
            file=sys.stderr)

    if not args.skip_gather:
        # resident-dataset minibatch gather A/B (XLA vs the Pallas
        # DMA kernel): ~12 ms/step of the AlexNet e2e-vs-synthetic gap
        # in r4's banked ladder is this gather.  Quick mode: measure +
        # print only, never overwrite the production verdict.
        dts = ("uint8",) if args.quick else ("uint8", "float32")
        for dt in dts:   # u8 = the resident-native path; f32 = the
            # classic loader path.  n only needs to defeat caching —
            # gather cost scales with ROW bytes — and the dataset
            # crosses the (possibly tunneled) transport once per
            # sweep, so the f32 leg uses fewer rows (633 MB vs 2.5 GB)
            n = 256 if args.quick else (4096 if dt == "uint8"
                                        else 1024)
            info = benchmark.autotune_gather(
                n=n, row=(19, 19, 3) if args.quick else (227, 227, 3),
                batch=32 if args.quick else 256, dtype_name=dt,
                db_path=db_path, save=not args.quick)
        print("gather%s: %s" % (
            " (quick, NOT saved)" if args.quick else "",
            json.dumps(info.ratings.get("gather", {}))),
            file=sys.stderr)

    if not args.skip_power:
        # LAST, so the chain's matmul dispatch consults the sweep's
        # freshly-written winner instead of a stale/partial entry (the
        # round-3 quick-pass tiles once poisoned this very rating)
        sec, gflops = benchmark.estimate_device_power(
            size=1024 if args.quick else benchmark.BENCH_SIZE,
            runs=1 if args.quick else 3)
        db = DeviceInfo.load_db(db_path)
        info = db.setdefault(model, DeviceInfo(model))
        info.ratings["power"] = {"chain_seconds": sec, "gflops": gflops}
        DeviceInfo.save_db(db, db_path)
        print("power: %.4f s/chain = %.0f GFLOPs" % (sec, gflops),
              file=sys.stderr)

    db = DeviceInfo.load_db(db_path)
    # two-key envelope: the measured DB under "devices", run
    # provenance under "_this_run" — NOT injected into the
    # device-model namespace (a hypothetical device kind named
    # "_this_run" aside, consumers iterating models must not need a
    # skip-the-magic-key rule; ADVICE r5).  The dumped DB always
    # contains every previously-measured device (incl. TPU entries),
    # so a watcher checking "did the sweep run on real hardware?"
    # reads _this_run, never greps the devices table (code-review r5).
    report = {
        "devices": {m: i.ratings for m, i in db.items()},
        "_this_run": {"device_kind": model,
                      "ts": time.time(),
                      "argv": (sys.argv[1:] if argv is None
                               else list(argv))},
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
