"""Boot a multi-host SPMD fleet: run the SAME command on every node
with the ``VELES_COORDINATOR`` / ``VELES_NUM_PROCS`` / ``VELES_PROC_ID``
env vars set, so :func:`veles_tpu.parallel.multihost.initialize` joins
them into one JAX runtime (one global mesh, collectives over ICI/DCN).

    python -m veles_tpu.scripts.spmd_launch \
        -n hostA hostB:2222x2 --coordinator hostA:47010 -- \
        python train.py --my-args

The reference's analogue is the master ssh-booting its slave fleet
(``launch_remote_progs``, ``veles/launcher.py:617-660``) — but where
those slaves join a ZMQ job star, these processes run one lockstep
program.  ``--launch-transform`` swaps ssh for anything that takes the
command as one argument (``sh -c`` exercises the full path locally).
"""

import argparse
import shlex
import signal
import subprocess
import sys
import time

from veles_tpu.launcher import parse_nodes


def build_plan(nodes):
    """[(host, ssh_port, process_id)] in deterministic rank order;
    process 0 lands on the first node (where the coordinator usually
    runs)."""
    plan = []
    for host, port, count in parse_nodes(nodes):
        for _ in range(count):
            plan.append((host, port, len(plan)))
    if not plan:
        raise ValueError("no nodes given")
    return plan


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("-n", "--nodes", nargs="+", required=True,
                        help="host[:ssh_port][xN] specs; xN = N "
                             "processes on that host")
    parser.add_argument("--coordinator", default=None,
                        help="host:port of the JAX coordinator "
                             "(default: first node, port 47010)")
    parser.add_argument("--launch-transform",
                        default="ssh -o BatchMode=yes -p %(port)d "
                                "%(host)s",
                        help="prefix template; the command rides as "
                             "ONE trailing argument")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- command to run on every node")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (put it after --)")

    plan = build_plan(args.nodes)
    coordinator = args.coordinator or \
        "%s:47010" % plan[0][0]

    procs = []

    def reap(*_a):
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGTERM, reap)
    try:
        for host, port, pid in plan:
            prefix = shlex.split(args.launch_transform
                                 % {"host": host, "port": port})
            remote = "env %s %s" % (
                " ".join("%s=%s" % kv for kv in (
                    ("VELES_COORDINATOR", coordinator),
                    ("VELES_NUM_PROCS", len(plan)),
                    ("VELES_PROC_ID", pid))),
                shlex.join(command))
            print("spmd_launch: rank %d on %s: %s"
                  % (pid, host, remote), file=sys.stderr)
            procs.append(subprocess.Popen(prefix + [remote]))
        # fail fast: one dead rank leaves the others blocked on their
        # next cross-host collective forever — tear the fleet down on
        # the first nonzero exit instead of waiting rank by rank
        while True:
            codes = [p.poll() for p in procs]
            bad = next((c for c in codes if c not in (None, 0)), None)
            if bad is not None:
                print("spmd_launch: rank %d exited rc=%d; reaping the "
                      "fleet" % (codes.index(bad), bad),
                      file=sys.stderr)
                return bad
            if all(c == 0 for c in codes):
                return 0
            time.sleep(0.2)
    finally:
        reap()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


if __name__ == "__main__":
    sys.exit(main())
