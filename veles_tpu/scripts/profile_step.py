"""Step-time breakdown for a sample's fused train step.

Measures, with the honest timing discipline of ``ops/timing.py``
(result-derived host-fetch sync + marginal timing):

- forward only (inference apply)
- forward + backward (value_and_grad, no update)
- the full train step (forward + backward + momentum update)

and prints a markdown table with per-phase seconds, derived phase
costs, images/sec and MFU.  Run on the real chip:

    python -m veles_tpu.scripts.profile_step [--sample alexnet]
        [--batch 256] [--out PROFILE.md]

(ref: the per-unit timer table ``workflow.py:767-826`` and the
``--sync-run`` kernel-accuracy note ``accelerated_units.py:294-297`` —
this is the fused-step analogue.)
"""

import argparse
import sys


def _peak_flops(device_kind):
    from veles_tpu.backends import peak_bf16_flops
    return peak_bf16_flops(device_kind)


def build(sample, batch):
    import jax
    import jax.numpy as jnp
    import numpy

    from veles_tpu import prng
    from veles_tpu.znicz.fused_graph import lower_specs

    prng.seed_all(1234)
    if sample == "transformer":
        # the GPT LM (bench stage config).  Keep --batch <= 32: the
        # chunked-CE live memory is O(batch * 128 * vocab) floats.
        # Honors the SAME BENCH_LM_REMAT / BENCH_LM_CE_CHUNK knobs as
        # bench.py's transformer stage, so PROFILE_LM.md describes the
        # exact program the banked LM line measured.
        import os
        from veles_tpu.samples import transformer as T
        cfg = {"vocab": 32000, "dim": 512, "heads": 8, "layers": 8,
               "mlp_ratio": 4, "seq_len": 1024}
        params0 = T.init_params(cfg, seed=0)
        velocity = jax.tree.map(numpy.zeros_like, params0)
        raw_step = T.make_train_step(
            cfg,
            remat=os.environ.get("BENCH_LM_REMAT", "0") == "1",
            ce_chunk=int(os.environ.get("BENCH_LM_CE_CHUNK", "128")))

        def step(state, x, _labels):
            p, v = state
            p, v, metrics = raw_step(p, v, x)
            return (p, v), metrics

        def apply_fn(state, x):
            return T.apply_fn(state[0], x, cfg)

        train_flops = T.train_step_flops(cfg, batch)
        flops_overrides = {"full_step": train_flops,
                           "forward": train_flops / 3.0}
        x = jax.device_put(T.synthetic_tokens(cfg, batch))
        labels = jax.device_put(
            numpy.zeros((batch,), numpy.int32))
        return ((params0, velocity), step, apply_fn, x, labels,
                flops_overrides)
    if sample == "mnist":
        from __graft_entry__ import MNIST_LAYERS
        from veles_tpu.znicz.fused import (init_mlp_params,
                                           make_train_step, mlp_apply,
                                           _specs_static)
        params = init_mlp_params(784, MNIST_LAYERS)
        step = make_train_step(MNIST_LAYERS)
        static = _specs_static(MNIST_LAYERS)

        def apply_fn(p, x):
            return mlp_apply(p, x, static)
        shape = (784,)
        n_classes = 10
    else:
        mod = __import__("veles_tpu.samples.%s" % sample,
                         fromlist=[sample])
        layers = mod.LAYERS
        shape = getattr(mod, "INPUT_SHAPE", (32, 32, 3))
        n_classes = 1000 if sample == "alexnet" else 10
        params, step, _eval, apply_raw = lower_specs(
            layers, shape, compute_dtype=jnp.bfloat16)

        def apply_fn(p, x):
            return apply_raw(p, x, train=False)
    # recurrent samples: XLA cost analysis counts the T-step sequence
    # scan body ONCE, so FLOPs must come from the analytic closed form
    # (see measure_fused_step's inner-scan caveat)
    flops_overrides = None
    if sample == "mnist_rnn":
        from veles_tpu.znicz.rnn import lstm_fwd_flops, lstm_train_flops
        t, d = shape
        h = int(layers[0]["->"]["hidden_units"])
        flops_overrides = {
            "full_step": lstm_train_flops(batch, t, d, h,
                                          head_classes=n_classes),
            "forward": lstm_fwd_flops(batch, t, d, h,
                                      head_classes=n_classes),
        }
    rng = numpy.random.default_rng(0)
    x = jax.device_put(rng.standard_normal(
        (batch,) + tuple(shape)).astype(numpy.float32))
    labels = jax.device_put(
        rng.integers(0, n_classes, batch).astype(numpy.int32))
    return params, step, apply_fn, x, labels, flops_overrides


def measure_phases(params, step, apply_fn, x, labels, k=10,
                   min_seconds=None, flops_overrides=None):
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.timing import (cost_flops, inprogram_marginal,
                                      measure_fused_step)

    phases = {}
    overrides = flops_overrides or {}

    # full step: in-program two-trip-count marginal (the bench
    # methodology — see ops/timing.py round-3 notes)
    sec, flops = measure_fused_step(
        step, jax.device_put(params), x, labels, k=max(k, 8),
        flops_override=overrides.get("full_step"))
    phases["full_step"] = (sec, flops)

    # forward-only: the same in-program marginal over inference applies,
    # serialized (see _serialized_forward_unit)
    dparams = jax.device_put(params)
    unit = _serialized_forward_unit(lambda p, xx: apply_fn(p, xx),
                                    dparams)

    # flops of one apply: the loop program counts the body ONCE plus
    # the warmup inline iteration — both identical applies, so /2 via a
    # dedicated lowering is unnecessary; use a 1-apply compile instead
    if overrides.get("forward"):
        fwd_flops = overrides["forward"]
    else:
        fwd1 = jax.jit(lambda a, b: apply_fn(a, b)).lower(params, x)
        fwd_flops = cost_flops(fwd1.compile())
    sec_fwd = inprogram_marginal(unit, (x, jnp.float32(0.0)),
                                 k1=2, k2=max(k, 8))
    phases["forward"] = (sec_fwd, fwd_flops)
    return phases


def _serialized_forward_unit(apply2, dparams):
    """The forward-timing loop body shared by measure_phases and
    measure_per_layer: iterations are serialized by feeding a result
    scalar back into one input element (hoist/CSE defeat), and the
    probe abs-sums the WHOLE output — a single-element probe would let
    XLA slice the forward pass down to batch row 0."""
    import jax
    import jax.numpy as jnp

    def unit(carry):
        x_, s = carry
        lead = x_[(slice(0, 1),) * x_.ndim]
        x_ = jax.lax.dynamic_update_slice(
            x_, (lead + (s * 1e-30).astype(x_.dtype)),
            (0,) * x_.ndim)
        o = apply2(dparams, x_)
        return x_, jnp.sum(jnp.abs(o), dtype=jnp.float32)

    return unit


def measure_per_layer(sample, batch, k=8, full_forward=None):
    """Forward seconds per LAYER, by timing each prefix of the layer
    stack (prefix k minus prefix k-1) with the in-program marginal.
    Layer-spec samples only (lower_specs; recurrent samples are
    excluded by the caller — a prefix's cost-analysis FLOPs would
    undercount their inner scan bodies).  Returns
    ``[(label, sec, flops), ...]``; negative differences (two prefixes
    within mutual noise) are clamped to 0.

    ``full_forward``: the already-measured ``(sec, flops)`` of the
    FULL forward (measure_phases), reused for the final prefix so the
    whole stack is not re-timed and re-compiled.
    """
    import jax
    import jax.numpy as jnp
    import numpy

    from veles_tpu import prng
    from veles_tpu.ops.timing import cost_flops, inprogram_marginal
    from veles_tpu.znicz.fused_graph import lower_specs

    mod = __import__("veles_tpu.samples.%s" % sample,
                     fromlist=[sample])
    layers = mod.LAYERS
    shape = getattr(mod, "INPUT_SHAPE", (32, 32, 3))
    rng = numpy.random.default_rng(0)
    x = jax.device_put(rng.standard_normal(
        (batch,) + tuple(shape)).astype(numpy.float32))

    rows, prev_sec, prev_flops = [], 0.0, 0.0
    for n_layers in range(1, len(layers) + 1):
        if full_forward is not None and n_layers == len(layers):
            sec, flops = full_forward
            flops = flops or 0.0
        else:
            prng.seed_all(1234)
            params, _s, _e, apply_raw = lower_specs(
                layers[:n_layers], shape, compute_dtype=jnp.bfloat16)
            dparams = jax.device_put(params)
            unit = _serialized_forward_unit(
                lambda p, xx, _a=apply_raw: _a(p, xx, train=False),
                dparams)
            sec = inprogram_marginal(unit, (x, jnp.float32(0.0)),
                                     k1=2, k2=k)
            flops = cost_flops(jax.jit(
                lambda p, xx, _a=apply_raw: _a(p, xx, train=False)
            ).lower(params, x).compile()) or 0.0
        label = layers[n_layers - 1].get("type", "?")
        rows.append(("%02d %s" % (n_layers, label),
                     max(sec - prev_sec, 0.0),
                     max(flops - prev_flops, 0.0)))
        prev_sec, prev_flops = sec, flops
    return rows


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--sample", default="alexnet",
                        choices=("alexnet", "cifar10", "mnist",
                                 "mnist_rnn", "stl10", "transformer"))
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument("--k", type=int, default=10)
    parser.add_argument("--out", default=None)
    parser.add_argument("--per-layer", action="store_true",
                        help="append a per-layer forward breakdown "
                             "(prefix-difference timing; layer-spec "
                             "samples only)")
    args = parser.parse_args(argv)

    import jax

    from veles_tpu.backends import enable_compilation_cache
    enable_compilation_cache(platform=jax.devices()[0].platform)
    kind = jax.devices()[0].device_kind
    (params, step, apply_fn, x, labels,
     flops_overrides) = build(args.sample, args.batch)
    phases = measure_phases(params, step, apply_fn, x, labels,
                            k=args.k, flops_overrides=flops_overrides)

    full_sec, full_flops = phases["full_step"]
    fwd_sec, fwd_flops = phases["forward"]
    bwd_sec = full_sec - fwd_sec
    peak = _peak_flops(kind)
    lines = [
        "# %s fused-step profile — %s, batch %d" % (
            args.sample, kind, args.batch),
        "",
        "| Phase | sec/step | share | GFLOP | TFLOP/s |",
        "|---|---|---|---|---|",
    ]
    for name, sec, flops in (
            ("forward", fwd_sec, fwd_flops),
            ("backward+update (derived)", bwd_sec,
             (full_flops - fwd_flops) if full_flops and fwd_flops
             else None),
            ("full step", full_sec, full_flops)):
        tf = (flops / sec / 1e12) if flops and sec > 0 else None
        lines.append("| %s | %.6f | %.0f%% | %s | %s |" % (
            name, sec, 100.0 * sec / full_sec,
            "%.2f" % (flops / 1e9) if flops else "—",
            "%.1f" % tf if tf else "—"))
    ips = args.batch / full_sec
    mfu = (full_flops / full_sec / peak) if (full_flops and peak) \
        else None
    lines += ["",
              "- images/sec: **%.1f**" % ips,
              "- MFU: **%s**" % ("%.4f" % mfu if mfu else "n/a"),
              "- peak bf16 FLOP/s assumed: %s" % (
                  "%.0fe12" % (peak / 1e12) if peak else "unknown")]
    if args.per_layer:
        if args.sample in ("mnist", "transformer", "mnist_rnn"):
            # mnist/transformer are not layer-spec builds; mnist_rnn's
            # inner T-step scan breaks prefix cost analysis (counted
            # once — the same caveat build() fixes analytically)
            lines += ["", "(per-layer breakdown: layer-spec samples "
                          "only — skipped for %s)" % args.sample]
        else:
            rows = measure_per_layer(args.sample, args.batch,
                                     k=max(args.k, 8),
                                     full_forward=phases["forward"])
            lines += ["", "## Per-layer forward (prefix-difference)",
                      "",
                      "(consecutive-prefix differences: rows at or "
                      "below the stopwatch's noise floor print 0 and "
                      "the first row absorbs the carry-update "
                      "overhead — read ms-scale rows, not µs ones)",
                      "",
                      "| layer | sec | share | GFLOP | TFLOP/s |",
                      "|---|---|---|---|---|"]
            for label, sec, flops in rows:
                tf = (flops / sec / 1e12) if flops and sec > 0 \
                    else None
                lines.append("| %s | %.6f | %.0f%% | %s | %s |" % (
                    label, sec,
                    (100.0 * sec / fwd_sec) if fwd_sec else 0.0,
                    "%.2f" % (flops / 1e9) if flops else "—",
                    "%.1f" % tf if tf else "—"))
    report = "\n".join(lines)
    print(report)
    if args.out:
        with open(args.out, "w") as fout:
            fout.write(report + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
