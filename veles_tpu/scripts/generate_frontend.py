"""Generate an HTML frontend form from the CLI argument registry.

Parity target: reference ``veles/scripts/generate_frontend.py`` — walks
the ``CommandLineArgumentsRegistry`` parser and emits an HTML form whose
inputs compose a ``veles`` command line (served by ``Main._open_frontend``
``__main__.py:258-333``).

Usage: ``python -m veles_tpu.scripts.generate_frontend [out.html]``
"""

import argparse
import html
import sys

from veles_tpu.cmdline import make_parser

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>veles_tpu frontend</title>
<style>
body {{ font-family: sans-serif; margin: 2em; max-width: 60em; }}
label {{ display: inline-block; min-width: 16em; font-weight: bold; }}
.row {{ margin: 0.4em 0; }}
.help {{ color: #666; font-size: 0.85em; margin-left: 16em; }}
#cmdline {{ background: #f4f4f4; padding: 1em; font-family: monospace;
            margin-top: 1.5em; white-space: pre-wrap; }}
</style></head><body>
<h1>veles_tpu launcher</h1>
<form oninput="compose()" onchange="compose()">
{rows}
</form>
<div id="cmdline">python -m veles_tpu</div>
<script>
function compose() {{
  var parts = ["python -m veles_tpu"];
  var fields = document.querySelectorAll("[data-flag]");
  var positional = [];
  fields.forEach(function(el) {{
    var flag = el.getAttribute("data-flag");
    if (el.type === "checkbox") {{
      if (el.checked) parts.push(flag);
    }} else if (el.value !== "" && el.value !== el.getAttribute(
        "data-default")) {{
      if (flag === "") positional.push(el.value);
      else parts.push(flag + " " + el.value);
    }} else if (flag === "" && el.value !== "") {{
      positional.push(el.value);
    }}
  }});
  document.getElementById("cmdline").textContent =
      parts.concat(positional).join(" ");
}}
</script>
</body></html>
"""


def _row(action):
    name = action.option_strings[-1] if action.option_strings \
        else action.dest
    flag = action.option_strings[-1] if action.option_strings else ""
    ident = "arg_%s" % action.dest
    helptext = html.escape(action.help or "")
    default = "" if action.default in (None, argparse.SUPPRESS) \
        else html.escape(str(action.default))
    if isinstance(action, (argparse._StoreTrueAction,
                           argparse._StoreFalseAction)):
        control = ('<input type="checkbox" id="%s" data-flag="%s"/>'
                   % (ident, flag))
    elif action.choices:
        options = "".join('<option>%s</option>'
                          % html.escape(str(c)) for c in action.choices)
        control = ('<select id="%s" data-flag="%s" data-default="%s">'
                   '<option value=""></option>%s</select>'
                   % (ident, flag, default, options))
    else:
        control = ('<input type="text" id="%s" data-flag="%s" '
                   'data-default="%s" placeholder="%s"/>'
                   % (ident, flag, default, default))
    return ('<div class="row"><label for="%s">%s</label>%s'
            '<div class="help">%s</div></div>'
            % (ident, html.escape(name), control, helptext))


def generate():
    # importing the components registers their arg contributions (the
    # reference generated the form from whatever was in-process)
    import veles_tpu.backends    # noqa: F401
    import veles_tpu.launcher    # noqa: F401
    parser = make_parser()
    rows = [_row(action) for action in parser._actions
            if not isinstance(action, argparse._HelpAction)]
    return _PAGE.format(rows="\n".join(rows))


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    out = argv[0] if argv else "frontend.html"
    with open(out, "w") as fout:
        fout.write(generate())
    print("wrote %s" % out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
