"""Collaborative image bounding-box labeling tool.

Parity target: ``/root/reference/veles/scripts/bboxer.py`` (tornado app:
image browser + canvas bbox editor; selections persist as ``<image>.json``
sidecars; concurrent-edit conflicts are rejected unless overwritten).

Fresh TPU-repo design: same sidecar format and conflict semantics, no
pyinotify/thumbnail-cache dependencies — images are listed per request
and served directly (browsers scale them; datasets labeled here are
typically small crops anyway).

Run: ``python -m veles_tpu.scripts.bboxer --root DIR [--port 8090]``
"""

import argparse
import json
import os
import sys

IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

_PAGE = """<!DOCTYPE html>
<html><head><title>bboxer</title><style>
body { font-family: sans-serif; margin: 1em; }
#files a { display: block; }
#wrap { position: relative; display: inline-block; }
#img { max-width: 90vw; }
canvas { position: absolute; left: 0; top: 0; cursor: crosshair; }
</style></head><body>
<h2>bboxer — %(nfiles)d images under %(root)s</h2>
<div id="files">%(links)s</div>
<div id="editor" style="display:none">
  <p><b id="fname"></b>
     <button onclick="save(false)">save</button>
     <button onclick="save(true)">overwrite</button>
     <button onclick="boxes.pop(); redraw()">undo box</button>
     <input id="label" placeholder="label"></p>
  <div id="wrap"><img id="img"><canvas id="cv"></canvas></div>
</div>
<script>
let boxes = [], cur = null, drag = null;
const img = document.getElementById("img"),
      cv = document.getElementById("cv"),
      ctx = cv.getContext("2d");
document.getElementById("files").addEventListener("click", e => {
  const f = e.target.dataset && e.target.dataset.f;
  if (f) { e.preventDefault(); open_image(f); }
});
function open_image(f) {
  cur = f;
  document.getElementById("editor").style.display = "block";
  document.getElementById("fname").textContent = f;
  img.onload = () => {
    cv.width = img.width; cv.height = img.height;
    fetch("selections", {method: "POST",
                         body: JSON.stringify({file: f})})
      .then(r => r.json()).then(s => { boxes = s; redraw(); });
  };
  img.src = "image/" + encodeURIComponent(f);
}
function scale() { return img.naturalWidth / img.width; }
function redraw() {
  ctx.clearRect(0, 0, cv.width, cv.height);
  ctx.strokeStyle = "#f00"; ctx.fillStyle = "#f00"; ctx.font = "12px sans-serif";
  for (const b of boxes) {
    const k = 1 / scale();
    ctx.strokeRect(b.x * k, b.y * k, b.w * k, b.h * k);
    ctx.fillText(b.label || "?", b.x * k + 2, b.y * k + 12);
  }
}
cv.onmousedown = e => { drag = [e.offsetX, e.offsetY]; };
cv.onmouseup = e => {
  if (!drag) return;
  const k = scale();
  boxes.push({x: Math.min(drag[0], e.offsetX) * k,
              y: Math.min(drag[1], e.offsetY) * k,
              w: Math.abs(e.offsetX - drag[0]) * k,
              h: Math.abs(e.offsetY - drag[1]) * k,
              label: document.getElementById("label").value});
  drag = null; redraw();
};
function save(overwrite) {
  fetch("update", {method: "POST", body: JSON.stringify(
    {file: cur, selections: boxes, overwrite: overwrite})})
    .then(r => { if (!r.ok) alert("conflict: someone else labeled " +
      "this image — reload or use overwrite"); });
}
</script></body></html>
"""


def sidecar(path):
    return path + ".json"


def make_app(root_dir):
    import tornado.web

    root_dir = os.path.abspath(root_dir)

    def resolve(rel):
        path = os.path.abspath(os.path.join(root_dir, rel))
        if not path.startswith(root_dir + os.sep) and path != root_dir:
            raise tornado.web.HTTPError(403)
        return path

    def list_images():
        out = []
        for base, _dirs, files in os.walk(root_dir):
            for name in sorted(files):
                if name.lower().endswith(IMAGE_EXTS):
                    out.append(os.path.relpath(
                        os.path.join(base, name), root_dir))
        return out

    class MainHandler(tornado.web.RequestHandler):
        def get(self):
            import html as _html
            files = list_images()
            # filenames ride in a data attribute (html-escaped, quote
            # safe) — never interpolated into JS or raw markup
            links = "".join(
                '<a href="#" data-f="%s">%s%s</a>' % (
                    _html.escape(f, quote=True), _html.escape(f),
                    " ✓" if os.path.exists(sidecar(resolve(f)))
                    else "")
                for f in files)
            self.write(_PAGE % {"nfiles": len(files),
                                "root": _html.escape(root_dir),
                                "links": links})

    class ImageHandler(tornado.web.RequestHandler):
        def get(self, rel):
            # images only (never the .json sidecars or other files),
            # with a real image Content-Type — tornado's text/html
            # default would let attacker-authored sidecar content
            # execute as a page in this origin
            if not rel.lower().endswith(IMAGE_EXTS):
                raise tornado.web.HTTPError(403)
            path = resolve(rel)
            if not os.path.exists(path):
                raise tornado.web.HTTPError(404)
            import mimetypes
            ctype = mimetypes.guess_type(rel)[0] or \
                "application/octet-stream"
            self.set_header("Content-Type", ctype)
            self.set_header("X-Content-Type-Options", "nosniff")
            with open(path, "rb") as fin:
                self.write(fin.read())

    def resolve_image(rel):
        if not str(rel).lower().endswith(IMAGE_EXTS):
            raise tornado.web.HTTPError(403)
        return resolve(rel)

    class SelectionsHandler(tornado.web.RequestHandler):
        def post(self):
            data = json.loads(self.request.body)
            path = sidecar(resolve_image(data["file"]))
            if os.access(path, os.R_OK):
                with open(path, "r") as fin:
                    self.write(fin.read())
            else:
                self.write("[]")
            self.set_header("Content-Type", "application/json")

    class UpdateHandler(tornado.web.RequestHandler):
        def post(self):
            data = json.loads(self.request.body)
            path = sidecar(resolve_image(data["file"]))
            if os.path.exists(path) and not data.get("overwrite"):
                with open(path, "r") as fin:
                    existing = json.load(fin)
                if existing != data["selections"]:
                    # collaborative conflict (ref UpdateHandler:
                    # bboxer.py 403 semantics)
                    raise tornado.web.HTTPError(403)
            with open(path, "w") as fout:
                json.dump(data["selections"], fout)
            self.write({"ok": True})

    return tornado.web.Application([
        (r"/", MainHandler),
        (r"/image/(.*)", ImageHandler),
        (r"/selections", SelectionsHandler),
        (r"/update", UpdateHandler),
    ])


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root", required=True,
                        help="directory of images to label")
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address (0.0.0.0 for collaborative "
                             "LAN labeling)")
    args = parser.parse_args(argv)
    import tornado.ioloop
    app = make_app(args.root)
    app.listen(args.port, address=args.host)
    print("bboxer serving %s on http://%s:%d/" % (
        args.root, args.host, args.port), file=sys.stderr)
    tornado.ioloop.IOLoop.current().start()
    return 0


if __name__ == "__main__":
    sys.exit(main())
