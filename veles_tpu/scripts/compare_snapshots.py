"""Compare two workflow snapshots unit-by-unit.

Parity target: reference ``veles/scripts/compare_snapshots.py`` — loads
two pickled workflows and reports numeric deltas per attribute (the
reference used ``NumDiff``, ``numpy_ext.py:116``).

Usage: ``python -m veles_tpu.scripts.compare_snapshots A.snap B.snap``
"""

import sys

import numpy

from veles_tpu.memory import Vector


def _vectors_of(workflow):
    """{unit_name.attr: ndarray} for every Vector on every unit."""
    out = {}
    for unit in workflow:
        for attr, value in vars(unit).items():
            if isinstance(value, Vector) and value:
                out["%s.%s" % (unit.name, attr)] = numpy.asarray(
                    value.mem)
    return out


def compare(workflow_a, workflow_b, rtol=1e-5, atol=1e-6):
    """Returns (report_rows, worst_delta).  Row: (key, status, delta)
    where status is one of equal/close/DIFFERENT/only-in-A/only-in-B."""
    va, vb = _vectors_of(workflow_a), _vectors_of(workflow_b)
    rows = []
    worst = 0.0
    for key in sorted(set(va) | set(vb)):
        if key not in va:
            rows.append((key, "only-in-B", None))
            continue
        if key not in vb:
            rows.append((key, "only-in-A", None))
            continue
        a, b = va[key], vb[key]
        if a.shape != b.shape:
            rows.append((key, "DIFFERENT", "shape %s vs %s"
                         % (a.shape, b.shape)))
            worst = float("inf")
            continue
        delta = float(numpy.abs(a - b).max()) if a.size else 0.0
        worst = max(worst, delta)
        if delta == 0.0:
            rows.append((key, "equal", 0.0))
        elif numpy.allclose(a, b, rtol=rtol, atol=atol):
            rows.append((key, "close", delta))
        else:
            rows.append((key, "DIFFERENT", delta))
    return rows, worst


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        print(__doc__)
        return 2
    from veles_tpu.snapshotter import load_snapshot
    wf_a = load_snapshot(argv[0])
    wf_b = load_snapshot(argv[1])
    rows, worst = compare(wf_a, wf_b)
    for key, status, delta in rows:
        print("%-50s %-12s %s" % (key, status,
                                  "" if delta is None else delta))
    print("worst delta: %s" % worst)
    return 0 if worst == 0.0 else 1


if __name__ == "__main__":
    sys.exit(main())
