"""Forge command-line client (ref ``veles/scripts/update_forge.py`` and
the ``forge`` console entry, ``setup.py:88-92``).

Usage:
  python -m veles_tpu.scripts.forge_cli list --server URL
  python -m veles_tpu.scripts.forge_cli upload NAME PACKAGE --server URL --token T
  python -m veles_tpu.scripts.forge_cli fetch NAME DEST --server URL
  python -m veles_tpu.scripts.forge_cli delete NAME --server URL --token T
  python -m veles_tpu.scripts.forge_cli serve DIR --port P --tokens T=user
"""

import argparse
import json
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(prog="veles_tpu-forge")
    sub = parser.add_subparsers(dest="verb", required=True)
    for verb in ("list", "upload", "fetch", "delete", "serve"):
        p = sub.add_parser(verb)
        p.add_argument("--server", default="http://127.0.0.1:8180")
        p.add_argument("--token", default=None)
        if verb == "upload":
            p.add_argument("name")
            p.add_argument("package")
            p.add_argument("--version", default=None)
        elif verb == "fetch":
            p.add_argument("name")
            p.add_argument("dest")
            p.add_argument("--version", default=None)
        elif verb == "delete":
            p.add_argument("name")
        elif verb == "serve":
            p.add_argument("directory")
            p.add_argument("--port", type=int, default=8180)
            p.add_argument("--tokens", nargs="*", default=(),
                           metavar="TOKEN=USER")
    args = parser.parse_args(argv)

    if args.verb == "serve":
        from veles_tpu.forge import ForgeServer
        tokens = dict(pair.split("=", 1) for pair in args.tokens)
        server = ForgeServer(args.directory, tokens=tokens,
                             port=args.port).start()
        print("forge server on %s — Ctrl-C to stop" % server.endpoint)
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            server.stop()
        return 0

    from veles_tpu.forge import ForgeClient
    client = ForgeClient(args.server, token=args.token)
    if args.verb == "list":
        print(json.dumps(client.list(), indent=1))
    elif args.verb == "upload":
        print(json.dumps(client.upload(args.name, args.package,
                                       version=args.version), indent=1))
    elif args.verb == "fetch":
        client.fetch(args.name, args.dest, version=args.version)
        print("fetched %s → %s" % (args.name, args.dest))
    elif args.verb == "delete":
        client.delete(args.name)
        print("deleted %s" % args.name)
    return 0


if __name__ == "__main__":
    sys.exit(main())
