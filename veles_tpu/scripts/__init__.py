"""Utility scripts (SURVEY §2.5): snapshot diffing, web-frontend
generation, forge CLI — the reference's ``veles/scripts/`` equivalents.
"""
