"""``python -m veles_tpu.chaos`` — fault-injection CLI.

``--smoke`` (the ``scripts/lint.sh`` CI gate) runs a fixed-seed,
in-process master–slave session over real ZMQ sockets with a schedule
injecting a slave death mid-job, a dropped job frame and a duplicated
update frame.  It must complete — no hang, every job applied EXACTLY
once, dedup/requeue counters consistent with the injections — or exit
non-zero.  ``--schedule file.json`` replays a saved schedule instead
of the built-in one — then only the universal gates apply (session
completes, every job exactly once), since the fault-specific counter
checks encode the built-in schedule; ``--json`` prints the
machine-readable summary.
"""

import argparse
import collections
import json
import sys

from veles_tpu.chaos.core import ChaosSchedule, controller

#: the smoke's built-in schedule: one slave death holding a job, one
#: lost job frame (master→slave), one duplicated update frame
#: (slave→master) — the three headline faults of docs/robustness.md
SMOKE_SCHEDULE = [
    {"site": "slave_job", "action": "slave_kill", "nth": 5},
    {"site": "master_send", "action": "drop", "op": "job", "nth": 2},
    {"site": "slave_send", "action": "dup", "op": "update", "nth": 3},
]
SMOKE_JOBS = 12
SMOKE_SEED = 20260804


class SmokeMaster(object):
    """Requeueing scripted master: jobs are numbered 1..n, a dropped
    slave's (or lost frame's) numbers go back on the queue — the same
    no-work-lost contract the real loader keeps via
    ``failed_minibatches``."""

    def __init__(self, n_jobs):
        self.queue = collections.deque(range(1, n_jobs + 1))
        self.pending = {}
        self.applied = []
        #: job numbers returned to the queue (reaper drop OR lost-frame
        #: rejoin — both recovery paths land here)
        self.requeues = 0

    def checksum(self):
        return "chaos-smoke-v1"

    def generate_data_for_slave(self, slave):
        from veles_tpu.workflow import NoJobYet
        if not self.queue:
            if any(self.pending.values()):
                # outstanding work may still be requeued — a None here
                # would latch no_more_jobs and lose it forever
                raise NoJobYet
            return None
        number = self.queue.popleft()
        self.pending.setdefault(slave.id, []).append(number)
        return {"job_number": number}

    def apply_data_from_slave(self, data, slave):
        number = data["result"]
        mine = self.pending.get(slave.id, [])
        if number in mine:
            mine.remove(number)
        self.applied.append(number)

    def drop_slave(self, slave):
        numbers = self.pending.pop(slave.id, [])
        self.requeues += len(numbers)
        self.queue.extend(numbers)


class SmokeSlave(object):
    def checksum(self):
        return "chaos-smoke-v1"

    def do_job(self, data, callback):
        callback({"result": data["job_number"]})


def run_smoke(schedule=None, seed=SMOKE_SEED, n_jobs=SMOKE_JOBS,
              as_json=False):
    from veles_tpu.parallel.jobs import JobClient, JobServer

    controller.arm(schedule if schedule is not None
                   else list(SMOKE_SCHEDULE), seed=seed)
    master = SmokeMaster(n_jobs)
    # slave_timeout ABOVE the client's 5 s rpc timeout: a dropped job
    # frame is then recovered by the client's reconnect/rejoin (the
    # lost-frame requeue path) rather than racing the reaper; the dead
    # slave's requeue still exercises the reaper path
    server = JobServer(master, slave_timeout=8.0,
                       heartbeat_interval=0.4).start()
    survivors = []
    try:
        # slave 1 is scheduled to die holding a job; slave 2 joins
        # afterwards (elastic membership) and finishes the queue
        for _ in range(3):
            client = JobClient(SmokeSlave(), server.endpoint,
                               heartbeat_interval=0.4,
                               reconnect_max_wait=10.0)
            client.handshake()
            survived = client.run()
            client.close()
            survivors.append(survived)
            if survived:
                break
    finally:
        server.stop()
        snap = controller.snapshot()
        controller.disarm()

    expected = list(range(1, n_jobs + 1))
    problems = []
    if sorted(master.applied) != expected:
        problems.append(
            "jobs not applied exactly once: %r" % (
                sorted(master.applied),))
    if not master.applied:
        problems.append("zero jobs done")
    if not survivors or not survivors[-1]:
        problems.append("no slave survived to session end")
    injected = snap["injected"]
    if schedule is None:
        # consistency checks tied to the BUILT-IN schedule's exact
        # faults (one slave_send update dup, one master_send job drop,
        # one slave kill) — a user-replayed --schedule keeps only the
        # universal gates above: its faults may dup acks (no master
        # dedup), drop nothing, or kill nobody, and each would trip
        # these spuriously
        if not any(s is False for s in survivors):
            problems.append("the scheduled slave death never fired")
        if server.dedup_dropped < injected.get("dup", 0):
            # >= not ==: a slow master can make the slave retry an
            # already-applied update, adding dedups beyond the
            # injected dup; FEWER dedups than dups means a duplicate
            # slipped past (exactly-once above would catch the double
            # apply — this names the broken counter)
            problems.append(
                "dedup counter inconsistent: %d deduplicated vs %d "
                "dup frame(s) injected" % (server.dedup_dropped,
                                           injected.get("dup", 0)))
        if injected.get("drop", 0) and not server.lost_requeued:
            problems.append(
                "a job frame was dropped but the lost-frame requeue "
                "path never fired")
        if injected.get("slave_kill", 0) and master.requeues < 2:
            problems.append(
                "expected requeues from both the dead slave (reaper) "
                "and the dropped frame (rejoin), saw %d"
                % master.requeues)
    summary = {
        "ok": not problems,
        "jobs_applied": len(master.applied),
        "requeues": master.requeues,
        "slaves_run": len(survivors),
        "dedup_dropped": server.dedup_dropped,
        "stale_rejected": server.stale_rejected,
        "lost_requeued": server.lost_requeued,
        "chaos": snap,
        "problems": problems,
    }
    if as_json:
        print(json.dumps(summary, indent=2))
    else:
        print("chaos smoke: %d/%d jobs applied exactly once, "
              "%d slave run(s), dedup=%d stale=%d requeued=%d, "
              "faults_injected=%d"
              % (len(master.applied), n_jobs, len(survivors),
                 server.dedup_dropped, server.stale_rejected,
                 server.lost_requeued, snap["faults_injected"]))
        for problem in problems:
            print("PROBLEM: %s" % problem)
    return 0 if not problems else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.chaos",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI fault-injection gate")
    parser.add_argument("--schedule", default=None, metavar="JSON",
                        help="replay this schedule file instead of "
                             "the built-in smoke schedule")
    parser.add_argument("--seed", type=int, default=SMOKE_SEED)
    parser.add_argument("--jobs", type=int, default=SMOKE_JOBS)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary")
    args = parser.parse_args(argv)
    schedule = None
    if args.schedule:
        schedule = ChaosSchedule.load(args.schedule)
    if args.smoke or schedule is not None:
        # an in-code watchdog on top of the caller's `timeout` wrapper:
        # a hang IS the failure mode under test, never a silent stall
        import signal

        def _hang(signum, frame):
            print("PROBLEM: chaos smoke hung (watchdog)",
                  file=sys.stderr)
            import os
            os._exit(3)
        signal.signal(signal.SIGALRM, _hang)
        signal.alarm(100)
        try:
            return run_smoke(schedule, seed=args.seed,
                             n_jobs=args.jobs, as_json=args.json)
        finally:
            signal.alarm(0)
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
