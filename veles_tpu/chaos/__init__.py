"""veles_tpu.chaos — deterministic fault injection for the job layer.

See :mod:`veles_tpu.chaos.core` for the fault model and knobs, and
``docs/robustness.md`` for the failure-model table this package
exercises.  ``python -m veles_tpu.chaos --smoke`` runs the CI gate: a
seeded master–slave session with injected slave death + frame faults
that must complete with consistent dedup accounting.
"""

from veles_tpu.chaos.core import (     # noqa: F401 - public API
    PROCESS_ACTIONS, WIRE_ACTIONS, ChaosController, ChaosSchedule,
    Fault, WirePlan, armed, configure, controller)
