"""Deterministic, seeded fault injection for the distributed stack.

The reference platform proves its elasticity with a single knob —
``--slave-death-probability`` (``client.py:303``) — that kills slaves
mid-job so the master's reaper/requeue/blacklist machinery is exercised
for real.  This module generalizes that idea into a *fault model* the
whole job layer is gated against:

* **wire faults** on the ZMQ job plane — ``drop``, ``dup``, ``delay``,
  ``corrupt`` a frame, or ``partition`` (drop every matching frame for
  a duration window);
* **process faults** at the process boundary — ``slave_kill``,
  ``slave_hang``, ``master_stall``, ``master_kill``.

Every injection decision is **deterministic**: probabilistic faults
draw from ONE seeded :class:`random.Random`, and scheduled faults fire
on the *nth matching occurrence* of a (site, op) pair — so a failure
run is replayable from ``(seed, schedule)`` alone, and the schedule is
a plain JSON-serializable list (:meth:`ChaosSchedule.to_json`).

Injection sites (consulted by :mod:`veles_tpu.parallel.jobs`):

==============  ========================================================
site            meaning
==============  ========================================================
``master_recv``  a frame arriving at the :class:`JobServer` ROUTER
``master_send``  a reply leaving the master
``slave_send``   a request leaving a :class:`JobClient`
``slave_recv``   a reply arriving at a :class:`JobClient`
``slave_job``    process-boundary check before each job's compute
``master_tick``  process-boundary check each server-loop iteration
``pod_chip``     pod-runtime check before each sharded dispatch
                 (``chip_kill`` → mesh shrink + reshard,
                 :mod:`veles_tpu.pod`)
==============  ========================================================

Knobs (``root.common.chaos.*``, read at :func:`configure` time —
called by ``Launcher.initialize`` so launcher-driven runs arm from the
config tree; code that builds ``JobServer``/``JobClient`` directly
must call :func:`configure` (or :meth:`ChaosController.arm`) itself,
as the tests and the smoke do):

* ``enabled`` — master switch (default off: every hook is one
  attribute check);
* ``seed`` — the RNG seed (default 1234);
* ``schedule`` — a list of fault dicts (or a path to a JSON file of
  them), see :class:`Fault`;
* ``drop_probability`` / ``dup_probability`` / ``delay_probability``
  + ``delay_ms`` / ``corrupt_probability`` — background probabilistic
  wire faults applied to every data-plane frame (pings excluded so the
  liveness channel itself stays testable via explicit schedule
  entries);
* ``slave_death_probability`` — the reference's knob, applied at
  ``slave_job`` (kept here so ONE switch arms the whole model).

Every injected fault emits a ``chaos`` trace instant (when tracing is
on), so injections land in the merged Perfetto timeline next to the
checkpoint spans and resume markers they provoke.
"""

import json
import random
import threading
import time

from veles_tpu import trace

#: wire actions a schedule entry (or probability knob) may request
WIRE_ACTIONS = ("drop", "dup", "delay", "corrupt", "partition")
#: process-boundary actions (``chip_kill`` fires at the pod runtime's
#: ``pod_chip`` site: one simulated chip drops out of the mesh, the
#: pod reshards onto the survivors and bumps its generation —
#: :meth:`veles_tpu.pod.runtime.PodRuntime.pre_dispatch`;
#: ``replica_drain`` fires at the fleet's ``fleet_decode`` site: one
#: decode replica is drained mid-stream and its live requests replay
#: onto the survivors — :meth:`veles_tpu.fleet.Fleet.tick`)
PROCESS_ACTIONS = ("slave_kill", "slave_hang", "master_stall",
                   "master_kill", "chip_kill", "replica_drain")


class Fault(object):
    """One serializable schedule entry.

    ``site``: an injection site (see module table).  ``action``: one of
    :data:`WIRE_ACTIONS` / :data:`PROCESS_ACTIONS`.  ``op``: restrict
    to frames with this wire op (``None`` = any).  ``nth``: fire on the
    nth *matching* occurrence (1-based); ``every``: fire on every kth
    match instead; ``prob``: fire with this probability per match
    (seeded RNG).  Exactly one of ``nth``/``every``/``prob`` selects.
    ``delay_ms`` (delay), ``duration_s`` (partition/hang/stall) and
    ``count`` (extra dup copies) parameterize the action."""

    FIELDS = ("site", "action", "op", "nth", "every", "prob",
              "delay_ms", "duration_s", "count")

    def __init__(self, site, action, op=None, nth=None, every=None,
                 prob=None, delay_ms=50.0, duration_s=1.0, count=1):
        if action not in WIRE_ACTIONS + PROCESS_ACTIONS:
            raise ValueError("unknown chaos action %r" % (action,))
        if action == "dup" and site == "slave_recv":
            # the slave consumes exactly one decoded reply per rpc —
            # a receive-side dup has no observable effect there, and
            # silently counting it would break injected==observed
            raise ValueError("dup cannot fire at slave_recv "
                             "(dup the reply at master_send instead)")
        selectors = [s for s in (nth, every, prob) if s is not None]
        if len(selectors) != 1:
            raise ValueError(
                "fault %s@%s needs exactly one of nth/every/prob"
                % (action, site))
        self.site = site
        self.action = action
        self.op = op
        self.nth = nth
        self.every = every
        self.prob = prob
        self.delay_ms = float(delay_ms)
        self.duration_s = float(duration_s)
        self.count = int(count)
        #: matches seen so far (the deterministic occurrence counter)
        self.seen = 0
        self.fired = 0

    def matches(self, op):
        return self.op is None or self.op == op

    def should_fire(self, rng):
        """Advance the occurrence counter and decide.  Called once per
        matching frame — the counter IS the determinism."""
        self.seen += 1
        if self.nth is not None:
            return self.seen == self.nth
        if self.every is not None:
            return self.seen % self.every == 0
        return rng.random() < self.prob

    def to_dict(self):
        return {k: getattr(self, k) for k in self.FIELDS
                if getattr(self, k) is not None}

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: v for k, v in dict(d).items()
                      if k in cls.FIELDS})

    def __repr__(self):
        sel = ("nth=%d" % self.nth if self.nth is not None else
               "every=%d" % self.every if self.every is not None else
               "prob=%g" % self.prob)
        return "<Fault %s@%s op=%s %s fired=%d>" % (
            self.action, self.site, self.op, sel, self.fired)


class ChaosSchedule(object):
    """An ordered, JSON-serializable list of :class:`Fault` entries —
    the replayable record of *which* failures a run injects."""

    def __init__(self, faults=()):
        self.faults = [f if isinstance(f, Fault) else Fault.from_dict(f)
                       for f in faults]

    def to_json(self):
        return json.dumps([f.to_dict() for f in self.faults], indent=2)

    @classmethod
    def from_json(cls, text):
        return cls(json.loads(text))

    @classmethod
    def load(cls, path):
        with open(path, "r") as fin:
            return cls.from_json(fin.read())

    def __iter__(self):
        return iter(self.faults)

    def __len__(self):
        return len(self.faults)


class WirePlan(object):
    """The injection decision for ONE frame: ``deliveries`` copies
    (0 = dropped, 2+ = duplicated), an optional pre-delivery ``delay``
    in seconds, and ``corrupt`` (mangle the frame bytes)."""

    __slots__ = ("deliveries", "delay_s", "corrupt")

    def __init__(self, deliveries=1, delay_s=0.0, corrupt=False):
        self.deliveries = deliveries
        self.delay_s = delay_s
        self.corrupt = corrupt


#: shared no-fault plan — the common case allocates nothing
_CLEAN = WirePlan()


class ChaosController(object):
    """Process-wide injection switchboard (``veles_tpu.chaos.controller``).

    Disabled (the default) every hook is a single attribute check on
    :attr:`armed`.  Armed, each hook consults the schedule + the
    probability knobs under one lock (the job wire is low-rate control
    traffic; contention is irrelevant next to a network frame)."""

    def __init__(self):
        self.armed = False
        self._lock = threading.Lock()
        self._rng = random.Random(1234)
        self.schedule = ChaosSchedule()
        self._prob = {}
        #: active partition windows: (site, op-or-None) -> end time
        self._partitions = {}
        #: per-action injected counts (the smoke's consistency record)
        self.injected = {}
        #: (site, op) -> frames OBSERVED while armed, injected or not —
        #: the wire-traffic probe the pod wire gate reads: arm an empty
        #: schedule and these counters prove steady-state pod training
        #: moves ZERO per-step gradient/update frames (control traffic
        #: is O(heartbeats + epochs), not O(minibatches))
        self.wire_frames = {}
        self.seed = 1234

    # -- configuration ------------------------------------------------------
    def configure(self, value=None):
        """(Re)read ``root.common.chaos.*``.  ``value`` overrides the
        ``enabled`` knob (used by tests/CLI).  Counters reset — a
        configure() is the start of a new replayable run."""
        from veles_tpu.config import root
        node = root.common.get("chaos")
        cfg = node.to_dict() if node is not None and node else {}
        with self._lock:
            self.armed = bool(cfg.get("enabled", False)
                              if value is None else value)
            self.seed = int(cfg.get("seed", 1234))
            self._rng = random.Random(self.seed)
            sched = cfg.get("schedule") or []
            if isinstance(sched, str):
                self.schedule = ChaosSchedule.load(sched)
            else:
                self.schedule = ChaosSchedule(sched)
            self._prob = {
                "drop": float(cfg.get("drop_probability", 0.0)),
                "dup": float(cfg.get("dup_probability", 0.0)),
                "delay": float(cfg.get("delay_probability", 0.0)),
                "corrupt": float(cfg.get("corrupt_probability", 0.0)),
                "slave_kill": float(
                    cfg.get("slave_death_probability", 0.0)),
            }
            self._delay_ms = float(cfg.get("delay_ms", 50.0))
            self._partitions = {}
            self.injected = {}
            self.wire_frames = {}
        return self

    def arm(self, schedule=None, seed=None):
        """Programmatic arming (tests, the smoke): install ``schedule``
        (a :class:`ChaosSchedule`, list of dicts, or JSON text) and
        reset counters without touching the config tree."""
        with self._lock:
            self.armed = True
            if seed is not None:
                self.seed = int(seed)
            self._rng = random.Random(self.seed)
            if schedule is not None:
                if isinstance(schedule, str):
                    schedule = ChaosSchedule.from_json(schedule)
                elif not isinstance(schedule, ChaosSchedule):
                    schedule = ChaosSchedule(schedule)
                self.schedule = schedule
            self._prob = {}
            self._partitions = {}
            self.injected = {}
            self.wire_frames = {}
        return self

    def disarm(self):
        with self._lock:
            self.armed = False
            self.schedule = ChaosSchedule()
            self._prob = {}
            self._partitions = {}

    # -- accounting ---------------------------------------------------------
    def _record(self, action, site, op, role=None, **extra):
        self.injected[action] = self.injected.get(action, 0) + 1
        if trace.enabled():
            args = {"site": site}
            if op:
                args["op"] = op
            args.update(extra)
            trace.instant("chaos", action, args, role=role)
        from veles_tpu import watch
        if watch.enabled():
            # "role" on a bus event is the PUBLISHING process's role
            # (stamped by the bus); the fault's target rides as
            # target_role so a master-injected slave_kill is not
            # misattributed to the master
            watch.publish("chaos", dict(extra, action=action,
                                        site=site, op=op,
                                        target_role=role))

    @property
    def faults_injected(self):
        """Total injections so far (the bench column's source)."""
        return sum(self.injected.values())

    def record_external(self, action, site, role=None):
        """Count a fault injected by machinery outside the controller's
        own hooks (the legacy ``JobClient(death_probability=)`` ctor
        knob) so :attr:`faults_injected` stays the ONE complete ledger
        — a bench line must never read 0 while kills fired inside its
        timed region."""
        with self._lock:
            self._record(action, site, None, role=role)

    def frames(self, site=None, op=None):
        """Frames observed at the wire hooks while armed, filtered by
        site and/or op — the traffic probe (counts traffic, injected
        or clean; 0 when never armed)."""
        with self._lock:
            items = list(self.wire_frames.items())
        total = 0
        for (s, o), n in items:
            if site is not None and s != site:
                continue
            if op is not None and o != op:
                continue
            total += n
        return total

    def snapshot(self):
        with self._lock:
            return {"seed": self.seed,
                    "injected": dict(self.injected),
                    "faults_injected": self.faults_injected,
                    "wire_frames": {"%s:%s" % k: n
                                    for k, n in
                                    self.wire_frames.items()},
                    "schedule": [f.to_dict() for f in self.schedule]}

    # -- wire hook ----------------------------------------------------------
    def wire(self, site, op, peer=None, role=None):
        """Decide the fate of one frame at ``site``.  Returns a
        :class:`WirePlan`; the shared clean plan when nothing fires."""
        if not self.armed:
            return _CLEAN
        with self._lock:
            key = (site, op)
            self.wire_frames[key] = self.wire_frames.get(key, 0) + 1
            now = time.monotonic()
            # live partition window: every matching frame drops
            for (psite, pop), end in list(self._partitions.items()):
                if now >= end:
                    del self._partitions[(psite, pop)]
                    continue
                if psite == site and (pop is None or pop == op):
                    self._record("partition_drop", site, op, role=role)
                    return WirePlan(deliveries=0)
            plan = None
            for fault in self.schedule:
                if fault.site != site or not fault.matches(op) \
                        or fault.action not in WIRE_ACTIONS:
                    continue
                if not fault.should_fire(self._rng):
                    continue
                fault.fired += 1
                if fault.action == "partition":
                    self._partitions[(site, fault.op)] = \
                        now + fault.duration_s
                    self._record("partition", site, op, role=role,
                                 duration_s=fault.duration_s)
                    return WirePlan(deliveries=0)
                plan = plan or WirePlan()
                self._apply_wire_action(plan, fault.action,
                                        fault.delay_ms, fault.count,
                                        site, op, role)
            # background probabilistic faults (never on pings: the
            # liveness channel is faulted via explicit schedule only)
            if self._prob and op != "ping":
                for action in ("drop", "dup", "delay", "corrupt"):
                    if action == "dup" and site == "slave_recv":
                        continue    # no receive-side observable
                    p = self._prob.get(action, 0.0)
                    if p and self._rng.random() < p:
                        plan = plan or WirePlan()
                        self._apply_wire_action(
                            plan, action, self._delay_ms, 1,
                            site, op, role)
            return plan or _CLEAN

    def _apply_wire_action(self, plan, action, delay_ms, count,
                           site, op, role):
        if action == "drop":
            plan.deliveries = 0
        elif action == "dup":
            plan.deliveries += count
        elif action == "delay":
            plan.delay_s += delay_ms / 1e3
        elif action == "corrupt":
            plan.corrupt = True
        self._record(action, site, op, role=role)

    def send_wire(self, site, op, blob, send, role=None):
        """Decide and APPLY one outgoing frame's fate: delay, corrupt
        the bytes, then deliver 0..N copies via ``send(blob)``.  The
        one implementation of the send-side fault sequence — master
        (``master_send``) and slave (``slave_send``) both delegate
        here so a new :class:`WirePlan` field cannot make their fault
        semantics silently diverge.  Callers check :attr:`armed`
        first (the disabled path must stay one attribute test)."""
        plan = self.wire(site, op, role=role)
        if plan.delay_s:
            time.sleep(plan.delay_s)
        if plan.corrupt:
            blob = self.corrupt_bytes(blob)
        for _ in range(plan.deliveries):
            send(blob)

    # -- process hook -------------------------------------------------------
    def process(self, point, role=None):
        """Process-boundary check: returns the fired :class:`Fault`
        (action in :data:`PROCESS_ACTIONS`) or ``None``."""
        if not self.armed:
            return None
        with self._lock:
            for fault in self.schedule:
                if fault.site != point \
                        or fault.action not in PROCESS_ACTIONS:
                    continue
                if not fault.should_fire(self._rng):
                    continue
                fault.fired += 1
                self._record(fault.action, point, None, role=role,
                             duration_s=fault.duration_s)
                return fault
            p = self._prob.get("slave_kill", 0.0)
            if p and point == "slave_job" and self._rng.random() < p:
                fault = Fault(point, "slave_kill", prob=p)
                fault.fired = 1
                self._record("slave_kill", point, None, role=role)
                return fault
        return None

    @staticmethod
    def corrupt_bytes(blob):
        """Deterministically mangle a frame: flip the low bit of every
        16th byte — enough to break the pickle, stable for replay."""
        mangled = bytearray(blob)
        for i in range(0, len(mangled), 16):
            mangled[i] ^= 1
        return bytes(mangled)


#: the process-wide controller every hook consults
controller = ChaosController()


def configure(value=None):
    return controller.configure(value)


def armed():
    return controller.armed
