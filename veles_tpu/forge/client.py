"""Forge client: fetch/upload/list/delete model packages.

Parity target: reference ``veles/forge/forge_client.py`` — ``fetch``
(``:101``), ``upload`` (``:147``), ``list`` (``:298``), ``delete``
(``:396``) against the hub, with manifest handling and checksum
verification on fetch (the reference checked ``Workflow.checksum``,
``workflow.py:852-866``).
"""

import hashlib
import json
import urllib.error
import urllib.parse
import urllib.request

from veles_tpu.logger import Logger


class ForgeError(RuntimeError):
    pass


class ForgeClient(Logger):
    def __init__(self, endpoint, token=None):
        super(ForgeClient, self).__init__()
        self.endpoint = endpoint.rstrip("/")
        self.token = token

    def _request(self, path, method="GET", data=None):
        url = self.endpoint + path
        req = urllib.request.Request(url, data=data, method=method)
        if self.token:
            req.add_header("X-Veles-Token", self.token)
        if data is not None:
            req.add_header("Content-Type", "application/octet-stream")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode()).get("error")
            except Exception:
                detail = str(e)
            raise ForgeError("%s %s failed: %s" % (method, path, detail))

    # -- verbs (ref forge_client.py:101,147,298,396) ------------------------
    def list(self):
        return json.loads(self._request("/models").decode())

    def upload(self, name, package_path, version=None):
        with open(package_path, "rb") as fin:
            blob = fin.read()
        path = "/models/%s" % urllib.parse.quote(name, safe="")
        if version:
            path += "?version=%s" % urllib.parse.quote(version)
        meta = json.loads(self._request(path, "POST", blob).decode())
        self.info("uploaded %s %s (%d bytes, sha %s…)", name,
                  meta["version"], meta["size"], meta["checksum"][:12])
        return meta

    def fetch(self, name, dest_path, version=None, verify=True):
        expected = None
        if verify:
            # resolve the manifest FIRST and pin its version for the
            # blob request — otherwise a concurrent upload between the
            # two requests makes the checksum spuriously mismatch
            manifest = self.manifest(name, version)
            expected = manifest.get("checksum")
            version = version or manifest.get("version")
        path = "/models/%s" % urllib.parse.quote(name, safe="")
        if version:
            path += "?version=%s" % urllib.parse.quote(version)
        blob = self._request(path)
        if expected:
            actual = hashlib.sha256(blob).hexdigest()
            if actual != expected:
                raise ForgeError(
                    "checksum mismatch for %s: %s != %s"
                    % (name, actual[:12], expected[:12]))
        with open(dest_path, "wb") as fout:
            fout.write(blob)
        return dest_path

    def manifest(self, name, version=None):
        path = "/models/%s/manifest" % urllib.parse.quote(name, safe="")
        if version:
            path += "?version=%s" % urllib.parse.quote(version)
        return json.loads(self._request(path).decode())

    def delete(self, name):
        self._request("/models/%s" % urllib.parse.quote(name, safe=""),
                      "DELETE")
        self.info("deleted %s", name)
