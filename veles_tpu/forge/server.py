"""Forge server: a model-hub HTTP service with versioned storage.

Parity target: reference ``veles/forge/forge_server.py:462`` — Tornado
server with git-backed package storage, per-user tokens and manifest
handling.  TPU re-design: stdlib ``ThreadingHTTPServer`` (zero extra
deps), content-addressed versioned directory storage (the git history
role), token auth via a JSON file or an in-memory dict.

REST surface (mirrors forge_client verbs fetch/upload/list/delete,
``forge_client.py:101,147,298,396``):
  GET    /models                     → JSON listing
  GET    /models/<name>             → latest package bytes
  GET    /models/<name>?version=V   → that version
  GET    /models/<name>/manifest    → JSON manifest
  POST   /models/<name>?version=V   → upload (X-Veles-Token required)
  DELETE /models/<name>             → delete model (token required)
"""

import hashlib
import hmac
import io
import json
import os
import threading
import urllib.parse
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.logger import Logger


def _manifest_from_package(blob):
    """Extracts contents.json from a .zip package blob (manifest role)."""
    try:
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            return json.loads(z.read("contents.json").decode())
    except Exception:
        return {}


class ForgeStore(object):
    """Versioned model storage: ``<dir>/<name>/<version>.pkg`` +
    ``manifest.json`` per version (content-addressed by sha256)."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    @staticmethod
    def check_name(name):
        """Reject path-traversal / unusable model names."""
        if not name or name in (".", "..") or "/" in name or \
                "\\" in name or "\x00" in name:
            raise ValueError("invalid model name %r" % name)
        return name

    def _model_dir(self, name):
        self.check_name(name)
        safe = urllib.parse.quote(name, safe="")
        if safe in (".", ".."):
            raise ValueError("invalid model name %r" % name)
        return os.path.join(self.directory, safe)

    @staticmethod
    def _version_key(version):
        """Natural sort: v10 > v9 (digits compared numerically)."""
        import re
        return [int(tok) if tok.isdigit() else tok
                for tok in re.split(r"(\d+)", version)]

    def put(self, name, blob, version=None, uploader=None):
        with self._lock:
            mdir = self._model_dir(name)
            os.makedirs(mdir, exist_ok=True)
            checksum = hashlib.sha256(blob).hexdigest()
            if version is None:
                # next free number, collision-proof against explicit
                # "vN" uploads (len()+1 could overwrite)
                taken = {v for v in self.versions(name)}
                n = len(taken) + 1
                while "v%d" % n in taken:
                    n += 1
                version = "v%d" % n
            self.check_version(version)
            with open(os.path.join(mdir, version + ".pkg"), "wb") as f:
                f.write(blob)
            manifest = _manifest_from_package(blob)
            meta = {"name": name, "version": version,
                    "checksum": checksum, "size": len(blob),
                    "uploader": uploader, "manifest": manifest}
            with open(os.path.join(mdir, version + ".json"), "w") as f:
                json.dump(meta, f, indent=1)
            return meta

    def versions(self, name):
        mdir = self._model_dir(name)
        if not os.path.isdir(mdir):
            return []
        return sorted(
            (fname[:-4] for fname in os.listdir(mdir)
             if fname.endswith(".pkg")), key=self._version_key)

    @staticmethod
    def check_version(version):
        if version is not None and (
                "/" in version or "\\" in version or
                version in (".", "..") or "\x00" in version):
            raise ValueError("invalid version %r" % version)
        return version

    def get(self, name, version=None):
        self.check_version(version)
        versions = self.versions(name)
        if not versions:
            return None, None
        version = version or versions[-1]
        mdir = self._model_dir(name)
        try:
            with open(os.path.join(mdir, version + ".pkg"), "rb") as f:
                blob = f.read()
            return blob, self.meta(name, version)
        except OSError:
            return None, None

    def meta(self, name, version=None):
        """The small .json sidecar only (no package read)."""
        self.check_version(version)
        versions = self.versions(name)
        if not versions:
            return None
        version = version or versions[-1]
        try:
            with open(os.path.join(self._model_dir(name),
                                   version + ".json"), "r") as f:
                return json.load(f)
        except OSError:
            return None

    def delete(self, name):
        with self._lock:
            mdir = self._model_dir(name)
            if not os.path.isdir(mdir):
                return False
            for fname in os.listdir(mdir):
                os.unlink(os.path.join(mdir, fname))
            os.rmdir(mdir)
            return True

    def listing(self):
        out = []
        for safe in sorted(os.listdir(self.directory)):
            name = urllib.parse.unquote(safe)
            # read each sidecar ONCE, dropping versions whose .json is
            # missing (e.g. a crash between the non-atomic .pkg/.json
            # writes) — one broken version must not take the listing
            # down, and a concurrent delete must not either
            metas = []
            for v in self.versions(name):
                meta = self.meta(name, v)
                if meta is not None:
                    metas.append((v, meta))
            if not metas:
                continue
            versions = [v for v, _ in metas]
            latest = metas[-1][1]
            out.append({"name": name, "versions": versions,
                        "latest": versions[-1],
                        "checksum": latest.get("checksum"),
                        "size": latest.get("size")})
        return out


class ForgeServer(Logger):
    """The hub service; ``tokens`` maps token → user name (uploads and
    deletions require one; reads are public, like the reference)."""

    def __init__(self, directory, tokens=None, host="127.0.0.1", port=0,
                 max_upload_bytes=512 * 1024 * 1024):
        super(ForgeServer, self).__init__()
        self.store = ForgeStore(directory)
        self.tokens = dict(tokens or {})
        self.max_upload_bytes = int(max_upload_bytes)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                server.debug("http: " + fmt, *args)

            def _reply(self, code, payload, ctype="application/json"):
                body = payload if isinstance(payload, bytes) else \
                    json.dumps(payload, indent=1).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _auth(self):
                token = self.headers.get("X-Veles-Token", "")
                # constant-time scan over all tokens — a dict lookup's
                # timing leaks prefix-match length to a remote prober.
                # Compare sha256 digests: fixed length, bytes-safe for
                # non-ASCII header values (compare_digest would raise).
                probe = hashlib.sha256(
                    token.encode("latin-1", "replace")).digest()
                user = None
                for candidate, candidate_user in server.tokens.items():
                    expected = hashlib.sha256(candidate.encode()).digest()
                    if hmac.compare_digest(expected, probe):
                        user = candidate_user
                if user is None:
                    self._reply(401, {"error": "bad token"})
                return user

            def _parse(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                query = dict(urllib.parse.parse_qsl(parsed.query))
                return parts, query

            def _do_safely(self, fn):
                try:
                    fn()
                except ValueError as e:   # bad model/version names
                    self._reply(400, {"error": str(e)})

            def do_GET(self):
                self._do_safely(self._get)

            def do_POST(self):
                self._do_safely(self._post)

            def do_DELETE(self):
                self._do_safely(self._delete)

            def _get(self):
                parts, query = self._parse()
                if not parts or parts == ["ui"]:
                    # browser UI (ref ships a JS site under web/
                    # projects/forge): one self-contained page over
                    # the JSON endpoints below
                    from veles_tpu.web_status import _ui_asset
                    self._reply(200, _ui_asset("forge.html"),
                                "text/html; charset=utf-8")
                    return
                if parts == ["models"]:
                    self._reply(200, server.store.listing())
                    return
                if len(parts) >= 2 and parts[0] == "models":
                    name = urllib.parse.unquote(parts[1])
                    if len(parts) == 3 and parts[2] == "manifest":
                        meta = server.store.meta(
                            name, query.get("version"))
                        if meta is None:
                            self._reply(404, {"error": "no such model"})
                        else:
                            self._reply(200, meta)
                        return
                    blob, _meta = server.store.get(
                        name, query.get("version"))
                    if blob is None:
                        self._reply(404, {"error": "no such model"})
                        return
                    self._reply(200, blob, "application/octet-stream")
                    return
                self._reply(404, {"error": "bad path"})

            def _post(self):
                parts, query = self._parse()
                user = self._auth()
                if user is None:
                    return
                if len(parts) == 2 and parts[0] == "models":
                    name = urllib.parse.unquote(parts[1])
                    length = int(self.headers.get("Content-Length", 0))
                    if length > server.max_upload_bytes:
                        # drain a bounded slice so the client reads the
                        # 413 instead of a connection reset, then close
                        self.close_connection = True
                        drained = 0
                        while drained < min(length, 1 << 20):
                            chunk = self.rfile.read(
                                min(65536, length - drained))
                            if not chunk:
                                break
                            drained += len(chunk)
                        self._reply(413, {"error": "upload exceeds %d "
                                          "bytes" % server.max_upload_bytes})
                        return
                    blob = self.rfile.read(length)
                    meta = server.store.put(
                        name, blob, version=query.get("version"),
                        uploader=user)
                    self._reply(200, meta)
                    return
                self._reply(404, {"error": "bad path"})

            def _delete(self):
                parts, _ = self._parse()
                user = self._auth()
                if user is None:
                    return
                if len(parts) == 2 and parts[0] == "models":
                    name = urllib.parse.unquote(parts[1])
                    if server.store.delete(name):
                        self._reply(200, {"deleted": name})
                    else:
                        self._reply(404, {"error": "no such model"})
                    return
                self._reply(404, {"error": "bad path"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.endpoint = "http://%s:%d" % (host, self.port)
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="forge-server")
        self._thread.start()
        self.info("forge server on %s (store: %s)", self.endpoint,
                  self.store.directory)
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5)
