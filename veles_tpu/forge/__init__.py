"""Forge: the model hub (SURVEY §2.5).

Reference: ``veles/forge/`` — client verbs ``forge_client.py:101-396``,
server ``forge_server.py:462`` (git-backed storage, tokens).
"""

from veles_tpu.forge.client import ForgeClient, ForgeError  # noqa: F401
from veles_tpu.forge.server import ForgeServer, ForgeStore  # noqa: F401
