"""GraphicsServer: broadcast pickled plotter units to detached viewers.

Parity target: reference ``veles/graphics_server.py:65-140`` — ``Plotter``
units pickle themselves onto a ZeroMQ PUB socket; one or more separate
``GraphicsClient`` processes subscribe and render with matplotlib.  The
reference additionally binds an ``epgm://`` multicast endpoint
(``graphics_server.py:100-110``) so a whole lab can watch one training
run; :class:`GraphicsServer` accepts the same via ``multicast=``:
``udp://GROUP:PORT`` uses the stdlib chunked-datagram transport
(:mod:`veles_tpu.multicast` — always available), while ZeroMQ schemes
(``epgm://interface;group:port`` / ``pgm://``) are passed to libzmq and
degrade gracefully when it lacks OpenPGM — the tcp endpoint always
works and viewers attach/detach at will without ever blocking training.
"""

import pickle
import threading

from veles_tpu.logger import Logger

_instance_lock = threading.Lock()
_instance = None


class GraphicsServer(Logger):
    """Singleton PUB endpoint (one per process, like the reference)."""

    def __init__(self, port=0, multicast=None):
        super(GraphicsServer, self).__init__()
        import zmq
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.PUB)
        if port:
            self._socket.bind("tcp://127.0.0.1:%d" % port)
            self.port = port
        else:
            self.port = self._socket.bind_to_random_port("tcp://127.0.0.1")
        self.endpoint = "tcp://127.0.0.1:%d" % self.port
        self.endpoints = [self.endpoint]
        if multicast is None:
            from veles_tpu.config import root
            multicast = root.common.graphics.get("multicast", None)
        self._mcast = None
        if multicast:
            # the reference's lab-wide broadcast (epgm multicast,
            # graphics_server.py:100-110).  udp://GROUP:PORT uses the
            # stdlib chunked-datagram transport (multicast.py — always
            # available); any other scheme is handed to libzmq (epgm
            # works iff built with OpenPGM).  Either way a bad group
            # spec must never take training down.
            try:
                if multicast.startswith("udp://"):
                    from veles_tpu.multicast import McastSender
                    self._mcast = McastSender(multicast)
                else:
                    self._socket.bind(multicast)
                self.endpoints.append(multicast)
                self.info("plot multicast on %s", multicast)
            except Exception as exc:
                self.warning(
                    "multicast endpoint %s unavailable (%s) — "
                    "continuing tcp-only", multicast, exc)
        import threading
        self._send_lock = threading.Lock()
        self.info("graphics server on %s", self.endpoint)

    @staticmethod
    def launch(port=0, multicast=None):
        global _instance
        with _instance_lock:
            if _instance is None:
                _instance = GraphicsServer(port, multicast=multicast)
            return _instance

    @staticmethod
    def instance():
        return _instance

    def serialize(self, plotter):
        """Pickle one plotter snapshot (caller's thread — must be the
        scheduler thread so the capture is consistent); None on error."""
        from veles_tpu.plotting_units import Plotter
        Plotter._plot_message_mode = True
        try:
            return pickle.dumps(plotter, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            self.exception("failed to pickle %r for plotting", plotter)
            return None
        finally:
            Plotter._plot_message_mode = False

    def send(self, blob):
        """Publish a serialized snapshot (thread-safe: zmq sockets must
        not be shared across threads without a guard)."""
        with self._send_lock:
            self._socket.send(blob)
            if self._mcast is not None:
                # best-effort contract: a transient error (ENOBUFS
                # under a datagram burst, an interface flap) drops ONE
                # frame; only a persistent failure streak disables the
                # transport for the run
                try:
                    self._mcast.send(blob)
                    self._mcast_failures = 0
                except OSError as exc:
                    self._mcast_failures = getattr(
                        self, "_mcast_failures", 0) + 1
                    if self._mcast_failures >= 25:
                        self.warning(
                            "multicast send failed %d times in a row "
                            "(%s) — disabling multicast",
                            self._mcast_failures, exc)
                        self._mcast.close()
                        self._mcast = None
                    elif self._mcast_failures == 1:
                        self.warning("multicast send failed (%s) — "
                                     "frame dropped", exc)

    def enqueue(self, plotter):
        """Serialize + publish synchronously (viewer re-runs
        ``redraw()``, like the reference)."""
        blob = self.serialize(plotter)
        if blob is not None:
            self.send(blob)

    def shutdown(self):
        global _instance
        with _instance_lock:
            self._socket.close(linger=0)
            if self._mcast is not None:
                self._mcast.close()
                self._mcast = None
            if _instance is self:
                _instance = None
