"""GraphicsServer: broadcast pickled plotter units to detached viewers.

Parity target: reference ``veles/graphics_server.py:65-140`` — ``Plotter``
units pickle themselves onto a ZeroMQ PUB socket; one or more separate
``GraphicsClient`` processes subscribe and render with matplotlib.  The
reference additionally binds an ``epgm://`` multicast endpoint
(``graphics_server.py:100-110``) so a whole lab can watch one training
run; :class:`GraphicsServer` accepts the same via ``multicast=`` (ZeroMQ
``epgm://interface;group:port`` / ``pgm://``), degrading gracefully when
libzmq lacks OpenPGM — the tcp endpoint always works and viewers
attach/detach at will without ever blocking training.
"""

import pickle
import threading

from veles_tpu.logger import Logger

_instance_lock = threading.Lock()
_instance = None


class GraphicsServer(Logger):
    """Singleton PUB endpoint (one per process, like the reference)."""

    def __init__(self, port=0, multicast=None):
        super(GraphicsServer, self).__init__()
        import zmq
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.PUB)
        if port:
            self._socket.bind("tcp://127.0.0.1:%d" % port)
            self.port = port
        else:
            self.port = self._socket.bind_to_random_port("tcp://127.0.0.1")
        self.endpoint = "tcp://127.0.0.1:%d" % self.port
        self.endpoints = [self.endpoint]
        if multicast is None:
            from veles_tpu.config import root
            multicast = root.common.graphics.get("multicast", None)
        if multicast:
            # the reference's lab-wide broadcast (epgm multicast);
            # PUB sockets bind any number of transports, so this rides
            # alongside tcp — and a libzmq built without OpenPGM (or a
            # bad group spec) must never take training down
            try:
                self._socket.bind(multicast)
                self.endpoints.append(multicast)
                self.info("plot multicast on %s", multicast)
            except Exception as exc:
                self.warning(
                    "multicast endpoint %s unavailable (%s) — "
                    "continuing tcp-only", multicast, exc)
        import threading
        self._send_lock = threading.Lock()
        self.info("graphics server on %s", self.endpoint)

    @staticmethod
    def launch(port=0, multicast=None):
        global _instance
        with _instance_lock:
            if _instance is None:
                _instance = GraphicsServer(port, multicast=multicast)
            return _instance

    @staticmethod
    def instance():
        return _instance

    def serialize(self, plotter):
        """Pickle one plotter snapshot (caller's thread — must be the
        scheduler thread so the capture is consistent); None on error."""
        from veles_tpu.plotting_units import Plotter
        Plotter._plot_message_mode = True
        try:
            return pickle.dumps(plotter, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            self.exception("failed to pickle %r for plotting", plotter)
            return None
        finally:
            Plotter._plot_message_mode = False

    def send(self, blob):
        """Publish a serialized snapshot (thread-safe: zmq sockets must
        not be shared across threads without a guard)."""
        with self._send_lock:
            self._socket.send(blob)

    def enqueue(self, plotter):
        """Serialize + publish synchronously (viewer re-runs
        ``redraw()``, like the reference)."""
        blob = self.serialize(plotter)
        if blob is not None:
            self.send(blob)

    def shutdown(self):
        global _instance
        with _instance_lock:
            self._socket.close(linger=0)
            if _instance is self:
                _instance = None
