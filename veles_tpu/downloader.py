"""Downloader: dataset fetch + unpack unit.

Parity target: reference ``veles/downloader.py:56`` — wget-based fetch
of a dataset archive into ``root.common.dirs.datasets`` with unpacking;
here urllib + tarfile/zipfile (no wget dependency), gated on the URL
being reachable — this image has zero egress, so tests exercise
``file://`` URLs and local archives.
"""

import os
import shutil
import tarfile
import urllib.request
import zipfile

from veles_tpu.config import root
from veles_tpu.units import Unit


class Downloader(Unit):
    """Fetches ``url`` into ``directory`` (default
    ``root.common.dirs.datasets``) and unpacks archives; no-ops when
    ``files`` already exist (ref ``:56`` semantics)."""

    def __init__(self, workflow, **kwargs):
        super(Downloader, self).__init__(workflow, **kwargs)
        self.url = kwargs.get("url")
        self.directory = kwargs.get(
            "directory", root.common.dirs.datasets
            if isinstance(root.common.dirs.datasets, str) else ".")
        #: files whose presence means the dataset is already there
        self.files = list(kwargs.get("files", ()))
        self.demand("url")

    @property
    def already_there(self):
        return self.files and all(
            os.path.exists(os.path.join(self.directory, f))
            for f in self.files)

    def initialize(self, **kwargs):
        super(Downloader, self).initialize(**kwargs)
        if self.already_there:
            self.debug("dataset already present in %s", self.directory)
            return
        os.makedirs(self.directory, exist_ok=True)
        name = os.path.basename(self.url.rstrip("/")) or "download"
        target = os.path.join(self.directory, name)
        self.info("fetching %s -> %s", self.url, target)
        with urllib.request.urlopen(self.url) as response, \
                open(target, "wb") as fout:
            shutil.copyfileobj(response, fout)
        self.unpack(target)

    def unpack(self, path):
        if tarfile.is_tarfile(path):
            with tarfile.open(path) as tar:
                tar.extractall(self.directory, filter="data")
            self.info("unpacked tar %s", path)
        elif zipfile.is_zipfile(path):
            with zipfile.ZipFile(path) as zf:
                zf.extractall(self.directory)
            self.info("unpacked zip %s", path)

    def run(self):
        pass
