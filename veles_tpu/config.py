"""Auto-vivifying configuration tree (the ``root.*`` namespace).

Capability parity with the reference's config system
(``veles/config.py:60-147`` — auto-vivifying dotted namespace; defaults at
``:178-290``; ``site_config.py`` override chain ``:294-307``; protected keys
``:79-85``), re-designed for the TPU build:

* the tree is a plain nested-attribute namespace, printable and
  pickle/JSON-able, so whole-run configuration snapshots ride along with
  checkpoints;
* genetic search-range markers (``Tuneable``/``Range`` — see
  :mod:`veles_tpu.genetics.config`) may be embedded as *values* anywhere in
  the tree, exactly like the reference embeds them
  (``veles/genetics/config.py:45-110``);
* TPU-relevant defaults live under ``root.common.engine`` (backend name,
  precision policy incl. bfloat16, mesh axes) instead of the reference's
  OpenCL/CUDA block-size knobs.
"""

import json
import os


class Config(object):
    """A node in the auto-vivifying config tree.

    Attribute access on a missing key creates a child ``Config`` node, so
    ``root.a.b.c = 1`` works with no prior declarations (reference
    ``veles/config.py:101``).
    """

    __slots__ = ("__dict__", "__path__")

    def __init__(self, path="root"):
        object.__setattr__(self, "__path__", path)

    # -- vivification ------------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self.path, name))
        self.__dict__[name] = child
        return child

    def __setattr__(self, name, value):
        if (id(self), name) in _PROTECTED:
            raise AttributeError(
                "config key %s.%s is protected" % (self.path, name))
        self.__dict__[name] = value

    # -- niceties ----------------------------------------------------------
    @property
    def path(self):
        return object.__getattribute__(self, "__path__")

    def __contains__(self, name):
        return name in self.__dict__

    def __iter__(self):
        return iter(sorted(self.__dict__.items()))

    def __bool__(self):
        return bool(self.__dict__)

    def __repr__(self):
        return "<Config %s: %d keys>" % (self.path, len(self.__dict__))

    def get(self, name, default=None):
        """Non-vivifying lookup."""
        return self.__dict__.get(name, default)

    def update(self, tree):
        """Deep-merge a nested dict (or another Config) into this node.

        Mirrors the reference's ``Config.update`` used by every
        ``<name>_config.py`` (``veles/config.py:118-140``).
        """
        if isinstance(tree, Config):
            tree = tree.to_dict()
        if not isinstance(tree, dict):
            raise TypeError("Config.update expects a dict, got %r" % tree)
        for key, value in tree.items():
            if isinstance(value, dict):
                node = self.__dict__.get(key)
                if not isinstance(node, Config):
                    node = Config("%s.%s" % (self.path, key))
                    self.__dict__[key] = node
                node.update(value)
            else:
                setattr(self, key, value)
        return self

    def to_dict(self):
        out = {}
        for key, value in self.__dict__.items():
            out[key] = value.to_dict() if isinstance(value, Config) else value
        return out

    def protect(self, *names):
        """Forbid reassignment of direct children (ref ``config.py:79-85``)."""
        for name in names:
            _PROTECTED.add((id(self), name))

    def print_(self, indent=0, file=None):
        import sys
        file = file or sys.stdout
        for key, value in sorted(self.__dict__.items()):
            if isinstance(value, Config):
                print("%s%s:" % ("  " * indent, key), file=file)
                value.print_(indent + 1, file)
            else:
                print("%s%s: %r" % ("  " * indent, key, value), file=file)


_PROTECTED = set()

#: The global configuration tree — the singular ``root`` every module imports.
root = Config("root")


def _default_dirs():
    base = os.environ.get("VELES_TPU_HOME",
                          os.path.join(os.path.expanduser("~"), ".veles_tpu"))
    return {
        "base": base,
        "datasets": os.path.join(base, "datasets"),
        "snapshots": os.path.join(base, "snapshots"),
        "cache": os.path.join(base, "cache"),
        "results": os.path.join(base, "results"),
    }


# Platform defaults (reference analogue: veles/config.py:178-290).
root.common.update({
    "dirs": _default_dirs(),
    "engine": {
        # "tpu" | "cpu" | "numpy"; AutoDevice resolves by PRIORITY.
        "backend": "auto",
        # Compute dtype for operands: "float32" or "bfloat16" (MXU-native).
        "precision_type": "float32",
        # Numerical-robustness knob, same direction as the reference's
        # precision levels (0 fast, 1 Kahan, 2 multipartial —
        # veles/config.py:246-249).  On TPU it selects MXU pass counts
        # for float32 matmuls: 0 → DEFAULT (bf16 passes), 1 → HIGH
        # (bf16_3x), 2 → HIGHEST (full f32).
        "precision_level": 0,
        "mesh": {
            # Logical mesh axes for pjit sharding; data-parallel by default.
            "axes": {"data": -1},   # -1 = all devices
        },
        # Eager unit-chain fast path: stitch maximal runs of pure jitted
        # units into ONE XLA program each at Workflow.initialize()
        # ("on" | "off"; honored by Workflow.run() and the job-layer
        # slave path — "off" restores the per-unit dispatch path).
        "stitch": "on",
        # Input pipeline for the eager/stitched trainer
        # ("auto" | "device" | "host"):  "device" (and "auto" when a
        # jit device is attached and the dataset is HBM-resident)
        # heads the first stitched segment with the loader — minibatch
        # selection becomes an in-program gather over the resident
        # dataset, with ZERO per-step host fill / host→device bytes.
        # "host" restores the seed per-step host fill.  Read at
        # Workflow.initialize()/rebuild_stitching() time.
        "loader": "auto",
        # Deferred-metric fetch cadence for the device-resident
        # evaluators: 0 = one batched fetch per epoch/class boundary;
        # K > 0 additionally flushes every K minibatches (bounds the
        # async dispatch queue on very long epochs).
        "metrics_every": 0,
        # Unified tracing (veles_tpu.trace): "off" (default — every
        # hook is a single attribute check), "on" (record spans into
        # the in-memory ring), or a *.json path (record AND write a
        # Perfetto-loadable Chrome trace-event file at process exit).
        # Read fresh at Workflow.initialize() via trace.configure().
        "trace": "off",
        # Trace ring capacity in events; wraparound keeps the newest.
        "trace_capacity": 65536,
        # In-program training-health telemetry (veles_tpu.watch):
        # "off" (default — stitched programs byte-identical to an
        # unwatched build), "on" (per-param-group grad/weight/update
        # norms + non-finite counts ride the deferred-metrics fetch as
        # device scalars, zero extra dispatches), "strict" (non-finite
        # params raise watch.health.HealthError naming the first bad
        # leaf at the window boundary).  Read at
        # Workflow.initialize()/rebuild_stitching() time.
        "health": "off",
        "interpret": False,         # run Pallas kernels in interpret mode
        # Master crash-recovery (veles_tpu.parallel.jobs.JobServer):
        # "dir" non-empty → the master checkpoints the workflow's
        # train state there (async TrainCheckpointer) every
        # "every_jobs" applied updates AND at every epoch boundary;
        # a restarted master resumes with `--resume` (launcher) /
        # JobServer.resume_from_checkpoint().
        "checkpoint": {"dir": "", "every_jobs": 0},
    },
    # Deterministic fault injection (veles_tpu.chaos; read at
    # chaos.configure() — the launcher calls it at initialize).  See
    # docs/robustness.md for the fault model; "schedule" is a list of
    # fault dicts (or a path to a JSON file of them), every run is
    # replayable from (seed, schedule).
    "chaos": {
        "enabled": False,
        "seed": 1234,
        "schedule": [],
        "drop_probability": 0.0,
        "dup_probability": 0.0,
        "delay_probability": 0.0,
        "delay_ms": 50.0,
        "corrupt_probability": 0.0,
        # the reference's --slave-death-probability (client.py:303)
        "slave_death_probability": 0.0,
    },
    # Fleet observability (veles_tpu.obs).  "slo" declares windowed
    # objectives per signal name: {"max"|"min": bound, "window_s",
    # "fast_window_s", "target", "burn_threshold"} — evaluated by the
    # serving SLO engine with multi-window burn rates; the three
    # autoscaling signals (queue depth, batch fill, TTFT p99 burn
    # rate) export on /metrics regardless.  "blackbox_dir" non-empty
    # arms the flight recorder: fatal exits (unhandled exception,
    # SIGTERM, chaos kills) dump the live trace ring + ledger summary
    # there as a loadable post-mortem (obs.blackbox.load).
    "obs": {
        "slo": {
            "ttft_p99_ms": {"max": 500.0, "window_s": 60.0,
                            "fast_window_s": 5.0, "target": 0.99,
                            "burn_threshold": 2.0},
        },
        "blackbox_dir": "",
    },
    # The live telemetry bus (veles_tpu.watch.bus): a non-empty
    # "endpoint" (e.g. "tcp://127.0.0.1:9461", or ":0" for a random
    # port) starts the drop-tolerant ZMQ PUB bus at
    # Workflow.initialize(); workflows, Decision epoch closes,
    # PodMaster/PodRuntime and the generative scheduler then publish
    # JSON snapshots onto it.  "hwm" bounds the per-subscriber send
    # queue (overflow drops frames — a slow viewer never backpressures
    # training); "history" sizes the host-side ring the blackbox
    # post-mortems embed; "conflate" opts into ZMQ keep-only-last wire
    # semantics.  Watch live: python -m veles_tpu.watch <endpoint>.
    "watch": {
        "endpoint": "",
        "hwm": 64,
        "history": 256,
        "conflate": False,
    },
    # Serving robustness: a batched `infer` exceeding this deadline
    # fails the batch's futures with serve.batcher.InferDeadlineExceeded
    # (HTTP 500) instead of blocking every queued client forever.
    # 0 = off (the direct, zero-overhead path).
    "serve": {"infer_deadline_ms": 0},
    "thread_pool": {"max_workers": 8},
    "network_compression": "snappy",
    "timings": set(),
    "trace": {"run": False},
    "web": {"host": "localhost", "port": 8090},
    "api": {"port": 8180},
    "forge": {"port": 8188, "service_name": "forge"},
    "warnings": {"numpy_run": True},
})


def apply_site_config():
    """Reference ``site_config.py`` chain (``veles/config.py:294-307``):
    look for ``site_config.py`` next to the package, in ``~/.veles_tpu`` and
    in ``$VELES_TPU_SITE_CONFIG``, exec each against ``root``."""
    candidates = [
        os.path.join(os.path.dirname(__file__), "site_config.py"),
        os.path.join(_default_dirs()["base"], "site_config.py"),
        os.environ.get("VELES_TPU_SITE_CONFIG", ""),
    ]
    for path in candidates:
        if path and os.path.exists(path):
            with open(path, "r") as fin:
                code = compile(fin.read(), path, "exec")
            exec(code, {"root": root})


def update_from_arguments(pairs):
    """Apply ``key=value`` CLI overrides (ref ``__main__.py:474-482``).

    ``key`` is a dotted path below ``root``; ``value`` is parsed as JSON when
    possible, else kept as a string.
    """
    for pair in pairs:
        key, _, raw = pair.partition("=")
        if not _:
            raise ValueError("override %r is not key=value" % pair)
        try:
            value = json.loads(raw)
        except ValueError:
            value = raw
        node = root
        parts = key.split(".")
        if parts[0] == "root":
            parts = parts[1:]
        if not parts:
            raise ValueError(
                "override %r names no key below root" % pair)
        for part in parts[:-1]:
            node = getattr(node, part)
        setattr(node, parts[-1], value)
