"""Workflow: the container unit executing a graph of units.

Parity target: reference ``veles/workflow.py`` —

* ``Workflow`` (``workflow.py:87``): unit container with
  ``start_point``/``end_point``, per-unit ``add_ref`` registration
  (``:402``), initialization in dependency order with partial-init requeue
  (``:303-336``), run/stop lifecycle (``:351-377``), run-time statistics
  (``:767-826``), result gathering (``:827-851``), content checksum
  (``:852-866``), graphviz export (``:628``) and the master–slave job
  protocol (``generate_data_for_slave`` ``:478``,
  ``apply_data_from_slave`` ``:533``, ``do_job`` ``:558``).

TPU re-design: execution is an iterative FIFO work-queue (see
:mod:`veles_tpu.units` module docstring) — single-threaded and
deterministic by default, with an optional background executor for
host-blocking units.  Device work inside unit ``run()`` bodies is
asynchronously dispatched by JAX, so the queue loop overlaps host
scheduling with TPU compute naturally.
"""

import collections
import hashlib
import inspect
import os
import threading
import time

from veles_tpu.plumbing import EndPoint, StartPoint
from veles_tpu.units import Unit


class ChecksumError(Exception):
    """A unit's defining code cannot be content-addressed — master/slave
    code-mismatch detection would be unsound, so checksum() fails closed."""


class NoMoreJobs(Exception):
    """Master has no further jobs for slaves (ref ``workflow.py:498``)."""


class NoJobYet(Exception):
    """Master has nothing to hand out *right now* but more jobs may
    appear (e.g. a GA generation waiting on in-flight evaluations); the
    slave should retry shortly instead of quitting."""


class Workflow(Unit):
    """Container unit holding and executing a unit graph."""

    hide_from_registry = True

    #: seconds _drain waits for in-flight background units before
    #: raising — run() returning means the graph IS quiescent, never a
    #: silent shrug (warnings escalate every 60 s until then)
    QUIESCENCE_TIMEOUT = 600.0

    def __init__(self, workflow=None, **kwargs):
        self._units = []
        self._sync_ = None
        self.result_file = kwargs.get("result_file")
        super(Workflow, self).__init__(workflow, **kwargs)
        self._launcher = None
        if kwargs.get("launcher") is not None:
            self.launcher = kwargs["launcher"]  # setter → add_ref
        self.stopped = False
        self._run_time = 0.0
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self.negotiates_on_connect = True

    def init_unpickled(self):
        super(Workflow, self).init_unpickled()
        self._queue_ = collections.deque()
        self._queue_lock_ = threading.Lock()
        self._queue_cond_ = threading.Condition(self._queue_lock_)
        self._inflight_ = 0
        self._finished_event_ = threading.Event()
        self._job_callback_ = None
        # stitched segments hold jitted programs → transient; rebuilt by
        # initialize() (which re-runs after every unpickle-and-resume)
        self._stitch_segments_ = []
        self._epoch_runner_ = None
        self._stitch_active_ = False
        #: was the switch on when segments were last (re)built?  run()
        #: uses this to honor an off→on flip without re-walking the
        #: graph on every call (slaves run() once per job)
        self._stitch_built_enabled_ = False

    def __setstate__(self, state):
        super(Workflow, self).__setstate__(state)
        # workflow back-references are weakrefs (transient) — re-link.
        for unit in self._units:
            unit.workflow = self

    # -- membership ---------------------------------------------------------
    def add_ref(self, unit):
        """Units self-register on construction (ref ``workflow.py:402``)."""
        if unit is self:
            raise ValueError("a workflow cannot contain itself")
        if unit not in self._units:
            self._units.append(unit)
        unit.workflow = self

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)

    @property
    def units(self):
        return list(self._units)

    def __iter__(self):
        return iter(self._units)

    def __len__(self):
        return len(self._units)

    def __getitem__(self, key):
        if isinstance(key, str):
            for unit in self._units:
                if unit.name == key:
                    return unit
            raise KeyError(key)
        return self._units[key]

    # -- mode flags ---------------------------------------------------------
    @property
    def launcher(self):
        return self._launcher

    @launcher.setter
    def launcher(self, value):
        old = getattr(self, "_launcher", None)
        if old is not None and old is not value:
            del_ref = getattr(old, "del_ref", None)
            if del_ref is not None:
                del_ref(self)
        self._launcher = value
        if value is not None:
            add_ref = getattr(value, "add_ref", None)
            if add_ref is not None:
                add_ref(self)

    @property
    def is_master(self):
        return getattr(self._launcher, "is_master", False)

    @property
    def is_slave(self):
        return getattr(self._launcher, "is_slave", False)

    @property
    def is_standalone(self):
        return getattr(self._launcher, "is_standalone", True)

    # -- initialization ----------------------------------------------------
    def units_in_dependency_order(self):
        """BFS from start_point over control edges; unreachable units are
        appended afterwards in insertion order (ref ``workflow.py:269``)."""
        seen = []
        seen_set = set()
        frontier = collections.deque([self.start_point])
        while frontier:
            unit = frontier.popleft()
            if id(unit) in seen_set:
                continue
            seen_set.add(id(unit))
            seen.append(unit)
            for dst in unit.links_to:
                if id(dst) not in seen_set:
                    frontier.append(dst)
        appended = []
        for unit in self._units:
            if id(unit) not in seen_set:
                seen_set.add(id(unit))
                seen.append(unit)
                appended.append(unit)
        if appended and not getattr(self, "_warned_unreachable_",
                                    False):
            # one-time structured downgrade of the analyzer's V-G02
            # finding: standalone runs see WHICH units silently ride
            # in insertion order (master/slave payload fragility).
            # Same detection helper as the analyzer pass, so the two
            # cannot disagree (an unreachable end_point is appended
            # for ordering but excluded from the finding, both here
            # and there).
            self._warned_unreachable_ = True
            from veles_tpu.analyze.graph import unreachable_units
            flagged = unreachable_units(
                self.start_point, self._units,
                exclude=(self.end_point,))
            if flagged:
                self.warning(
                    "V-G02: %d unit(s) unreachable from start_point, "
                    "appended in insertion order: %s — they "
                    "initialize but never run; `python -m "
                    "veles_tpu.analyze` has the full pre-flight "
                    "report",
                    len(flagged), ", ".join(u.name for u in flagged))
        return seen

    def initialize(self, device=None, **kwargs):
        """Initialize all units in dependency order with partial-init
        requeue (ref ``workflow.py:303-336``): a unit whose demanded
        attributes are not yet produced is retried after its producers.
        Only :class:`~veles_tpu.units.MissingDemandedAttributes` requeues —
        each unit at most once per remaining peer — so genuine
        AttributeError bugs in ``initialize()`` bodies surface immediately."""
        from veles_tpu import trace, watch
        from veles_tpu.obs import blackbox
        from veles_tpu.units import MissingDemandedAttributes
        # honor the root.common.engine.trace knob per initialize (the
        # natural "a run starts here" boundary — off stays a single
        # attribute check in every hook); the flight-recorder knob
        # (root.common.obs.blackbox_dir) and the telemetry-bus knob
        # (root.common.watch.endpoint) arm at the same boundary
        trace.configure()
        blackbox.configure()
        watch.configure()
        self.device = device
        pending = collections.deque(self.units_in_dependency_order())
        retries = {}
        limit = len(pending)
        while pending:
            unit = pending.popleft()
            try:
                if device is not None and _accepts_kwarg(
                        unit.initialize, "device"):
                    unit.initialize(device=device, **kwargs)
                else:
                    unit.initialize(**kwargs)
            except MissingDemandedAttributes:
                retries[id(unit)] = retries.get(id(unit), 0) + 1
                if retries[id(unit)] > limit:
                    raise
                pending.append(unit)
        self._is_initialized = True
        self.stopped = False
        self.rebuild_stitching()
        return self

    # -- segment stitching (the eager fast path, veles_tpu.stitch) ----------
    def rebuild_stitching(self):
        """(Re)walk the unit chain and compile maximal runs of pure
        jitted units into single XLA programs (see
        :mod:`veles_tpu.stitch`).  Called at the end of
        :meth:`initialize` and again after any graph surgery (e.g. the
        slave-mode back-edge removal)."""
        from veles_tpu import stitch, trace
        with trace.span("segment", "rebuild_stitching"):
            for segment in self._stitch_segments_:
                segment.detach()
            self._stitch_segments_ = stitch.build_segments(self)
            self._stitch_built_enabled_ = stitch.enabled()
            # the epoch-scan runner rides the stitched shape: rebuilt
            # with it so its cycle analysis and compiled K-step window
            # programs can never outlive the segments they fold
            if self._stitch_segments_:
                from veles_tpu import epoch_scan
                self._epoch_runner_ = epoch_scan.build_runner(self)
            else:
                self._epoch_runner_ = None
        return self._stitch_segments_

    @property
    def stitch_active(self):
        """True while run() executes with stitched segments live."""
        return self._stitch_active_

    def stitch_report(self):
        """Observability: segment composition + dispatch counts (the
        compile/dispatch-count tests and the job layer's slave log
        read this).  ``loader_headed`` marks segments whose head runs a
        host prelude — i.e. the device-resident input pipeline fused
        the minibatch gather into that program."""
        from veles_tpu import stitch
        runner = self._epoch_runner_
        return {
            "enabled": stitch.enabled(),
            "segments": [segment.names
                         for segment in self._stitch_segments_],
            "loader_headed": [segment.has_prelude
                              for segment in self._stitch_segments_],
            "dispatches": sum(segment.dispatches
                              for segment in self._stitch_segments_),
            # the epoch-scan view: eligibility (with the blocking
            # reason when not), windows executed and steps they
            # covered — `dispatches` above stays the PER-STEP count
            "epoch_scan": runner.describe() if runner is not None
            else None,
        }

    def perf_report(self):
        """Text summary of the performance ledger
        (:mod:`veles_tpu.prof`): per-segment (and per-serve-bucket)
        flops / bytes / dispatch wall-time / achieved FLOP/s — MFU
        when the attached device has a peak-table entry — plus
        compile/recompile totals and the per-category HBM ledger.
        Always available (dispatch accounting has no knob); pair with
        ``trace_report()`` for the where-did-the-time-go view."""
        from veles_tpu import prof
        return prof.report_text()

    def trace_report(self, top=10):
        """Text summary of the in-memory trace ring (per-category
        totals, top-K spans by total time, segment dispatch vs
        host-gap split) — :func:`veles_tpu.trace.report_text` over the
        process-wide recorder.  Enable recording with
        ``root.common.engine.trace=on`` (or a ``.json`` path to also
        get the Perfetto timeline)."""
        from veles_tpu import trace
        return trace.report_text(top=top)

    # -- execution ----------------------------------------------------------
    def schedule(self, unit, src):
        """Enqueue a gate check for ``unit`` triggered by ``src``."""
        with self._queue_cond_:
            self._queue_.append((unit, src))
            self._queue_cond_.notify_all()

    def run(self):
        """Run the graph to completion (ref ``workflow.py:351-377``).

        The master never executes the graph body — job generation drives it
        instead (ref ``workflow.py:350-354``)."""
        if not self._is_initialized:
            raise RuntimeError("initialize() the workflow before run()")
        if self.is_master:
            return
        from veles_tpu import stitch
        # honored per run in BOTH directions: off after initialize
        # restores the per-unit path; on after an off-initialize builds
        # the missed segments now (once — not a graph re-walk per job)
        if stitch.enabled() and not self._stitch_segments_ \
                and not self._stitch_built_enabled_:
            self.rebuild_stitching()
        self._stitch_active_ = (bool(self._stitch_segments_)
                                and stitch.enabled())
        for segment in self._stitch_segments_:
            # an interrupted previous run may have left members
            # unconsumed — stale pass state must not suppress the
            # eager fallback
            segment.reset_pass()
        if self._epoch_runner_ is not None:
            # same hazard, Decision half: a window dispatched but the
            # decision never fired — its absorb flag must not skip a
            # real minibatch on this run
            self._epoch_runner_.reset_pass()
        self.stopped = False
        self._finished_event_.clear()
        tic = time.time()
        self.event("run", "begin")
        from veles_tpu import watch
        if watch.enabled():
            watch.publish("run", phase="begin",
                          workflow=type(self).__name__)
        self.schedule(self.start_point, None)
        self._drain()
        self._run_time += time.time() - tic
        self.event("run", "end")
        if watch.enabled():
            watch.publish("run", phase="end",
                          workflow=type(self).__name__,
                          run_time=round(self._run_time, 3),
                          results=self.gather_results())
            watch.publish("perf", self._perf_event())

    def _perf_event(self):
        """The compact perf digest a run's end publishes onto the
        telemetry bus: ledger counters + the HBM ledger peak — the
        live twin of ``perf_report()``'s headline numbers."""
        from veles_tpu import prof
        from veles_tpu.memory import Watcher
        totals = prof.ledger.summary()["totals"]
        hbm = Watcher.hbm_ledger()
        event = {key: totals.get(key) for key in
                 ("compiles", "recompiles", "flops_dispatched",
                  "achieved_flops", "mfu", "psum_bytes_moved")}
        event["hbm_peak_bytes"] = hbm.get("peak_bytes", 0)
        event["hbm_bytes"] = {
            cat: info["bytes"] for cat, info in
            hbm.get("by_category", {}).items() if info}
        report = self.stitch_report()
        event["dispatches"] = report.get("dispatches", 0)
        scan = report.get("epoch_scan") or {}
        event["scan_windows"] = scan.get("windows", 0)
        event["scan_steps"] = scan.get("steps", 0)
        return event

    def _drain(self):
        """Pop-and-run until the queue is empty AND no background unit is
        in flight.  ``wants_thread`` units execute on the shared host
        thread pool (ref ``veles/units.py:496-505`` ran *every* unit
        there); their downstream units are only scheduled from the
        worker after ``run()`` completes, so control-graph ordering is
        preserved — but units NOT downstream keep draining concurrently."""
        queue = self._queue_
        cond = self._queue_cond_
        while True:
            with cond:
                while not queue and self._inflight_ and not self.stopped:
                    cond.wait(0.05)
                if self.stopped or (not queue and not self._inflight_):
                    break
                unit, src = queue.popleft()
            if unit.wants_thread:
                self._spawn(unit, src)
            else:
                unit._check_gate_and_run(src)
        # join stragglers: run() returning MUST mean the graph is
        # quiescent — a wedged background unit would otherwise race
        # snapshot/teardown.  Escalate with warnings, then fail loudly
        # instead of silently violating the contract.
        with cond:
            start = time.time()
            next_warn = 60.0
            while self._inflight_:
                cond.wait(0.5)
                if not self._inflight_:   # finished at the boundary
                    break
                elapsed = time.time() - start
                if elapsed >= self.QUIESCENCE_TIMEOUT:
                    raise RuntimeError(
                        "workflow not quiescent: %d background unit(s) "
                        "still running %.0fs after drain" % (
                            self._inflight_, elapsed))
                if elapsed >= next_warn:
                    self.warning(
                        "%d background unit(s) still running %.0fs "
                        "after drain; waiting (timeout %.0fs)",
                        self._inflight_, elapsed, self.QUIESCENCE_TIMEOUT)
                    next_warn += 60.0
            queue.clear()

    def _spawn(self, unit, src):
        from veles_tpu import thread_pool
        with self._queue_cond_:
            self._inflight_ += 1
        thread_pool.submit(self._run_background, unit, src)

    def _run_background(self, unit, src):
        try:
            unit._check_gate_and_run(src)
        except Exception:
            self.exception("background unit %r failed", unit)
        finally:
            with self._queue_cond_:
                self._inflight_ -= 1
                self._queue_cond_.notify_all()

    def stop(self):
        self.stopped = True
        with self._queue_cond_:
            self._queue_cond_.notify_all()
        for unit in self._units:
            unit.stop()

    def on_workflow_finished(self):
        self.stopped = True
        self._finished_event_.set()
        cb, self._job_callback_ = self._job_callback_, None
        if cb is not None:
            cb(self.generate_data_for_master())
        if self.result_file:
            self.write_results()
        notify = getattr(self._launcher, "on_workflow_finished", None)
        if notify is not None:
            notify()

    def on_unit_failed(self, unit):
        self.warning("unit %r failed; stopping workflow", unit)
        self.stopped = True
        self._finished_event_.set()

    @property
    def run_time(self):
        return self._run_time

    # -- master/slave job protocol (ref workflow.py:478-617) ----------------
    def generate_data_for_slave(self, slave=None):
        """Per-unit payload list in dependency order; ``None`` entries for
        units that only negotiate on connect (ref ``workflow.py:478-510``)."""
        data = []
        for unit in self.units_in_dependency_order():
            if unit is self:
                continue
            data.append(unit.generate_data_for_slave(slave))
        return data

    def apply_data_from_master(self, data):
        units = [u for u in self.units_in_dependency_order() if u is not self]
        if len(data) != len(units):
            raise ValueError(
                "job payload has %d entries for %d units — master/slave "
                "workflow checksum mismatch?" % (len(data), len(units)))
        for unit, payload in zip(units, data):
            if payload is not None:
                unit.apply_data_from_master(payload)

    def prefetch_job(self, data):
        """Slave-side lookahead: offer the NEXT job's per-unit payloads
        to units exposing ``prefetch_job_data`` (the loader starts its
        minibatch IO) while the current job still computes.  Read-only
        with respect to serving state — ``apply_data_from_master``
        still happens when the job is actually processed."""
        units = [u for u in self.units_in_dependency_order()
                 if u is not self]
        if len(data) != len(units):
            return
        for unit, payload in zip(units, data):
            hook = getattr(unit, "prefetch_job_data", None)
            if hook is not None and payload is not None:
                try:
                    hook(payload)
                except Exception:
                    self.exception("prefetch_job_data failed on %r",
                                   unit)

    def generate_data_for_master(self):
        return [u.generate_data_for_master()
                for u in self.units_in_dependency_order() if u is not self]

    def apply_data_from_slave(self, data, slave=None):
        units = [u for u in self.units_in_dependency_order() if u is not self]
        if len(data) != len(units):
            raise ValueError(
                "update payload has %d entries for %d units — master/slave "
                "workflow checksum mismatch?" % (len(data), len(units)))
        for unit, payload in zip(units, data):
            if payload is not None:
                unit.apply_data_from_slave(payload, slave)

    def drop_slave(self, slave=None):
        for unit in self._units:
            unit.drop_slave(slave)

    def do_job(self, data, callback):
        """Slave side: install payload, run, send update via ``callback``
        (ref ``workflow.py:558-576``)."""
        self.apply_data_from_master(data)
        self._job_callback_ = callback
        self.run()

    # -- master crash-recovery (checkpoint protocol) ------------------------
    def _checkpoint_key(self, index, unit):
        """Stable per-unit key: dependency-order index + sanitized
        name.  The handshake checksum guarantees a restarted master
        rebuilds the same graph, so the index is reproducible; the
        name makes a mismatch loudly visible in the checkpoint dir."""
        safe = "".join(c if c.isalnum() else "_" for c in unit.name)
        return "u%03d_%s" % (index, safe)

    def capture_train_state(self):
        """Gather ``(train, meta)`` for a
        :class:`veles_tpu.checkpoint.TrainCheckpointer` — the master
        crash-recovery snapshot (docs/robustness.md).

        Every unit exposing ``checkpoint_state()`` contributes a dict;
        ndarray values go into the sharded ``train`` pytree, everything
        else into the JSON ``meta`` side.  The split is reassembled in
        :meth:`restore_train_state`, so units never see it."""
        import numpy
        train, meta = {}, {}
        for i, unit in enumerate(self.units_in_dependency_order()):
            if unit is self:
                continue
            hook = getattr(unit, "checkpoint_state", None)
            if hook is None:
                continue
            try:
                state = hook()
            except Exception:
                self.exception("checkpoint_state failed on %r", unit)
                continue
            if not state:
                continue
            key = self._checkpoint_key(i, unit)
            arrays = {k: v for k, v in state.items()
                      if isinstance(v, numpy.ndarray)}
            small = {k: v for k, v in state.items()
                     if not isinstance(v, numpy.ndarray)}
            if arrays:
                train[key] = arrays
            if small:
                meta[key] = small
        return train, meta

    def restore_train_state(self, train, meta):
        """Install a checkpoint captured by :meth:`capture_train_state`
        into this (freshly built and initialized) workflow: each
        contributing unit's ``restore_checkpoint_state(state)`` gets
        its reassembled dict back."""
        train = train or {}
        meta = meta or {}
        restored = 0
        for i, unit in enumerate(self.units_in_dependency_order()):
            if unit is self:
                continue
            hook = getattr(unit, "restore_checkpoint_state", None)
            if hook is None:
                continue
            key = self._checkpoint_key(i, unit)
            state = {}
            state.update(meta.get(key) or {})
            state.update(train.get(key) or {})
            if not state:
                continue
            try:
                hook(state)
                restored += 1
            except Exception:
                self.exception("restore_checkpoint_state failed on %r",
                               unit)
        self.info("restored checkpoint state into %d unit(s)", restored)
        return restored

    # -- results / stats ----------------------------------------------------
    def gather_results(self):
        """Collect metrics from IResultProvider units
        (ref ``workflow.py:827-851``)."""
        results = {}
        for unit in self._units:
            get = getattr(unit, "get_metric_values", None)
            if callable(get):
                try:
                    results.update(get())
                except Exception:
                    self.exception("result provider %r failed", unit)
        return results

    def write_results(self, path=None):
        import json
        path = path or self.result_file
        if not path:
            return

        def _default(obj):
            try:
                return float(obj)
            except (TypeError, ValueError):
                return repr(obj)
        with open(path, "w") as fout:
            json.dump(self.gather_results(), fout, indent=2,
                      default=_default)

    def get_unit_run_time_stats(self):
        """(unit, seconds) sorted descending (ref ``workflow.py:767-826``)."""
        stats = [(unit, unit.run_time) for unit in self._units]
        stats.sort(key=lambda pair: -pair[1])
        return stats

    def print_stats(self, top=10):
        total = sum(t for _, t in self.get_unit_run_time_stats()) or 1e-12
        self.info("unit run-time stats (top %d):", top)
        for unit, seconds in self.get_unit_run_time_stats()[:top]:
            self.info("  %6.2f%%  %8.3f s  %s",
                      100.0 * seconds / total, seconds, unit.name)

    # -- identity / export --------------------------------------------------
    def checksum(self):
        """Content-address the workflow definition so master and slave can
        verify they run the same code (ref ``workflow.py:852-866``, which
        hashes the workflow *file* bytes).

        Hashes (a) the graph structure (class + unit names in dependency
        order) and (b) the bytes of every module file defining a unit
        class.  A unit whose code cannot be located (REPL/exec-defined
        with no retrievable source) raises :class:`ChecksumError` —
        failing closed instead of letting two different workflows
        checksum equal."""
        sha = hashlib.sha256()
        files = {}      # module name → file path (module name, not the
        # path, goes into the hash: master and slave may hold the same
        # code at different absolute install locations)
        for unit in self.units_in_dependency_order():
            sha.update(type(unit).__name__.encode())
            sha.update(unit.name.encode())
            mod = inspect.getmodule(type(unit))
            fname = getattr(mod, "__file__", None)
            if fname and os.path.isfile(fname):
                files[mod.__name__] = fname
                continue
            try:
                sha.update(inspect.getsource(type(unit)).encode())
            except (OSError, TypeError):
                raise ChecksumError(
                    "cannot content-address %r (class %s: no module file "
                    "and no retrievable source) — master/slave checksum "
                    "would be unsound" % (unit, type(unit).__name__))
        for modname in sorted(files):
            sha.update(modname.encode())
            with open(files[modname], "rb") as fin:
                sha.update(fin.read())
        return sha.hexdigest()

    def package_export(self, path, precision=32, with_stablehlo=True):
        """Write a native-inference package (ref ``workflow.py:868-975``).

        Requires the workflow (or a subclass) to expose ``forwards`` —
        the forward units in execution order (StandardWorkflow does).
        """
        from veles_tpu.package import export_package
        return export_package(self, path, precision=precision,
                              with_stablehlo=with_stablehlo,
                              name=self.name)

    def generate_graph(self):
        """DOT text of the control graph (ref ``workflow.py:628``)."""
        lines = ["digraph %s {" % type(self).__name__.replace(" ", "_")]
        idx = {id(u): "u%d" % i for i, u in enumerate(self._units)}
        for unit in self._units:
            lines.append('  %s [label="%s\\n%s"];' % (
                idx[id(unit)], type(unit).__name__, unit.name))
        for unit in self._units:
            for dst in unit.links_to:
                if id(dst) in idx:
                    lines.append("  %s -> %s;" % (idx[id(unit)],
                                                  idx[id(dst)]))
        lines.append("}")
        return "\n".join(lines)


def _accepts_kwarg(fn, name):
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    if name in sig.parameters:
        return True
    return any(p.kind is inspect.Parameter.VAR_KEYWORD
               for p in sig.parameters.values())
