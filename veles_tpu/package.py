"""Model packaging: export a trained workflow for native inference.

Parity target: reference ``Workflow.package_export`` (``workflow.py:868-975``)
which writes ``contents.json`` + ``.npy`` weight files into a ``.zip`` or
``.tar.gz`` consumed by the C++ libVeles runtime
(``libVeles/src/workflow_loader.cc:41-49``, ``main_file_loader.h:100-136``).

TPU re-design (SURVEY §2.8 seam): the package carries BOTH
  * an interpretable unit list (``contents.json`` + ``.npy`` arrays) — the
    portable schema the native C++ runtime (``native/``) executes, and
  * optionally a serialized StableHLO module (``model.stablehlo``) produced
    by ``jax.export`` — the XLA-native artifact a PJRT consumer can run
    bit-identically to the trained graph.

Inference-time semantics (applied identically by :class:`PackagedRunner`
and the C++ runtime): dropout → identity (inverted dropout needs no test
scaling), stochastic pooling → probabilistic weighting (the Zeiler &
Fergus test-time procedure: Σ pᵢ·xᵢ over each window).
"""

import hashlib
import io
import json
import os
import tarfile
import zipfile

import numpy

FORMAT_VERSION = 1
#: int8 packages need a dequantizing reader — they declare version 2 so
#: pre-int8 readers fail closed instead of silently using raw codes
INT8_FORMAT_VERSION = 2
STABLEHLO_NAME = "model.stablehlo"
CONTENTS_NAME = "contents.json"


def _unit_export_entry(unit, array_refs):
    """Build the contents.json entry for one forward unit.

    ``array_refs``: dict array-name → file ref (filled by caller).
    """
    mapping = getattr(type(unit), "MAPPING", None)
    if mapping is None and type(unit).__name__ == "MeanDispNormalizer":
        mapping = "mean_disp"
    if not mapping:
        raise ValueError("unit %r (%s) is not packageable: no MAPPING"
                         % (unit, type(unit).__name__))
    entry = {"type": mapping, "name": unit.name or mapping,
             "config": {}, "arrays": array_refs}
    if mapping.startswith("all2all") or mapping == "softmax":
        entry["config"]["output_sample_shape"] = \
            list(unit.output_sample_shape)
        entry["config"]["activation"] = type(unit).ACTIVATION
        entry["config"]["is_softmax"] = mapping == "softmax"
        entry["config"]["include_bias"] = bool(unit.include_bias)
    elif mapping.startswith("conv"):
        entry["config"].update(
            n_kernels=unit.n_kernels, kx=unit.kx, ky=unit.ky,
            padding=list(unit.padding), sliding=list(unit.sliding),
            activation=type(unit).ACTIVATION,
            include_bias=bool(unit.include_bias))
        if getattr(unit, "grouping", 1) != 1:
            entry["config"]["grouping"] = int(unit.grouping)
    elif mapping.endswith("pooling"):
        entry["config"].update(kind=type(unit).KIND, kx=unit.kx,
                               ky=unit.ky, sliding=list(unit.sliding))
    elif mapping == "lrn":
        entry["config"].update(alpha=unit.alpha, beta=unit.beta,
                               k=unit.k, n=unit.n)
    elif mapping.startswith("activation_"):
        entry["config"].update(func=type(unit).FUNC, k=unit.k)
    elif mapping == "dropout":
        entry["config"].update(dropout_ratio=unit.dropout_ratio)
    elif mapping == "mean_disp":
        pass
    elif mapping in ("lstm", "rnn"):
        entry["config"].update(hidden_units=unit.hidden_units,
                               last_only=bool(unit.last_only),
                               include_bias=bool(unit.include_bias))
    elif mapping == "deconv":
        # transposed conv shares the paired Conv's weight layout
        # (ky, kx, C, K); its pure fn has no bias term
        left, right, top, bottom = unit.padding
        if not (0 <= left < unit.kx and 0 <= right < unit.kx
                and 0 <= top < unit.ky and 0 <= bottom < unit.ky):
            raise ValueError(
                "deconv with forward padding >= kernel size is not "
                "packageable (padding %r vs kernel (%d, %d))"
                % (unit.padding, unit.kx, unit.ky))
        entry["config"].update(
            n_kernels=unit.n_kernels, kx=unit.kx, ky=unit.ky,
            padding=list(unit.padding), sliding=list(unit.sliding),
            activation=type(unit).ACTIVATION, include_bias=False)
    elif mapping == "cutter":
        entry["config"].update(window=list(unit.window))
    elif mapping == "channel_splitter":
        entry["config"].update(start=int(unit.start),
                               count=unit.count)
    else:
        raise ValueError("unit type %r is not packageable" % mapping)
    return entry


def _quantize_int8(arr):
    """Per-output-channel symmetric int8: scale_j = max|w[..., j]|/127.
    Returns (int8 array, float32 scales over the last axis)."""
    flat = arr.reshape(-1, arr.shape[-1]) if arr.ndim > 1 \
        else arr.reshape(1, -1)
    scale = numpy.abs(flat).max(axis=0) / 127.0
    scale = numpy.where(scale == 0, 1.0, scale).astype(numpy.float32)
    q = numpy.clip(numpy.rint(arr / scale), -127, 127)
    return q.astype(numpy.int8), scale


def _collect_arrays(unit, precision):
    """name → numpy array (host-synced, precision-cast) for one unit.

    ``precision=8``: weights are per-output-channel symmetric int8
    (scales stored alongside as ``weights.scale``); bias/mean/disp stay
    float32 — the loaders (PackagedRunner and the native engine's
    workflow loader) dequantize at load, so compute stays float and
    the package is 4× smaller than fp32 (the same trade the fp16
    packages make at 2×)."""
    dtype = numpy.float16 if precision == 16 else numpy.float32
    out = {}
    # rdisp is MeanDispNormalizer's reciprocal dispersion; packaged as
    # "disp" (the runner multiplies, matching the unit's (x-mean)*rdisp)
    for attr, name in (("weights", "weights"), ("bias", "bias"),
                       ("mean", "mean"), ("rdisp", "disp")):
        vec = getattr(unit, attr, None)
        if vec is None or not vec:
            continue
        vec.map_read()
        out[name] = numpy.ascontiguousarray(vec.mem, dtype=dtype)
    if not getattr(unit, "include_bias", True):
        out.pop("bias", None)
    if out.get("weights") is not None and \
            getattr(unit, "weights_transposed", False) and \
            out["weights"].ndim == 2:
        # normalize to the package's canonical (fan-in, neurons)
        # layout so the golden model and native engine never need the
        # storage knob
        out["weights"] = numpy.ascontiguousarray(out["weights"].T)
    if precision == 8 and out.get("weights") is not None:
        q, scale = _quantize_int8(out["weights"])
        out["weights"] = q
        out["weights.scale"] = scale
    return out


def dequantize_arrays(arrays):
    """Resolve ``<name>.scale`` companions in-place: the int8 payload
    (already float-typed by the loader) is multiplied by its per-last-
    axis scales and the companion entry removed.  Shared by
    :class:`PackagedRunner`; the native engine applies the same rule in
    C++ (``native/src/workflow.cc``)."""
    for key in [k for k in arrays if k.endswith(".scale")]:
        base = key[:-len(".scale")]
        scale = arrays.pop(key)
        if base not in arrays:
            continue
        arr = arrays[base]
        if scale.size and arr.shape[-1] == scale.size:
            # the multiply's f32 output buffer is the only copy made
            arrays[base] = numpy.asarray(arr, numpy.float32) \
                * numpy.asarray(scale, numpy.float32)
        else:
            raise ValueError(
                "scale %r (%d entries) does not match %r last axis %r"
                % (key, scale.size, base, arr.shape))
    return arrays


def _npy_bytes(array):
    buf = io.BytesIO()
    numpy.save(buf, array, allow_pickle=False)
    return buf.getvalue()


def export_stablehlo(forwards, input_shape, dtype=numpy.float32):
    """Serialize the whole forward chain as StableHLO via ``jax.export``.

    Returns bytes, or None when jax.export is unavailable.
    """
    try:
        import jax
        from jax import export as jax_export
        fn = build_forward_fn(forwards)
        spec = jax.ShapeDtypeStruct(tuple(input_shape), dtype)
        exported = jax_export.export(jax.jit(fn))(spec)
        return exported.serialize()
    except Exception:
        # units without a jax pure form (e.g. MeanDispNormalizer) or an
        # unsupported chain: the interpretable package is still written
        return None


def build_forward_fn(forwards):
    """Compose the units' pure functions into one jittable forward fn
    (closure over host-synced params)."""
    import jax.numpy as jnp
    steps = []
    for unit in forwards:
        pure = type(unit).pure
        cfg = unit.pure_config()
        params = {}
        for attr, key in (("weights", "w"), ("bias", "b")):
            vec = getattr(unit, attr, None)
            if vec:
                vec.map_read()
                params[key] = jnp.asarray(vec.mem)
        if not getattr(unit, "include_bias", True):
            params.pop("b", None)
        mapping = type(unit).MAPPING
        if mapping == "dropout":
            steps.append(lambda x: x)  # inference: identity
            continue
        if mapping.endswith("pooling") and "stochastic" in mapping:
            def step(x, p=params, c=cfg):
                raise NotImplementedError(
                    "stochastic pooling has no jax test-time export")
            steps.append(step)
            continue

        def step(x, pure=pure, p=params, c=cfg):
            return pure(p, x, **c)
        steps.append(step)

    def forward(x):
        for s in steps:
            x = s(x)
        return x
    return forward


def export_package(workflow_or_forwards, path, precision=32,
                   with_stablehlo=True, name=None):
    """Write a ``.zip`` or ``.tar.gz`` inference package.

    ``workflow_or_forwards``: a workflow exposing ``.forwards`` (e.g.
    :class:`veles_tpu.znicz.standard_workflow.StandardWorkflow`) or an
    explicit list of forward units in execution order.
    """
    if precision not in (8, 16, 32):
        raise ValueError("precision must be 8, 16 or 32")
    forwards = getattr(workflow_or_forwards, "forwards",
                       workflow_or_forwards)
    if not forwards:
        raise ValueError("nothing to export: no forward units")
    files = {}          # arcname → bytes
    units = []
    counter = 0
    for unit in forwards:
        arrays = _collect_arrays(unit, precision)
        refs = {}
        for aname, arr in sorted(arrays.items()):
            fname = "@%04d_%s.npy" % (
                counter, "x".join(str(d) for d in arr.shape) or "scalar")
            counter += 1
            files[fname] = _npy_bytes(arr)
            refs[aname] = fname
        units.append(_unit_export_entry(unit, refs))
    input_shape = list(forwards[0].input.shape) \
        if getattr(forwards[0], "input", None) is not None else None
    contents = {
        "format_version": INT8_FORMAT_VERSION
        if precision == 8 else FORMAT_VERSION,
        "framework": "veles_tpu",
        "name": name or getattr(workflow_or_forwards, "name", "model"),
        "precision": precision,
        "input_shape": input_shape,
        "units": units,
    }
    if precision == 8:
        # the StableHLO blob would embed the live fp32 weights — a
        # second, divergent weight set that also defeats the 4x size
        # reduction; int8 packages are interpretable-units only
        with_stablehlo = False
    if with_stablehlo and input_shape:
        blob = export_stablehlo(forwards, input_shape)
        if blob:
            files[STABLEHLO_NAME] = bytes(blob)
            contents["stablehlo"] = STABLEHLO_NAME
    # content checksum over every array/artifact file, stored INSIDE
    # contents.json so consumers can verify package integrity
    digest = hashlib.sha256()
    for arcname, data in sorted(files.items()):
        digest.update(arcname.encode())
        digest.update(data)
    contents["checksum"] = digest.hexdigest()
    files[CONTENTS_NAME] = json.dumps(
        contents, indent=1, sort_keys=True).encode()

    if path.endswith(".zip"):
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            for arcname, data in sorted(files.items()):
                z.writestr(arcname, data)
    elif path.endswith((".tar.gz", ".tgz")):
        with tarfile.open(path, "w:gz") as t:
            for arcname, data in sorted(files.items()):
                info = tarfile.TarInfo(arcname)
                info.size = len(data)
                t.addfile(info, io.BytesIO(data))
    else:
        raise ValueError("path must end with .zip, .tar.gz or .tgz")
    return contents


def _read_package(path):
    """arcname → bytes from a .zip/.tgz package or a directory."""
    files = {}
    if os.path.isdir(path):
        for fname in os.listdir(path):
            with open(os.path.join(path, fname), "rb") as f:
                files[fname] = f.read()
    elif path.endswith(".zip"):
        with zipfile.ZipFile(path) as z:
            for arcname in z.namelist():
                files[arcname] = z.read(arcname)
    else:
        with tarfile.open(path, "r:*") as t:
            for member in t.getmembers():
                if member.isfile():
                    files[member.name] = t.extractfile(member).read()
    return files


# ---------------------------------------------------------------------------
# Pure-numpy packaged inference — the golden model for the native runtime.

def _np_act(name, z):
    if name is None:
        return z
    if name == "tanh":
        return 1.7159 * numpy.tanh(0.6666 * z)
    if name == "sigmoid":
        return 1.0 / (1.0 + numpy.exp(-z))
    if name == "relu":  # znicz RELU = clipped softplus (fused.py _ACT)
        return numpy.log1p(numpy.exp(numpy.minimum(z, 30.0)))
    if name == "strict_relu":
        return numpy.maximum(z, 0.0)
    raise ValueError("unknown activation %r" % name)


def _np_act_unit(func, x, k):
    if func == "tanh":
        return 1.7159 * numpy.tanh(0.6666 * x)
    if func == "sigmoid":
        return 1.0 / (1.0 + numpy.exp(-x))
    if func == "relu":
        return numpy.log1p(numpy.exp(numpy.minimum(x, 30.0)))
    if func == "strict_relu":
        return numpy.maximum(x, 0.0)
    if func == "log":
        return numpy.log(x + numpy.sqrt(x * x + 1.0))
    if func == "tanhlog":
        t = 1.7159 * numpy.tanh(0.6666 * x)
        return numpy.where(
            numpy.abs(t) <= 1.7159 * 0.6666, t,
            numpy.sign(x) * numpy.log(
                numpy.abs(x * 0.6666 * 1.7159) + 1.0))
    if func == "sincos":
        odd = (numpy.arange(x.shape[-1]) % 2) == 1
        return numpy.where(odd, numpy.sin(x), numpy.cos(x))
    if func == "mul":
        return x * k
    raise ValueError("unknown func %r" % func)


def _np_softmax(z):
    z = z - z.max(axis=-1, keepdims=True)
    e = numpy.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def _np_deconv(x, w, padding, sliding):
    """Transposed conv matching ``znicz.misc_units.Deconv.pure``
    (``lax.conv_transpose``, HWOI, no kernel flip): dilate the input by
    the stride, pad with (k−1−p) per edge, then correlate."""
    left, right, top, bottom = padding
    sx, sy = sliding
    b_sz, h, wd, _k = x.shape
    ky, kx, c_out, _k2 = w.shape
    hd, wdd = (h - 1) * sy + 1, (wd - 1) * sx + 1
    pt, pb = ky - 1 - top, ky - 1 - bottom
    pl, pr = kx - 1 - left, kx - 1 - right
    if min(pt, pb, pl, pr) < 0:
        # the export gate rejects these; a hand-built package must not
        # silently flip numpy slices (eager conv_transpose would crop)
        raise ValueError(
            "deconv: forward padding %r >= kernel (%d, %d) is not "
            "supported by the packaged runner" % (padding, kx, ky))
    xp = numpy.zeros((b_sz, hd + pt + pb, wdd + pl + pr, x.shape[-1]),
                     numpy.float32)
    xp[:, pt:pt + hd:sy, pl:pl + wdd:sx, :] = x
    out_h = xp.shape[1] - ky + 1
    out_w = xp.shape[2] - kx + 1
    out = numpy.zeros((b_sz, out_h, out_w, c_out), numpy.float32)
    for dy in range(ky):
        for dx in range(kx):
            patch = xp[:, dy:dy + out_h, dx:dx + out_w, :]
            out += patch @ w[dy, dx].T          # (…, K) @ (K, C)
    return out


def _np_conv(x, w, b, padding, sliding, grouping=1):
    left, right, top, bottom = padding
    sx, sy = sliding
    ky, kx, cin, k = w.shape           # cin = per-group fan-in
    if grouping > 1:
        # output block i reads input channel group i (XLA's
        # feature_group_count semantics; native runtime matches)
        kpg = k // grouping
        outs = [
            _np_conv(x[..., gi * cin:(gi + 1) * cin],
                     w[..., gi * kpg:(gi + 1) * kpg], None,
                     padding, sliding)
            for gi in range(grouping)]
        out = numpy.concatenate(outs, axis=-1)
        return out if b is None else out + b
    x = numpy.pad(x, ((0, 0), (top, bottom), (left, right), (0, 0)))
    bsz, h, ww, _ = x.shape
    oh = (h - ky) // sy + 1
    ow = (ww - kx) // sx + 1
    # im2col → one big sgemm (mirrors the native runtime's strategy)
    cols = numpy.empty((bsz, oh, ow, ky * kx * cin), x.dtype)
    for iy in range(ky):
        for ix in range(kx):
            patch = x[:, iy:iy + oh * sy:sy, ix:ix + ow * sx:sx, :]
            cols[..., (iy * kx + ix) * cin:(iy * kx + ix + 1) * cin] = patch
    out = cols.reshape(-1, ky * kx * cin) @ w.reshape(ky * kx * cin, k)
    out = out.reshape(bsz, oh, ow, k)
    if b is not None:
        out = out + b
    return out


def _np_pool(x, kind, kx, ky, sliding):
    sx, sy = sliding
    b, h, w, c = x.shape
    oh = (h - ky) // sy + 1
    ow = (w - kx) // sx + 1
    patches = numpy.empty((b, oh, ow, ky * kx, c), x.dtype)
    for iy in range(ky):
        for ix in range(kx):
            patches[:, :, :, iy * kx + ix, :] = \
                x[:, iy:iy + oh * sy:sy, ix:ix + ow * sx:sx, :]
    if kind == "max":
        return patches.max(axis=3)
    if kind == "avg":
        return patches.mean(axis=3)
    mag = numpy.abs(patches)
    if kind == "maxabs":
        sel = numpy.argmax(mag, axis=3)
        return numpy.take_along_axis(
            patches, sel[:, :, :, None, :], axis=3)[:, :, :, 0, :]
    # stochastic{,abs}: test-time probabilistic weighting (Σ pᵢ·xᵢ)
    probs = mag / numpy.maximum(mag.sum(axis=3, keepdims=True), 1e-12)
    vals = mag if kind == "stochasticabs" else patches
    return (probs * vals).sum(axis=3)


def _np_lrn(x, alpha, beta, k, n):
    half = n // 2
    sq = x * x
    pads = [(0, 0)] * (x.ndim - 1) + [(half, n - 1 - half)]
    padded = numpy.pad(sq, pads)
    window = numpy.zeros_like(x)
    for i in range(n):
        sl = [slice(None)] * (x.ndim - 1) + \
            [slice(i, i + x.shape[-1])]
        window = window + padded[tuple(sl)]
    return x / (k + alpha * window) ** beta


class PackagedRunner(object):
    """Executes a package's unit list in pure numpy (fp32)."""

    def __init__(self, path_or_files):
        files = path_or_files if isinstance(path_or_files, dict) \
            else _read_package(path_or_files)
        self.contents = json.loads(files[CONTENTS_NAME].decode())
        if self.contents.get("format_version") not in (
                FORMAT_VERSION, INT8_FORMAT_VERSION):
            raise ValueError("unsupported package format %r"
                             % self.contents.get("format_version"))
        expected = self.contents.get("checksum")
        if expected:
            digest = hashlib.sha256()
            for arcname, data in sorted(files.items()):
                if arcname != CONTENTS_NAME:
                    digest.update(arcname.encode())
                    digest.update(data)
            if digest.hexdigest() != expected:
                raise ValueError("package checksum mismatch")
        self.units = []
        for entry in self.contents["units"]:
            arrays = {
                name: numpy.load(io.BytesIO(files[ref]),
                                 allow_pickle=False).astype(numpy.float32)
                for name, ref in entry["arrays"].items()}
            dequantize_arrays(arrays)
            self.units.append((entry["type"], entry["config"], arrays))

    @property
    def input_shape(self):
        return self.contents.get("input_shape")

    def run(self, x):
        x = numpy.asarray(x, numpy.float32)
        for utype, cfg, arrays in self.units:
            x = self._run_unit(utype, cfg, arrays, x)
        return x

    def _run_unit(self, utype, cfg, arrays, x):
        if utype.startswith("all2all") or utype == "softmax":
            h = x.reshape(len(x), -1)
            z = h @ arrays["weights"]
            if "bias" in arrays:
                z = z + arrays["bias"]
            if cfg.get("is_softmax"):
                z = _np_softmax(z)
            else:
                z = _np_act(cfg.get("activation"), z)
            return z.reshape([len(x)] + list(cfg["output_sample_shape"]))
        if utype.startswith("conv"):
            out = _np_conv(x, arrays["weights"], arrays.get("bias"),
                           cfg["padding"], cfg["sliding"],
                           cfg.get("grouping", 1))
            return _np_act(cfg.get("activation"), out)
        if utype.endswith("pooling"):
            return _np_pool(x, cfg["kind"], cfg["kx"], cfg["ky"],
                            cfg["sliding"])
        if utype == "lrn":
            return _np_lrn(x, cfg["alpha"], cfg["beta"], cfg["k"],
                           cfg["n"])
        if utype.startswith("activation_"):
            return _np_act_unit(cfg["func"], x, cfg.get("k", 1.0))
        if utype == "dropout":
            return x
        if utype == "mean_disp":
            return (x - arrays["mean"]) * arrays["disp"]
        if utype == "deconv":
            out = _np_deconv(x, arrays["weights"], cfg["padding"],
                             cfg["sliding"])
            return _np_act(cfg.get("activation"), out)
        if utype == "cutter":
            y, xo, h, w = cfg["window"]
            return numpy.ascontiguousarray(x[:, y:y + h, xo:xo + w, :])
        if utype == "channel_splitter":
            start = int(cfg["start"])
            count = cfg.get("count")
            stop = x.shape[-1] if count is None else start + int(count)
            return numpy.ascontiguousarray(x[..., start:stop])
        if utype in ("lstm", "rnn"):
            b, t, _d = x.shape
            h_units = int(cfg["hidden_units"])
            w = arrays["weights"]
            bias = arrays.get("bias")

            def sigmoid(z):
                return 1.0 / (1.0 + numpy.exp(-z))

            last_only = bool(cfg.get("last_only"))
            hh = numpy.zeros((b, h_units), numpy.float32)
            cc = numpy.zeros_like(hh) if utype == "lstm" else None
            ys = None if last_only else numpy.empty(
                (b, t, h_units), numpy.float32)
            for step in range(t):
                z = numpy.concatenate([x[:, step], hh], axis=1) @ w
                if bias is not None:
                    z = z + bias
                if utype == "lstm":
                    i, f, g, o = numpy.split(z, 4, axis=1)
                    cc = sigmoid(f) * cc + sigmoid(i) * numpy.tanh(g)
                    hh = sigmoid(o) * numpy.tanh(cc)
                else:
                    hh = numpy.tanh(z)
                if ys is not None:
                    ys[:, step] = hh
            return hh if last_only else ys
        raise ValueError("unknown packaged unit type %r" % utype)
