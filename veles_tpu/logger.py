"""Class-scoped colored logging + event timeline.

Parity target: reference ``veles/logger.py`` — per-class loggers with color
(``logger.py:59+``), an ``event()`` timeline API (``logger.py:264-280``) and
optional duplication of all records to an external sink (the reference used
MongoDB, ``logger.py:292``; here the sink is a pluggable callable so the
status server / metric writer can subscribe without a database dependency).
"""

import logging
import sys
import threading
import time

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[92m",
    logging.WARNING: "\033[93m",
    logging.ERROR: "\033[91m",
    logging.CRITICAL: "\033[1;91m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        msg = super(_ColorFormatter, self).format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return "%s%s%s" % (color, msg, _RESET) if color else msg
        return msg


_configured = False
_configure_lock = threading.Lock()


def setup_logging(level=logging.INFO, debug_classes=()):
    """Install the root handler once; per-class DEBUG like the reference's
    ``--debug CLASS,...`` flag (``veles/__main__.py:833-835``)."""
    global _configured
    with _configure_lock:
        if not _configured:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(_ColorFormatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s",
                "%H:%M:%S"))
            logging.getLogger().addHandler(handler)
            _configured = True
        logging.getLogger().setLevel(level)
        for klass in debug_classes:
            logging.getLogger(klass).setLevel(logging.DEBUG)


class Logger(object):
    """Mixin giving every object a logger named after its class and an
    ``event()`` timeline channel."""

    #: Pluggable event sinks: callables taking the event dict.
    event_sinks = []

    def __init__(self, **kwargs):
        super(Logger, self).__init__()
        self._logger_ = logging.getLogger(self.__class__.__name__)

    @property
    def logger(self):
        return self._logger_

    def init_unpickled(self):
        # Logger objects are not pickleable; restore after unpickle
        # (cooperates with Pickleable in distributable.py).
        sup = super(Logger, self)
        if hasattr(sup, "init_unpickled"):
            sup.init_unpickled()
        self._logger_ = logging.getLogger(self.__class__.__name__)

    def debug(self, msg, *args):
        self._logger_.debug(msg, *args)

    def info(self, msg, *args):
        self._logger_.info(msg, *args)

    def warning(self, msg, *args):
        self._logger_.warning(msg, *args)

    def error(self, msg, *args):
        self._logger_.error(msg, *args)

    def exception(self, msg="", *args):
        self._logger_.exception(msg, *args)

    def event(self, name, phase, **kwargs):
        """Record a timeline event (ref ``veles/logger.py:264-280``).

        ``phase`` is ``"begin"``, ``"end"`` or ``"single"``; consumers (web
        status, trace writer) subscribe via :attr:`event_sinks`.
        """
        record = {"name": name, "phase": phase, "time": time.time(),
                  "instance": getattr(self, "name", self.__class__.__name__)}
        record.update(kwargs)
        for sink in Logger.event_sinks:
            try:
                sink(record)
            except Exception:  # noqa: BLE001 - sinks must not kill the run
                self._logger_.exception("event sink failed")
        return record
