"""Class-scoped colored logging + event timeline.

Parity target: reference ``veles/logger.py`` — per-class loggers with color
(``logger.py:59+``), an ``event()`` timeline API (``logger.py:264-280``) and
optional duplication of all records to an external sink.  The reference
duplicated into MongoDB with a TTL index garbage-collecting old records
(``logger.py:292``, ``web_status.py:158-190``); here the sink is a
pluggable callable, and :func:`duplicate_logs_to_db` provides the
zero-dependency equivalent — every record mirrored into SQLite with the
same TTL-expiry semantics (purged on open and periodically), queryable
by session/logger/level for post-mortems and the status page.
"""

import logging
import sys
import threading
import time

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[92m",
    logging.WARNING: "\033[93m",
    logging.ERROR: "\033[91m",
    logging.CRITICAL: "\033[1;91m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        msg = super(_ColorFormatter, self).format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return "%s%s%s" % (color, msg, _RESET) if color else msg
        return msg


_configured = False
_configure_lock = threading.Lock()


def setup_logging(level=logging.INFO, debug_classes=()):
    """Install the root handler once; per-class DEBUG like the reference's
    ``--debug CLASS,...`` flag (``veles/__main__.py:833-835``)."""
    global _configured
    with _configure_lock:
        if not _configured:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(_ColorFormatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s",
                "%H:%M:%S"))
            logging.getLogger().addHandler(handler)
            _configured = True
        logging.getLogger().setLevel(level)
        for klass in debug_classes:
            logging.getLogger(klass).setLevel(logging.DEBUG)


class Logger(object):
    """Mixin giving every object a logger named after its class and an
    ``event()`` timeline channel."""

    #: Pluggable event sinks: callables taking the event dict.
    event_sinks = []

    def __init__(self, **kwargs):
        super(Logger, self).__init__()
        self._logger_ = logging.getLogger(self.__class__.__name__)

    @property
    def logger(self):
        return self._logger_

    def init_unpickled(self):
        # Logger objects are not pickleable; restore after unpickle
        # (cooperates with Pickleable in distributable.py).
        sup = super(Logger, self)
        if hasattr(sup, "init_unpickled"):
            sup.init_unpickled()
        self._logger_ = logging.getLogger(self.__class__.__name__)

    def debug(self, msg, *args):
        self._logger_.debug(msg, *args)

    def info(self, msg, *args):
        self._logger_.info(msg, *args)

    def warning(self, msg, *args):
        self._logger_.warning(msg, *args)

    def error(self, msg, *args):
        self._logger_.error(msg, *args)

    def exception(self, msg="", *args):
        self._logger_.exception(msg, *args)

    def event(self, name, phase, **kwargs):
        """Record a timeline event (ref ``veles/logger.py:264-280``).

        ``phase`` is ``"begin"``, ``"end"`` or ``"single"``; consumers (web
        status, trace writer) subscribe via :attr:`event_sinks`.
        """
        record = {"name": name, "phase": phase, "time": time.time(),
                  "instance": getattr(self, "name", self.__class__.__name__)}
        record.update(kwargs)
        for sink in Logger.event_sinks:
            try:
                sink(record)
            except Exception:  # noqa: BLE001 - sinks must not kill the run
                self._logger_.exception("event sink failed")
        return record


class SQLiteLogHandler(logging.Handler):
    """Mirror every log record into a SQLite table with TTL expiry —
    the reference's MongoDB duplication + TTL index
    (``veles/logger.py:292``) without the database dependency.

    Thread-safe (one connection guarded by the handler lock; SQLite
    serializes writers anyway).  Old rows are purged on open and then
    opportunistically every ``gc_every`` inserts, mirroring the TTL
    index's background expiry.
    """

    def __init__(self, path, session=None, ttl_days=7.0, gc_every=500):
        super(SQLiteLogHandler, self).__init__()
        import os
        import sqlite3
        import uuid
        self.path = path
        self.session = session or uuid.uuid4().hex
        self.ttl_seconds = float(ttl_days) * 86400.0
        self.gc_every = int(gc_every)
        self._since_gc = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        # WAL + NORMAL: one fsync per checkpoint instead of per log
        # record — the handler sits on the root-logger hot path
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS logs ("
            " ts REAL, session TEXT, logger TEXT, level INTEGER,"
            " message TEXT)")
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS logs_ts ON logs (ts)")
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS logs_session ON logs (session)")
        self.purge()

    def purge(self, now=None):
        """Delete rows older than the TTL (the MongoDB TTL-index
        equivalent); returns the number of expired rows."""
        cutoff = (now if now is not None else time.time()) \
            - self.ttl_seconds
        with self._conn:
            cur = self._conn.execute("DELETE FROM logs WHERE ts < ?",
                                     (cutoff,))
        return cur.rowcount

    def emit(self, record):
        try:
            with self._conn:
                self._conn.execute(
                    "INSERT INTO logs VALUES (?, ?, ?, ?, ?)",
                    (record.created, self.session, record.name,
                     record.levelno, self.format(record)))
            self._since_gc += 1
            if self._since_gc >= self.gc_every:
                self._since_gc = 0
                self.purge()
        except Exception:
            self.handleError(record)

    def query(self, session=None, logger=None, min_level=None,
              limit=200):
        """Recent records (newest first) for the status page /
        post-mortem CLI — the reference's web-status log view."""
        sql = "SELECT ts, session, logger, level, message FROM logs"
        clauses, args = [], []
        if session:
            clauses.append("session = ?")
            args.append(session)
        if logger:
            clauses.append("logger = ?")
            args.append(logger)
        if min_level is not None:
            clauses.append("level >= ?")
            args.append(int(min_level))
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += " ORDER BY ts DESC LIMIT ?"
        args.append(int(limit))
        return list(self._conn.execute(sql, args))

    def close(self):
        try:
            self._conn.close()
        finally:
            super(SQLiteLogHandler, self).close()


def duplicate_logs_to_db(path, session=None, ttl_days=7.0,
                         level=logging.DEBUG):
    """Attach a :class:`SQLiteLogHandler` to the root logger (the
    reference's ``--log-mongo addr`` duplication, ``logger.py:292``).
    Returns the handler; call ``.close()`` (or keep it for
    ``.query()``) when done."""
    handler = SQLiteLogHandler(path, session=session, ttl_days=ttl_days)
    handler.setLevel(level)
    handler.setFormatter(logging.Formatter("%(message)s"))
    logging.getLogger().addHandler(handler)
    return handler
