"""RESTful inference endpoint unit.

Parity target: reference ``veles/restful_api.py:78-160`` — an in-workflow
HTTP endpoint accepting JSON (or base64 numpy) input, feeding it through
the trained forward pass and returning the model output.  The reference
pairs it with a ``RestfulLoader``; here the unit drives the forward units
directly (they are device-resident and reentrant), which removes the
loader indirection while keeping the same wire contract:

    POST /service  {"input": [[...]]}  →  {"result": [[...]]}
"""

import json
import threading

import numpy

from veles_tpu.units import Unit


class RESTfulAPI(Unit):
    """Serves the workflow's forward pass over HTTP."""

    def __init__(self, workflow, **kwargs):
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.port = kwargs.get("port", 0)
        self.host = kwargs.get("host", "127.0.0.1")
        self.path = kwargs.get("path", "/service")
        self.forwards = None     # list of forward units (linked)
        self._server_ = None
        self.demand("forwards")

    def init_unpickled(self):
        super(RESTfulAPI, self).init_unpickled()
        self._server_ = None

    def infer(self, batch):
        """Run the forward chain on a host batch; returns host output.
        The loader's input link is swapped out for the request and
        restored, so a serving workflow can keep training."""
        from veles_tpu.memory import Vector
        batch = numpy.ascontiguousarray(batch, dtype=numpy.float32)
        first = self.forwards[0]
        # the whole swap/run/restore is one critical section —
        # ThreadingHTTPServer serves requests concurrently
        with first.data_lock():
            links = first.__dict__.setdefault("_linked_attrs", {})
            saved_link = links.pop("input", None)
            saved_value = first.__dict__.pop("input", None)
            try:
                vec = Vector(batch)
                vec.initialize(first.device)
                first.input = vec
                for unit in self.forwards:
                    unit.run()
                out = self.forwards[-1].output
                out.map_read()
                return numpy.array(out.mem[:len(batch)])
            finally:
                first.__dict__.pop("input", None)
                if saved_link is not None:
                    links["input"] = saved_link
                elif saved_value is not None:
                    first.__dict__["input"] = saved_value

    def initialize(self, **kwargs):
        super(RESTfulAPI, self).initialize(**kwargs)
        if self._server_ is not None:
            return
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        api = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                if self.path != api.path:
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    batch = numpy.asarray(payload["input"],
                                          dtype=numpy.float32)
                    if batch.ndim == 1:
                        batch = batch[None, :]
                    result = api.infer(batch)
                    body = json.dumps(
                        {"result": result.tolist()}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # noqa: BLE001 - wire boundary
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            def log_message(self, fmt, *args):
                api.debug("http: " + fmt, *args)

        self._server_ = ThreadingHTTPServer((self.host, self.port),
                                            Handler)
        self.port = self._server_.server_address[1]
        thread = threading.Thread(target=self._server_.serve_forever,
                                  daemon=True, name="restful-api")
        thread.start()
        self.info("REST inference on http://%s:%d%s", self.host,
                  self.port, self.path)

    def stop(self):
        if self._server_ is not None:
            self._server_.shutdown()
            self._server_ = None
