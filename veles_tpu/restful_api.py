"""RESTful inference endpoint unit — thin adapter over veles_tpu.serve.

Parity target: reference ``veles/restful_api.py:78-160`` — an
in-workflow HTTP endpoint accepting JSON or base64 numpy input, feeding
it through the trained forward pass and returning the model output.
The wire contract is unchanged::

    POST /service  {"input": [[...]]}                    → {"result": [[...]]}
    POST /service  {"input_b64": ..., "shape": [...]}    → {"result": [[...]]}

Historically this unit ran one un-batched forward per HTTP request
inside a per-request critical section (swapping the loader's input
link in and out of the live forward chain).  It is now a thin adapter
over :mod:`veles_tpu.serve`: the forward chain's pure functions are
extracted once into an :class:`~veles_tpu.serve.engine.InferenceEngine`
(AOT-warmed batch buckets, no steady-state recompiles) fronted by a
:class:`~veles_tpu.serve.batcher.DynamicBatcher`, so concurrent
requests coalesce into single device calls and the workflow's links
are never touched — a serving workflow keeps training undisturbed,
and requests see the live weights (the engine re-reads the forwards'
params per device call).

For standalone / multi-model / snapshot-fed serving use
:class:`veles_tpu.serve.ServingServer` directly (docs/services.md
§ Serving engine); this unit remains the one-liner for exposing a
workflow you are training right now.
"""

import numpy

from veles_tpu.units import Unit


class RESTfulAPI(Unit):
    """Serves the workflow's forward pass over HTTP."""

    def __init__(self, workflow, **kwargs):
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.port = kwargs.get("port", 0)
        self.host = kwargs.get("host", "127.0.0.1")
        self.path = kwargs.get("path", "/service")
        #: serving knobs forwarded to the engine/batcher (see
        #: docs/services.md for the full table)
        self.max_batch_size = kwargs.get("max_batch_size", 64)
        self.max_wait_ms = kwargs.get("max_wait_ms", 2.0)
        self.max_queue_rows = kwargs.get("max_queue_rows", 1024)
        self.buckets = kwargs.get("buckets")
        #: eager bucket warmup stalls initialize() for one XLA compile
        #: per bucket — the in-workflow unit defaults to lazy compiles
        #: (each bucket AOT-compiles on first use), matching the old
        #: unit's instant start; standalone ServingServer deployments
        #: default to warmup=True instead
        self.warmup = kwargs.get("warmup", False)
        #: live=True re-reads the forwards' weights per device call
        #: (serve-while-training, the old unit's semantics); pass
        #: live=False once training is done to skip the per-batch host
        #: read + device upload of the whole param tree
        self.live = kwargs.get("live", True)
        self.forwards = None     # list of forward units (linked)
        self._server_ = None
        self.demand("forwards")

    def init_unpickled(self):
        super(RESTfulAPI, self).init_unpickled()
        self._server_ = None

    @property
    def engine(self):
        """The serving engine (None before :meth:`initialize`)."""
        return (self._server_.registry.get("default").engine
                if self._server_ is not None else None)

    @property
    def metrics(self):
        return self._server_.metrics if self._server_ is not None \
            else None

    def infer(self, batch):
        """Run the forward on a host batch; returns host output.
        Pure-function path: the live units' links and state are not
        touched, so a serving workflow can keep training."""
        if self._server_ is None:
            raise RuntimeError("initialize() the unit before infer()")
        batch = numpy.ascontiguousarray(batch, dtype=numpy.float32)
        return self._server_.registry.infer("default", batch)

    def initialize(self, **kwargs):
        super(RESTfulAPI, self).initialize(**kwargs)
        if self._server_ is not None:
            return
        from veles_tpu.serve import InferenceEngine, ServingServer
        engine = InferenceEngine.from_forwards(
            self.forwards, live=self.live,
            max_batch_size=self.max_batch_size, buckets=self.buckets)
        self._server_ = ServingServer(
            engine=engine, host=self.host, port=self.port,
            path=self.path, warmup=self.warmup,
            batcher_config={"max_wait_ms": self.max_wait_ms,
                            "max_queue_rows": self.max_queue_rows})
        self._server_.start()
        self.port = self._server_.port
        self.info("REST inference on http://%s:%d%s (buckets %s)",
                  self.host, self.port, self.path,
                  list(engine.buckets))

    def stop(self):
        if self._server_ is not None:
            self._server_.stop()
            self._server_ = None
