"""UDP-multicast plot transport: lab-wide broadcast with zero deps.

Parity target: the reference binds an OpenPGM multicast endpoint on its
plot PUB socket so any number of viewers across a LAN can watch one
training run without per-viewer connections
(``veles/graphics_server.py:100-110``, ``rndepgm://`` binds
``txzmq/connection.py:589-612``).  libzmq in this image is built
without OpenPGM, so ``epgm://`` binds fail; this module provides the
same capability over plain UDP multicast from the stdlib — always
available, viewers join/leave freely, and a lost datagram loses one
plot frame, never training (the same best-effort contract PGM gave the
reference).

Endpoint syntax: ``udp://GROUP:PORT`` or ``udp://IFACE;GROUP:PORT``
(the reference's ``epgm://interface;group:port`` shape) with GROUP an
IPv4 multicast group (224.0.0.0/4) and IFACE the local address whose
interface should carry the traffic, e.g.
``udp://239.255.42.99:5005`` or ``udp://127.0.0.1;239.255.42.99:5005``.

Wire format: pickled plot frames can exceed a UDP datagram, so each
frame is chunked; every datagram is

    b"VPLT" | frame_id u32 | chunk_idx u16 | n_chunks u16 | payload

with network byte order.  The receiver reassembles per frame_id and
drops stale partial frames — exactly the drop-late-frames semantics a
live plot wants.
"""

import socket
import struct
import time

MAGIC = b"VPLT"
_HEADER = struct.Struct("!4sIHH")
#: payload per datagram; total stays under the 65507 UDP maximum and
#: within common default socket buffers
CHUNK = 60000


def parse_udp(endpoint):
    """``udp://[IFACE;]GROUP:PORT`` -> (group, port, iface_or_None);
    raises ValueError on anything else (callers fall back to other
    transports)."""
    if not endpoint.startswith("udp://"):
        raise ValueError("not a udp:// endpoint: %r" % (endpoint,))
    rest = endpoint[len("udp://"):]
    iface, sep, tail = rest.partition(";")
    if not sep:
        iface, tail = None, rest
    group, sep, port = tail.rpartition(":")
    if not sep or not group:
        raise ValueError("udp:// endpoint needs GROUP:PORT: %r"
                         % (endpoint,))
    port = int(port)
    first = int(group.split(".", 1)[0])
    if not 224 <= first <= 239:
        raise ValueError("%r is not an IPv4 multicast group" % (group,))
    return group, port, iface


class McastSender(object):
    """Chunking multicast publisher for one ``udp://`` endpoint."""

    def __init__(self, endpoint, ttl=1, loop=True, interface=None):
        self.group, self.port, ep_iface = parse_udp(endpoint)
        interface = interface or ep_iface
        self.endpoint = endpoint
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_TTL,
                              ttl)
        # loop=True lets same-host viewers (and the tests) receive
        self._sock.setsockopt(socket.IPPROTO_IP,
                              socket.IP_MULTICAST_LOOP, 1 if loop else 0)
        if interface:
            self._sock.setsockopt(
                socket.IPPROTO_IP, socket.IP_MULTICAST_IF,
                socket.inet_aton(interface))
        self._frame_id = 0

    def send(self, blob):
        """Broadcast one frame (any bytes); best-effort, never raises
        into the training loop for transient network errors."""
        self._frame_id = (self._frame_id + 1) & 0xFFFFFFFF
        n_chunks = max(1, (len(blob) + CHUNK - 1) // CHUNK)
        if n_chunks > 0xFFFF:
            raise ValueError("frame too large for the chunk header "
                             "(%d bytes)" % len(blob))
        for idx in range(n_chunks):
            part = blob[idx * CHUNK:(idx + 1) * CHUNK]
            datagram = _HEADER.pack(MAGIC, self._frame_id, idx,
                                    n_chunks) + part
            self._sock.sendto(datagram, (self.group, self.port))

    def close(self):
        self._sock.close()


class McastReceiver(object):
    """Reassembling multicast subscriber for one ``udp://`` endpoint."""

    def __init__(self, endpoint, interface=None):
        self.group, self.port, ep_iface = parse_udp(endpoint)
        interface = interface or ep_iface
        self.endpoint = endpoint
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # a burst of chunked frames (60 KB datagrams back-to-back)
        # overflows the default receive buffer — ask for 4 MB (the
        # kernel clamps to rmem_max; partial grants still help)
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                  4 << 20)
        except OSError:
            pass
        self._sock.bind(("", self.port))
        mreq = socket.inet_aton(self.group) + socket.inet_aton(
            interface or "0.0.0.0")
        self._sock.setsockopt(socket.IPPROTO_IP,
                              socket.IP_ADD_MEMBERSHIP, mreq)
        # partial frames keyed by (sender_addr, frame_id) so two
        # publishers (or a restarted one) on the same group can never
        # interleave chunks into one frame; value = (n_chunks, chunks)
        self._partial = {}
        #: bound on simultaneously-tracked partial frames — on a lossy
        #: link where frames never complete this is the memory ceiling
        #: (oldest-first eviction = drop-late-frames semantics)
        self.max_partial = 64

    def recv_frame(self, timeout=1.0):
        """Return the next complete frame's bytes, or None on timeout.
        Incomplete frames are evicted oldest-first once
        :attr:`max_partial` distinct frames are in flight (late/lost
        chunks = dropped plot, by design)."""
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                return None
            self._sock.settimeout(left)
            try:
                datagram, sender = self._sock.recvfrom(
                    CHUNK + _HEADER.size)
            except socket.timeout:
                return None
            if len(datagram) < _HEADER.size:
                continue
            magic, frame_id, idx, n_chunks = _HEADER.unpack(
                datagram[:_HEADER.size])
            if magic != MAGIC or idx >= n_chunks:
                continue
            key = (sender, frame_id)
            total, chunks = self._partial.get(key, (n_chunks, None))
            if chunks is None or total != n_chunks:
                # first chunk, or a frame_id reused with a different
                # chunk count (sender restart): start clean
                total, chunks = n_chunks, {}
                self._partial[key] = (total, chunks)
            chunks[idx] = datagram[_HEADER.size:]
            if len(chunks) == total:
                del self._partial[key]
                # GC this sender's older partials: the stream has
                # moved past them
                for stale in [k for k in self._partial
                              if k[0] == sender
                              and (frame_id - k[1]) & 0x80000000 == 0]:
                    del self._partial[stale]
                return b"".join(chunks[i] for i in range(total))
            while len(self._partial) > self.max_partial:
                del self._partial[next(iter(self._partial))]

    def close(self):
        try:
            mreq = socket.inet_aton(self.group) + socket.inet_aton(
                "0.0.0.0")
            self._sock.setsockopt(socket.IPPROTO_IP,
                                  socket.IP_DROP_MEMBERSHIP, mreq)
        except OSError:
            pass
        self._sock.close()
