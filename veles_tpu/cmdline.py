"""Distributed argument registry (ref ``veles/cmdline.py:61-232``).

The reference lets any class with the ``CommandLineArgumentsRegistry``
metaclass contribute an ``init_parser`` to one global argparse parser, so
the CLI surface is assembled from the components that are actually in the
process.  We keep that shape: components call :func:`register_arguments`
(or use the :class:`CommandLineArgumentsRegistry` metaclass and define a
static ``init_parser(parser)``), and :func:`make_parser` folds every
contribution into one parser.
"""

import argparse

#: Registered contributor callables ``f(parser) -> None``.
_CONTRIBUTORS = []
_SEEN = set()


def register_arguments(contributor):
    """Register an ``init_parser``-style contributor (idempotent)."""
    key = (getattr(contributor, "__module__", ""),
           getattr(contributor, "__qualname__", None) or id(contributor))
    if key in _SEEN:
        return contributor
    _SEEN.add(key)
    _CONTRIBUTORS.append(contributor)
    return contributor


class CommandLineArgumentsRegistry(type):
    """Metaclass mirror of ``cmdline.py:61``: classes defining
    ``init_parser(parser)`` auto-contribute it at class-creation time."""

    def __init__(cls, name, bases, namespace):
        super(CommandLineArgumentsRegistry, cls).__init__(
            name, bases, namespace)
        init_parser = namespace.get("init_parser")
        if init_parser is not None:
            fn = init_parser.__func__ if isinstance(
                init_parser, staticmethod) else init_parser
            register_arguments(fn)


def make_parser(prog="veles_tpu", description=None):
    """Build the composite parser: core args + every registered
    contributor (ref ``cmdline.py:125-232``)."""
    parser = argparse.ArgumentParser(
        prog=prog,
        description=description or
        "TPU-native VELES: run a workflow standalone, as master, or as "
        "slave.",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument(
        "workflow", nargs="?", default=None,
        help="workflow python file or dotted module "
             "(e.g. veles_tpu.samples.mnist)")
    parser.add_argument(
        "config", nargs="?", default=None,
        help="optional config python file exec'd against root.*")
    parser.add_argument(
        "overrides", nargs="*", default=[], metavar="key=value",
        help="dotted root.* config overrides, JSON-parsed values")
    parser.add_argument(
        "-v", "--verbosity", default="info",
        choices=["debug", "info", "warning", "error"],
        help="log level")
    parser.add_argument(
        "--debug", default="", metavar="CLASS,...",
        help="comma-separated class names forced to DEBUG "
             "(ref __main__.py:833-835)")
    parser.add_argument(
        "--log-db", default="", metavar="PATH",
        help="duplicate every log record into a TTL-expired SQLite DB "
             "at PATH (the reference's --log-mongo duplication, "
             "logger.py:292, without the database dependency)")
    parser.add_argument(
        "--version", action="store_true",
        help="print version and backend info, then exit "
             "(ref cmdline.py:143)")
    parser.add_argument(
        "--no-logo", action="store_true",
        help="do not print the version banner at startup "
             "(ref cmdline.py:139)")
    parser.add_argument(
        "--dump-config", action="store_true",
        help="print the initial global configuration after applying "
             "the config file and overrides (ref cmdline.py:169)")
    parser.add_argument(
        "--dump-unit-attributes", default="no",
        choices=["no", "pretty", "all"],
        help="print unit __dict__-s after workflow initialization; "
             "\"pretty\" elides large arrays (ref cmdline.py:207)")
    parser.add_argument(
        "--visualize", action="store_true",
        help="initialize but do not run; write the workflow graph "
             "next to the snapshot dir and start the plotting "
             "endpoint (ref cmdline.py:178)")
    parser.add_argument(
        "-b", "--background", action="store_true",
        help="detach and run as a daemon (ref cmdline.py:228)")
    parser.add_argument(
        "--debug-pickle", action="store_true",
        help="on a failed snapshot pickle, walk the workflow and name "
             "the offending attribute (ref cmdline.py:158)")
    parser.add_argument(
        "--html-help", action="store_true",
        help="write the full argument reference as an HTML page and "
             "print its path (the reference opened it in a browser; "
             "this image is headless — ref cmdline.py:146)")
    parser.add_argument(
        "-r", "--random-seed", default=None,
        help="seed for the named PRNG streams (int, or path[:dtype:count] "
             "to a seed file; ref prng/random_generator.py:106)")
    parser.add_argument(
        "-w", "--snapshot", default="",
        help="resume from a snapshot file (ref __main__.py:539-590)")
    parser.add_argument(
        "--test", action="store_true",
        help="run in evaluation mode instead of training")
    parser.add_argument(
        "--fused", action="store_true",
        help="train through the fused lowering: one XLA program per "
             "minibatch (StandardWorkflow(fused=True); standalone/SPMD "
             "modes)")
    parser.add_argument(
        "--result-file", default="",
        help="write gathered IResultProvider results JSON here "
             "(ref workflow.py:827-851)")
    parser.add_argument(
        "--dry-run", default="", choices=["", "load", "init"],
        help="load: parse args + apply config, stop before "
             "constructing the workflow; init: construct + initialize "
             "the workflow, then exit without training "
             "(ref cmdline.py:172 choices no/load/init/exec)")
    parser.add_argument(
        "--workflow-graph", default="",
        help="write the unit graph in DOT format to this path "
             "(ref workflow.py:628)")
    parser.add_argument(
        "--optimize", default="", metavar="SIZE[:GENERATIONS]",
        help="genetic hyperparameter optimization over config Tuneables "
             "(ref cmdline.py:183-190)")
    parser.add_argument(
        "--ensemble-train", default="", metavar="N:RATIO",
        help="train an ensemble of N models on RATIO-sized train subsets")
    parser.add_argument(
        "--ensemble-test", default="", metavar="INPUT_JSON",
        help="evaluate a trained ensemble listed in INPUT_JSON")
    parser.add_argument(
        "--manhole", action="store_true",
        help="arm the debug backdoor: SIGUSR1 dumps all thread stacks, "
             "SIGUSR2 serves a REPL on a UNIX socket (attach with "
             "python -m veles_tpu.manhole <pid>; ref --manhole "
             "thread_pool.py:139)")
    parser.add_argument(
        "--debug-nans", action="store_true",
        help="enable jax_debug_nans: any NaN produced on device raises "
             "at the emitting op (SURVEY §5.2's TPU 'sanitizer' — jit "
             "purity makes data races moot; NaNs are what's left)")
    parser.add_argument(
        "--profile", default="", metavar="TRACE_DIR",
        help="record a jax.profiler trace of the run into TRACE_DIR "
             "(view with TensorBoard / xprof; SURVEY §5.1 TPU "
             "equivalent of per-unit timing)")
    parser.add_argument(
        "--frontend", default="", metavar="OUT_HTML",
        help="generate the HTML command-composer form from the argument "
             "registry and exit (ref scripts/generate_frontend.py)")
    for contribute in list(_CONTRIBUTORS):
        contribute(parser)
    return parser
