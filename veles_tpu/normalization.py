"""Pluggable, stateful, pickleable feature normalizers.

Parity target: reference ``veles/normalization.py`` — registry at ``:110``,
classes ``:260-660``; the type set is enumerated in
``docs/manualrst_veles_workflow_parameters.rst:245-259``: ``none``,
``linear``, ``range_linear``, ``mean_disp``, ``exp``, ``pointwise``,
``external_mean``, ``internal_mean``.

The contract: ``analyze(data)`` is streamed over the TRAIN set once to
accumulate statistics; ``normalize(data)`` edits a host batch in place;
``state`` is a picklable dict so a derived loader (or an inference
package) can reuse statistics without the dataset.
"""

import numpy


class NormalizerRegistry(type):
    """MAPPING name → class (ref ``normalization.py:110``)."""

    normalizers = {}

    def __init__(cls, name, bases, namespace):
        super(NormalizerRegistry, cls).__init__(name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if mapping:
            NormalizerRegistry.normalizers[mapping] = cls


def normalizer_factory(name, **kwargs):
    try:
        klass = NormalizerRegistry.normalizers[name]
    except KeyError:
        raise ValueError(
            "unknown normalization type %r (have: %s)" %
            (name, ", ".join(sorted(NormalizerRegistry.normalizers))))
    return klass(**kwargs)


class NormalizerBase(object, metaclass=NormalizerRegistry):
    MAPPING = None

    def __init__(self, **kwargs):
        self.reset()

    @property
    def is_initialized(self):
        return self._initialized

    @property
    def state(self):
        """Picklable statistics dict; assignable (ref normalizer.state)."""
        return {k: v for k, v in self.__dict__.items()}

    @state.setter
    def state(self, value):
        self.__dict__.update(value)

    def reset(self):
        self._initialized = False

    def analyze(self, data):
        self._initialized = True

    def normalize(self, data):
        raise NotImplementedError

    def denormalize(self, data):
        raise NotImplementedError

    def as_affine(self):
        """``(scale, shift)`` such that ``normalize(x) == x*scale +
        shift`` for every sample, or None when this normalizer is not a
        sample-independent affine map (per-sample linear, exp, ...).

        Affine normalizers can be FUSED into a jitted train step
        (``fused_graph.lower_specs input_norm``) so the dataset stays
        device-resident in its native storage dtype — see
        ``FullBatchLoader(native_device_dtype=True)``.  scale/shift may
        be scalars or flat per-feature arrays.
        """
        return None

    def _require(self):
        if not self._initialized:
            raise RuntimeError(
                "%s used before analyze()" % type(self).__name__)


class StatelessNormalizer(NormalizerBase):
    """No statistics needed (ref ``normalization.py:260``)."""

    @property
    def is_initialized(self):
        return True

    def analyze(self, data):
        self._initialized = True


class NoneNormalizer(StatelessNormalizer):
    """Identity (ref ``:496``)."""

    MAPPING = "none"

    def normalize(self, data):
        pass

    def denormalize(self, data):
        pass

    def as_affine(self):
        return (1.0, 0.0)


class ScaleNormalizer(StatelessNormalizer):
    """Fixed multiplicative scale (e.g. ``1/255`` for byte images).

    The affine form feeds ``FullBatchLoader(native_device_dtype=True)``
    exactly: u8 pixels stay resident, the fused step multiplies
    in-program, and the trajectory is bit-identical to pre-scaled
    float32 data."""

    MAPPING = "scale"

    def __init__(self, scale=1.0 / 255.0, **kwargs):
        self.scale = float(scale)
        super(ScaleNormalizer, self).__init__(**kwargs)

    def normalize(self, data):
        data *= self.scale

    def denormalize(self, data):
        data /= self.scale

    def as_affine(self):
        return (self.scale, 0.0)


class LinearNormalizer(StatelessNormalizer):
    """Per-sample scale into [interval] by that sample's min/max
    (ref ``:347``)."""

    MAPPING = "linear"

    def __init__(self, interval=(-1, 1), **kwargs):
        self.interval = tuple(interval)
        super(LinearNormalizer, self).__init__(**kwargs)

    def normalize(self, data):
        lo, hi = self.interval
        flat = data.reshape(len(data), -1)
        dmin = flat.min(axis=1, keepdims=True)
        dmax = flat.max(axis=1, keepdims=True)
        span = numpy.where(dmax > dmin, dmax - dmin, 1)
        flat[...] = (flat - dmin) / span * (hi - lo) + lo

    def denormalize(self, data):
        raise NotImplementedError(
            "per-sample linear normalization is not invertible without the "
            "original min/max")


class RangeLinearNormalizer(NormalizerBase):
    """Global min/max over TRAIN → scale into [interval] (ref ``:398``)."""

    MAPPING = "range_linear"

    def __init__(self, interval=(-1, 1), **kwargs):
        self.interval = tuple(interval)
        super(RangeLinearNormalizer, self).__init__(**kwargs)

    def reset(self):
        super(RangeLinearNormalizer, self).reset()
        self.gmin = None
        self.gmax = None

    def analyze(self, data):
        dmin, dmax = float(data.min()), float(data.max())
        self.gmin = dmin if self.gmin is None else min(self.gmin, dmin)
        self.gmax = dmax if self.gmax is None else max(self.gmax, dmax)
        super(RangeLinearNormalizer, self).analyze(data)

    def normalize(self, data):
        self._require()
        lo, hi = self.interval
        span = (self.gmax - self.gmin) or 1.0
        data[...] = (data - self.gmin) / span * (hi - lo) + lo

    def denormalize(self, data):
        self._require()
        lo, hi = self.interval
        span = (self.gmax - self.gmin) or 1.0
        data[...] = (data - lo) / (hi - lo) * span + self.gmin

    def as_affine(self):
        self._require()
        lo, hi = self.interval
        scale = (hi - lo) / ((self.gmax - self.gmin) or 1.0)
        return (scale, lo - self.gmin * scale)


class MeanDispersionNormalizer(NormalizerBase):
    """Per-feature ``(x - mean) / (max - min)`` accumulated over TRAIN
    (ref ``:284``); the device-side consumer is
    :func:`veles_tpu.ops.normalize.mean_disp_normalize`."""

    MAPPING = "mean_disp"

    def reset(self):
        super(MeanDispersionNormalizer, self).reset()
        self._sum = None
        self._count = 0
        self._min = None
        self._max = None

    def analyze(self, data):
        batch = data.reshape(len(data), -1).astype(numpy.float64)
        if self._sum is None:
            self._sum = batch.sum(axis=0)
            self._min = batch.min(axis=0)
            self._max = batch.max(axis=0)
        else:
            self._sum += batch.sum(axis=0)
            self._min = numpy.minimum(self._min, batch.min(axis=0))
            self._max = numpy.maximum(self._max, batch.max(axis=0))
        self._count += len(batch)
        super(MeanDispersionNormalizer, self).analyze(data)

    @property
    def mean(self):
        self._require()
        return (self._sum / max(self._count, 1)).astype(numpy.float32)

    @property
    def disp(self):
        """Reciprocal dispersion multiplier (ref multiplies by it)."""
        self._require()
        span = self._max - self._min
        return (1.0 / numpy.where(span > 0, span, 1)).astype(numpy.float32)

    def normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat[...] = (flat - self.mean) * self.disp

    def denormalize(self, data):
        flat = data.reshape(len(data), -1)
        flat[...] = flat / self.disp + self.mean

    def as_affine(self):
        disp = self.disp
        return (disp, -self.mean * disp)


class ExponentNormalizer(StatelessNormalizer):
    """Per-sample softmax-style squash: exp(x - max) / sum (ref ``:467``)."""

    MAPPING = "exp"

    def normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= flat.max(axis=1, keepdims=True)
        numpy.exp(flat, out=flat)
        flat /= flat.sum(axis=1, keepdims=True)

    def denormalize(self, data):
        raise NotImplementedError("exp normalization is not invertible")


class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map fit from TRAIN min/max into [-1, 1]
    (ref ``:511``)."""

    MAPPING = "pointwise"

    def reset(self):
        super(PointwiseNormalizer, self).reset()
        self._min = None
        self._max = None

    def analyze(self, data):
        batch = data.reshape(len(data), -1)
        bmin = batch.min(axis=0)
        bmax = batch.max(axis=0)
        if self._min is None:
            self._min, self._max = bmin.copy(), bmax.copy()
        else:
            self._min = numpy.minimum(self._min, bmin)
            self._max = numpy.maximum(self._max, bmax)
        super(PointwiseNormalizer, self).analyze(data)

    def _coeffs(self):
        span = self._max - self._min
        mul = numpy.where(span > 0, 2.0 / numpy.where(span > 0, span, 1), 0)
        add = numpy.where(span > 0, -1.0 - self._min * mul, 0)
        return mul, add

    def normalize(self, data):
        self._require()
        mul, add = self._coeffs()
        flat = data.reshape(len(data), -1)
        flat[...] = flat * mul + add

    def denormalize(self, data):
        self._require()
        mul, add = self._coeffs()
        flat = data.reshape(len(data), -1)
        flat[...] = (flat - add) / numpy.where(mul != 0, mul, 1)

    def as_affine(self):
        self._require()
        return self._coeffs()


class ExternalMeanNormalizer(StatelessNormalizer):
    """Subtract a user-supplied mean array (ref ``:593``)."""

    MAPPING = "external_mean"

    def __init__(self, mean_source=None, scale=1.0, **kwargs):
        if mean_source is None:
            raise ValueError("external_mean requires mean_source")
        if isinstance(mean_source, str):
            mean_source = numpy.load(mean_source)
        self.mean = numpy.asarray(mean_source, dtype=numpy.float32)
        self.scale = scale
        super(ExternalMeanNormalizer, self).__init__(**kwargs)

    def normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= self.mean.reshape(1, -1)
        if self.scale != 1.0:
            flat *= self.scale

    def denormalize(self, data):
        flat = data.reshape(len(data), -1)
        if self.scale != 1.0:
            flat /= self.scale
        flat += self.mean.reshape(1, -1)

    def as_affine(self):
        return (float(self.scale),
                -self.mean.reshape(-1) * float(self.scale))


class InternalMeanNormalizer(NormalizerBase):
    """Subtract the TRAIN-set mean (ref ``:636``)."""

    MAPPING = "internal_mean"

    def reset(self):
        super(InternalMeanNormalizer, self).reset()
        self._sum = None
        self._count = 0

    def analyze(self, data):
        batch = data.reshape(len(data), -1).astype(numpy.float64)
        if self._sum is None:
            self._sum = batch.sum(axis=0)
        else:
            self._sum += batch.sum(axis=0)
        self._count += len(batch)
        super(InternalMeanNormalizer, self).analyze(data)

    @property
    def mean(self):
        self._require()
        return (self._sum / max(self._count, 1)).astype(numpy.float32)

    def normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= self.mean

    def denormalize(self, data):
        flat = data.reshape(len(data), -1)
        flat += self.mean

    def as_affine(self):
        return (1.0, -self.mean.reshape(-1))
