"""``python -m veles_tpu <workflow> [<config>] [key=value ...]`` — the
framework entry point (ref ``veles/__main__.py:136-859``).

Call sequence mirrors SURVEY §3.1: parse args → seed named PRNGs →
load workflow module (file, dotted module, or snapshot) → exec config
file against ``root.*`` → apply ``key=value`` overrides → construct
Launcher + workflow → initialize → run.

Workflow module conventions supported:

- ``run(load, main)`` — the reference convention
  (``__main__.py:716-799``): the module calls ``load(WorkflowClass,
  **kwargs)`` to construct and ``main(**kwargs)`` to initialize+run.
- ``create_workflow(device=..., **kwargs) -> workflow`` — the native
  convention used by :mod:`veles_tpu.samples`.
"""

import importlib
import importlib.util
import logging
import os
import runpy
import sys

from veles_tpu import prng
from veles_tpu.cmdline import make_parser
from veles_tpu.config import (
    apply_site_config, root, update_from_arguments)
from veles_tpu.launcher import Launcher
from veles_tpu.logger import Logger


_peak_printer_registered = False


class Main(Logger):
    """One CLI invocation (ref ``Main`` ``__main__.py:136``)."""

    def __init__(self, argv=None):
        super(Main, self).__init__()
        self.argv = list(sys.argv[1:] if argv is None else argv)
        self.args = None
        self.launcher = None
        self.workflow = None
        self.module = None

    # -- setup --------------------------------------------------------------
    def _parse(self):
        parser = make_parser()
        args, extra = parser.parse_known_args(self.argv)
        # argparse puts stray key=value positionals into `extra` or
        # `config`; sort them out (ref __main__.py:474-482).
        overrides = list(args.overrides)
        for item in extra:
            if "=" in item and not item.startswith("-"):
                overrides.append(item)
            else:
                parser.error("unrecognized argument: %s" % item)
        if args.config and "=" in args.config and \
                not os.path.exists(args.config):
            overrides.insert(0, args.config)
            args.config = None
        args.overrides = overrides
        self.args = args
        return args

    def _setup_logging(self):
        level = getattr(logging, self.args.verbosity.upper())
        logging.basicConfig(level=level)
        logging.getLogger().setLevel(level)
        for name in filter(None, self.args.debug.split(",")):
            logging.getLogger(name).setLevel(logging.DEBUG)
        if getattr(self.args, "log_db", ""):
            from veles_tpu.logger import duplicate_logs_to_db
            self.log_db_handler = duplicate_logs_to_db(self.args.log_db)

    def _seed_random(self):
        """Seed every named stream (ref ``__main__.py:483-538``)."""
        spec = self.args.random_seed
        if spec is None:
            prng.seed_all(1234)
            return
        try:
            prng.seed_all(int(spec))
            return
        except ValueError:
            pass
        # path[:dtype[:count]] — read seed bytes from a file
        # (ref random_generator.py:106: /dev/urandom support).
        parts = spec.split(":")
        path, dtype, count = (
            parts[0],
            parts[1] if len(parts) > 1 else "uint32",
            int(parts[2]) if len(parts) > 2 else 16)
        import numpy
        with open(path, "rb") as fin:
            raw = numpy.frombuffer(
                fin.read(numpy.dtype(dtype).itemsize * count),
                dtype=dtype, count=count)
        prng.seed_all(int(numpy.sum(raw.astype(numpy.uint64)) %
                          (2 ** 31)))

    def _apply_config(self):
        """Exec the config file then CLI overrides against ``root``
        (ref ``__main__.py:426-482``)."""
        apply_site_config()
        if self.args.config:
            with open(self.args.config, "r") as fin:
                code = compile(fin.read(), self.args.config, "exec")
            exec(code, {"root": root})
        if self.args.overrides:
            update_from_arguments(self.args.overrides)

    # -- model loading ------------------------------------------------------
    def _load_module(self, spec):
        """Import a workflow module from a file path or dotted name
        (ref ``_load_model`` ``__main__.py:396-425``)."""
        if os.path.exists(spec):
            name = os.path.splitext(os.path.basename(spec))[0]
            modspec = importlib.util.spec_from_file_location(name, spec)
            module = importlib.util.module_from_spec(modspec)
            sys.modules[name] = module
            modspec.loader.exec_module(module)
            return module
        return importlib.import_module(spec)

    def _construct(self):
        """Build launcher + workflow from the module or a snapshot."""
        launcher_kwargs = {
            "listen": self.args.listen,
            "master_address": self.args.master_address,
            "device": self.args.device,
            "testing": self.args.test,
            "graphics": self.args.graphics,
            "web_status": self.args.web_status,
            "checkpoint_dir": getattr(self.args, "checkpoint_dir",
                                      None),
            "checkpoint_every": getattr(self.args, "checkpoint_every",
                                        None),
            "resume": getattr(self.args, "resume", False),
        }
        if self.args.snapshot:
            from veles_tpu.snapshotter import load_snapshot
            self.workflow = load_snapshot(self.args.snapshot)
            self.launcher = Launcher(self.workflow, **launcher_kwargs)
            self.info("resumed workflow from %s", self.args.snapshot)
            return
        if not self.args.workflow:
            raise SystemExit("no workflow given (and no --snapshot)")
        self.module = self._load_module(self.args.workflow)
        if hasattr(self.module, "run"):
            self._construct_via_run(launcher_kwargs)
        elif hasattr(self.module, "create_workflow"):
            self.launcher = Launcher(**launcher_kwargs)
            extra = {"fused": True} if self.args.fused else {}
            self.workflow = self.module.create_workflow(
                launcher=self.launcher, **extra)
            if self.workflow.launcher is not self.launcher:
                self.workflow.launcher = self.launcher
        else:
            raise SystemExit(
                "workflow module %r defines neither run(load, main) nor "
                "create_workflow(...)" % self.args.workflow)

    def _construct_via_run(self, launcher_kwargs):
        """The reference convention: module.run(load, main)
        (``__main__.py:591-715``)."""
        main_self = self

        def load(workflow_class, **kwargs):
            main_self.launcher = Launcher(**launcher_kwargs)
            if main_self.args.fused:
                # explicit opt-in only: non-StandardWorkflow classes
                # need not accept the kwarg
                kwargs.setdefault("fused", True)
            main_self.workflow = workflow_class(
                main_self.launcher, **kwargs)
            return main_self.workflow, None

        def main(**kwargs):
            if main_self.args.analyze:
                return    # pre-flight wants the constructed graph only
            main_self.launcher.initialize(**kwargs)
            if not main_self.args.dry_run:
                main_self.launcher.run()

        self.module.run(load, main)

    @staticmethod
    def print_peak_memory():
        """Peak RSS line, registered atexit (ref startup step 7:
        'Peak memory usage printer is registered on program exit')."""
        import resource
        peak_kib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        print("Peak resident memory: %.1f MiB" % (peak_kib / 1024.0),
              file=sys.stderr)

    @staticmethod
    def _version_line():
        import jax

        import veles_tpu
        return "veles_tpu %s (jax %s, %s)" % (
            veles_tpu.__version__, jax.__version__,
            "python %d.%d" % sys.version_info[:2])

    def _dump_unit_attributes(self, mode):
        """Print every unit's __dict__ after initialization
        (ref ``--dump-unit-attributes``); ``pretty`` elides arrays."""
        import numpy

        for unit in self.workflow:
            attrs = {}
            for key, value in sorted(vars(unit).items()):
                if key.endswith("_"):
                    continue
                if mode == "pretty" and isinstance(
                        value, numpy.ndarray) and value.size > 16:
                    value = "<%s array %s>" % (value.dtype,
                                               "x".join(map(
                                                   str, value.shape)))
                attrs[key] = value
            print("%s: %s" % (unit.name or type(unit).__name__, attrs))

    def _daemonize(self):
        """Double-fork into the background (ref ``-b``)."""
        if os.fork() > 0:
            os._exit(0)
        os.setsid()
        if os.fork() > 0:
            os._exit(0)
        devnull = os.open(os.devnull, os.O_RDWR)
        for fd in (0, 1):
            os.dup2(devnull, fd)
        # keep stderr: logging still reaches the launch terminal's
        # redirect target if any; daemons should pair this with
        # --log-db for durable records
        self.info("daemonized (pid %d)", os.getpid())

    # -- run ----------------------------------------------------------------
    def run(self):
        args = self._parse()
        if args.version:
            print(self._version_line())
            return 0
        if args.html_help:
            import tempfile

            from veles_tpu.scripts.generate_frontend import generate
            fd, path = tempfile.mkstemp(suffix=".html",
                                        prefix="veles_tpu_help_")
            with os.fdopen(fd, "w") as fout:
                fout.write(generate())
            print("argument reference written to %s" % path)
            return 0
        if not args.no_logo:
            print(self._version_line(), file=sys.stderr)
        global _peak_printer_registered
        if not _peak_printer_registered:
            _peak_printer_registered = True
            import atexit
            atexit.register(self.print_peak_memory)
        if args.background:
            self._daemonize()
        if args.visualize and not args.dry_run:
            # "initialize but do not run" must hold for BOTH workflow
            # conventions: run(load, main) modules consult dry_run
            # inside main(), so set it rather than special-casing
            args.dry_run = "init"
        if args.device in ("numpy", "cpu"):
            # a CPU-only run must not touch the TPU: a sitecustomize may
            # pin a tunnel platform behind JAX_PLATFORMS' back, and
            # backend init would then block on unreachable hardware
            try:
                import jax
                if jax.config.jax_platforms != "cpu":
                    jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        self._setup_logging()
        if args.manhole:
            from veles_tpu import manhole
            manhole.install(namespace={"main": self})
            self.info("manhole armed: SIGUSR1 dumps stacks, SIGUSR2 "
                      "serves a REPL (pid %d)", os.getpid())
        if args.debug_nans:
            import jax
            jax.config.update("jax_debug_nans", True)
            self.info("NaN checking enabled (jax_debug_nans)")
        if args.debug_pickle:
            from veles_tpu import snapshotter
            snapshotter.DEBUG_PICKLE = True
            self.info("pickle diagnostics enabled")
        self._seed_random()
        self._apply_config()
        # config may carry a seed (e.g. ensemble members get distinct
        # streams via common.engine.seed); CLI --random-seed wins
        cfg_seed = root.common.engine.get("seed", None)
        if cfg_seed is not None and args.random_seed is None:
            prng.seed_all(int(cfg_seed))
        if args.dump_config:
            root.print_()
        if args.dry_run == "load":
            self.info("dry run (load) complete")
            return 0
        if args.frontend:
            from veles_tpu.scripts.generate_frontend import generate
            with open(args.frontend, "w") as fout:
                fout.write(generate())
            self.info("wrote frontend form to %s", args.frontend)
            return 0
        if args.optimize:
            return self._run_optimization()
        if args.ensemble_train or args.ensemble_test:
            return self._run_ensemble()
        if args.profile:
            # device-level tracing around the whole run (the per-unit
            # wall-time table remains in Workflow.print_stats)
            import jax.profiler
            jax.profiler.start_trace(args.profile)
            self.info("jax.profiler trace → %s", args.profile)
        try:
            return self._run_constructed(args)
        finally:
            if args.profile:
                import jax.profiler
                jax.profiler.stop_trace()
                self.info("profiler trace written to %s", args.profile)

    def _run_constructed(self, args):
        self._construct()
        if args.analyze:
            if self.workflow is None:
                raise SystemExit("--analyze: no workflow constructed")
            from veles_tpu.analyze import analyze_workflow
            report = analyze_workflow(self.workflow)
            print(report.render_text())
            return 1 if report.has_errors else 0
        if args.result_file:
            self.workflow.result_file = args.result_file
        if self.workflow is not None and \
                not getattr(self.workflow, "_is_initialized", False) \
                and self.launcher is not None:
            self.launcher.initialize()
        if args.workflow_graph and self.workflow is not None:
            with open(args.workflow_graph, "w") as fout:
                fout.write(self.workflow.generate_graph())
            self.info("wrote workflow graph to %s", args.workflow_graph)
        if args.dump_unit_attributes != "no" and \
                self.workflow is not None:
            self._dump_unit_attributes(args.dump_unit_attributes)
        if args.visualize and self.workflow is not None:
            # initialize-only + graph written into the snapshots dir
            # (the documented location); plotting endpoints only live
            # as long as a process, so the advice is a fixed port —
            # not a reattach promise that would dangle
            out_dir = root.common.dirs.get("snapshots", ".")
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, "workflow_graph.dot")
            with open(path, "w") as fout:
                fout.write(self.workflow.generate_graph())
            self.info(
                "visualize: graph at %s — not running.  For live "
                "plots, run WITHOUT --visualize and attach "
                "graphics_client to the GraphicsServer endpoint "
                "printed at startup (pin it with "
                "root.common.graphics.port)", path)
            return 0
        if args.dry_run:
            self.info("dry run (%s) complete", args.dry_run)
            return 0
        if self.module is None or not hasattr(self.module, "run"):
            # run() convention already ran inside _construct_via_run
            self.launcher.run()
        if args.result_file and self.workflow is not None:
            self.workflow.write_results(args.result_file)
        return 0

    def _run_optimization(self):
        """--optimize SIZE[:GENERATIONS] (ref ``__main__.py:334``)."""
        try:
            from veles_tpu.genetics import GeneticsOptimizer
        except ImportError:
            raise SystemExit(
                "--optimize requires veles_tpu.genetics")
        # tuneable Range/Choice markers may live at module level in the
        # workflow itself (the reference's GeneticExample pattern):
        # import it so the scan sees them — harmless when the markers
        # come from the config file instead
        try:
            self._load_module(self.args.workflow)
        except Exception:
            self.warning("could not pre-import %r for the tuneable "
                         "scan; relying on the config file",
                         self.args.workflow)
        size, _, generations = self.args.optimize.partition(":")
        optimizer = GeneticsOptimizer(
            workflow_spec=self.args.workflow,
            config_file=self.args.config,
            population_size=int(size),
            generations=int(generations) if generations else None,
            result_file=self.args.result_file or None,
            extra_args=self._child_args())
        best = optimizer.run()
        self.info("best config: %s fitness=%s", best.config_overrides,
                  best.fitness)
        return 0

    def _child_args(self):
        """CLI args every spawned child run (GA member, ensemble
        member) must inherit: the device, --fused, and the parent's
        key=value overrides — a child evaluating a config the user
        never asked for would silently skew the search."""
        extra = []
        if getattr(self.args, "device", None):
            extra += ["-d", self.args.device]
        if self.args.fused:
            extra.append("--fused")
        extra += list(self.args.overrides)
        return extra

    def _run_ensemble(self):
        try:
            from veles_tpu.ensemble import (
                EnsembleModelManager, EnsembleTestManager)
        except ImportError:
            raise SystemExit(
                "--ensemble-* requires veles_tpu.ensemble")
        if self.args.ensemble_train:
            n, _, ratio = self.args.ensemble_train.partition(":")
            manager = EnsembleModelManager(
                workflow_spec=self.args.workflow,
                config_file=self.args.config,
                size=int(n), train_ratio=float(ratio or 1.0),
                result_file=self.args.result_file or None,
                extra_args=self._child_args())
        else:
            manager = EnsembleTestManager(
                workflow_spec=self.args.workflow,
                config_file=self.args.config,
                input_file=self.args.ensemble_test,
                result_file=self.args.result_file or None,
                extra_args=self._child_args())
        manager.run()
        return 0


def __run__():
    sys.exit(Main().run())


if __name__ == "__main__":
    __run__()
