"""veles_tpu.serve — dynamic-batching, AOT-compiled model serving.

The inference half of the platform (the reference ships a dedicated
runtime, libVeles, separate from the training core; SURVEY §0/§2.8).
Pieces, each its own module:

- :mod:`engine` — :class:`InferenceEngine`: the trained workflow's pure
  forward (via ``fused_graph.lower_specs`` or the forward-unit chain),
  params device-resident, a small set of power-of-two batch buckets
  AOT-compiled up front so steady-state serving never recompiles.
- :mod:`batcher` — :class:`DynamicBatcher`: coalesces concurrent
  requests into one padded device call (``max_batch_size`` /
  ``max_wait_ms``), fans results back out via per-request futures, and
  sheds load (:class:`QueueFull` → HTTP 503) instead of stalling.
- :mod:`registry` — :class:`ModelRegistry`: multiple named models,
  versions loaded from :mod:`veles_tpu.snapshotter` files, hot-swapped
  atomically (in-flight batches finish on the old version).
- :mod:`server` — :class:`ServingServer`: threaded HTTP front-end with
  the classic ``POST /service {"input": ...} → {"result": ...}`` wire
  contract plus ``/healthz`` and a text ``/metrics`` endpoint.
- :mod:`metrics` — :class:`ServingMetrics`: QPS, queue depth,
  batch-fill ratio and latency percentiles, also publishable to the
  existing :mod:`veles_tpu.web_status` service.
- :mod:`wire` — request decoding (JSON lists or base64 numpy).

``veles_tpu.restful_api.RESTfulAPI`` is a thin in-workflow adapter over
these parts; new deployments should drive :class:`ServingServer`
directly (see ``docs/services.md`` § Serving engine).
"""

from veles_tpu.serve.batcher import DynamicBatcher, QueueFull
from veles_tpu.serve.engine import InferenceEngine
from veles_tpu.serve.metrics import ServingMetrics
from veles_tpu.serve.registry import ModelRegistry, ReplicaSet
from veles_tpu.serve.server import ServingServer
from veles_tpu.serve.wire import decode_gen_request, decode_input

__all__ = [
    "DynamicBatcher", "InferenceEngine", "ModelRegistry", "QueueFull",
    "ReplicaSet", "ServingMetrics", "ServingServer",
    "decode_gen_request", "decode_input",
]
