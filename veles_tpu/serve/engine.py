"""InferenceEngine: a trained model's pure forward, AOT-warmed.

The serving counterpart of :func:`veles_tpu.znicz.fused_graph
.lower_specs`: the training side lowers a workflow to one jitted train
step; here the same lowering's ``apply_fn`` (or, for workflows built
without layer specs, the forward units' own pure functions) becomes the
single device call the platform serves from.

Three properties production serving needs that the in-workflow
``RESTfulAPI.infer`` critical section could not give:

1. **Pure + reentrant** — no link swapping, no unit state, so any
   number of batcher threads may hold a reference while a hot-swap
   installs a successor engine.
2. **Params device-resident** — weights are ``jax.device_put`` once at
   construction, not re-uploaded per request.
3. **No steady-state compiles** — a small set of power-of-two batch
   *buckets* is AOT-compiled by :meth:`warmup` (``jit.lower(...)
   .compile()``); every request batch is padded up to the nearest
   bucket, so XLA sees only shapes it has already compiled.
   :attr:`compile_count` exposes the exact number of compiles for
   monitoring (and the no-recompile-after-warmup test gate).

Bucket padding is value-safe for inference graphs: every serving
forward here is row-independent (dense/conv/activation/softmax are
per-sample; LRN normalizes across channels, not batch; dropout-style
units declare ``SKIP_AT_EVAL``), so the padded rows cannot bleed into
real rows and the sliced result is byte-identical to the un-batched
forward — asserted in ``tests/test_serve.py``.
"""

import itertools
import threading
import time

import numpy

from veles_tpu import prof, trace
from veles_tpu.logger import Logger

#: per-process engine sequence for performance-ledger entry names
_ENGINE_SEQ = itertools.count()


def infer_sample_shape(workflow, forwards):
    """One sample's shape from the first forward's ``input`` or the
    loader's minibatch buffer; ``None`` when neither is declared.
    Shared with the static analyzer (:mod:`veles_tpu.analyze.shapes`)
    so serving and analysis agree on the chain's entry shape."""
    first = forwards[0] if forwards else None
    inp = getattr(first, "input", None)
    shape = getattr(inp, "shape", None)
    if shape and len(shape) > 1:
        return tuple(shape[1:])
    loader = getattr(workflow, "loader", None)
    data = getattr(loader, "minibatch_data", None)
    shape = getattr(data, "shape", None)
    if shape and len(shape) > 1:
        return tuple(shape[1:])
    return None


def forward_stages(forwards):
    """Validate the pure-function protocol over a forward chain and
    return its stages: ``[(pure_fn, static_config, skip_at_eval)]``.

    The single definition of "servable" — :meth:`InferenceEngine
    .from_forwards` builds its device call from these stages, and the
    static analyzer (:mod:`veles_tpu.analyze.shapes`) propagates
    ``jax.eval_shape`` structs through the very same triples, so the
    two can never disagree about what the serving forward computes.
    Raises ``ValueError`` naming the offending units otherwise.
    """
    forwards = list(forwards)
    if not forwards:
        raise ValueError("empty forward chain")
    unservable = [u for u in forwards
                  if not (callable(getattr(type(u), "pure", None))
                          and callable(getattr(u, "pure_config", None))
                          and callable(getattr(u, "pure_params",
                                               None)))]
    if unservable:
        raise ValueError(
            "forward unit(s) %s lack the pure-function protocol "
            "(a static `pure(params, x, **config)` plus "
            "`pure_config()`/`pure_params()`) and cannot be "
            "served by the batching engine — keep such workflows "
            "on a custom serving path" %
            ", ".join(type(u).__name__ for u in unservable))
    return tuple(
        (type(u).pure, dict(u.pure_config()),
         bool(getattr(type(u), "SKIP_AT_EVAL", False)))
        for u in forwards)


def _power_of_two_buckets(max_batch_size):
    buckets = []
    b = 1
    while b < max_batch_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch_size)
    return tuple(buckets)


class InferenceEngine(Logger):
    """Pure forward + device-resident params + AOT-warmed batch buckets.

    ``apply_fn(params, x)`` must be traceable by ``jax.jit`` and
    row-independent.  ``params`` is any pytree (the lowering's
    per-layer list of dicts, stripped to inference keys).

    ``params_source``: optional 0-arg callable returning fresh *host*
    params — the serve-while-training mode the in-workflow adapter
    uses: every device call re-installs the current weights, so a
    training loop's progress is visible to clients without rebuilding
    the engine (shapes must stay fixed; a topology change needs a new
    engine + registry hot-swap).

    ``mesh`` / ``param_specs``: the declarative mesh-sharded forward
    the generative engine already has (ROADMAP item 3 tail), ported:
    pass a ``jax.sharding.Mesh`` and the AOT buckets pjit over it —
    params placed per ``param_specs`` (a PartitionSpec pytree matching
    the params tree, or a callable ``leaf -> PartitionSpec | None``
    like :func:`veles_tpu.parallel.dp.tp_rules`; ``None`` replicates),
    request batches replicated like the gen engine's tokens.  A
    ``None``/single-device mesh is the transparent fallback: the
    single-device path is byte-identical (no pjit wrapper at all).
    """

    def __init__(self, params, apply_fn, sample_shape,
                 max_batch_size=64, buckets=None, params_source=None,
                 mesh=None, param_specs=None, quant_axes=None,
                 **kwargs):
        super(InferenceEngine, self).__init__(**kwargs)
        import jax
        self._jax = jax
        #: per-stage quantization axes ({"w": (axis,)} aligned with
        #: the params list) — the engine constructors derive them from
        #: each unit's ``weights_transposed`` so ``quantize_int8``
        #: reduces over the true fan-in axis
        self._quant_axes = quant_axes
        self.sample_shape = tuple(int(d) for d in sample_shape)
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.buckets = tuple(sorted(set(
            int(b) for b in (buckets
                             or _power_of_two_buckets(
                                 self.max_batch_size)))))
        if self.buckets[-1] != self.max_batch_size:
            raise ValueError(
                "largest bucket %d must equal max_batch_size %d"
                % (self.buckets[-1], self.max_batch_size))
        self.params_source = params_source
        # a mesh without >1 device total IS the single-device path
        self.mesh = mesh if (mesh is not None and
                             int(numpy.prod(list(mesh.shape.values())
                                            or [1])) > 1) else None
        self._shardings = self._build_shardings(params, param_specs)
        if self._shardings is None:
            self._params = jax.device_put(params)
            self._jit = jax.jit(apply_fn)
        else:
            p_sh, repl = self._shardings
            self._params = jax.device_put(params, p_sh)
            self._jit = jax.jit(apply_fn, in_shardings=(p_sh, repl),
                                out_shardings=repl)
        from veles_tpu.quant import tree_is_quantized, tree_nbytes
        #: "int8" after quantize_int8() (or when constructor-injected
        #: params already carry veles_tpu.quant pairs); None = float
        self.quantized = "int8" if tree_is_quantized(params) else None
        #: actual device bytes of the served params (int8 leaves count
        #: one byte) — held in the HBM ledger's params category until
        #: close(); the int8-vs-float acceptance gate reads this line
        from veles_tpu.memory import Watcher
        self.params_nbytes = tree_nbytes(self._params)
        Watcher.track(self.params_nbytes, "params")
        self._params_tracked = True
        self._ledger_gen = Watcher.generation
        self._compiled = {}          # batch size -> AOT executable
        self._compile_lock = threading.Lock()
        self.compile_count = 0
        self.infer_calls = 0         # device calls (monitoring)
        #: performance-ledger identity + per-bucket entries
        self.prof_name = "engine%d" % next(_ENGINE_SEQ)
        self._prof_entries = {}      # batch size -> LedgerEntry
        #: set by warmup(); a bucket compile after this is by
        #: definition a steady-state recompile (the sentinel flags it)
        self._warmed = False

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_workflow(cls, workflow, sample_shape=None, **kwargs):
        """Engine from a (trained or snapshot-loaded) workflow.

        Primary path: re-lower the workflow's layer specs through
        ``fused_graph.lower_specs`` with the trained weights injected
        as ``init``, and serve its ``apply_fn``.  Workflows built
        without specs (hand-linked graphs) fall back to
        :meth:`from_forwards` over their forward-unit chain.
        """
        fused_trainer = getattr(workflow, "fused_trainer", None)
        if fused_trainer is not None:
            # trained params live on the trainer's device tree; push
            # them into the forwards' Vectors before reading
            fused_trainer.sync_weights()
        specs = getattr(workflow, "layers", None)
        forwards = getattr(workflow, "forwards", None)
        if not forwards:
            raise ValueError("workflow has no forward units to serve")
        if sample_shape is None:
            sample_shape = cls._infer_sample_shape(workflow, forwards)
        if not specs or len(specs) != len(forwards):
            return cls.from_forwards(forwards,
                                     sample_shape=sample_shape,
                                     **kwargs)
        from veles_tpu.znicz.fused_graph import lower_specs
        lowered_specs = []
        for spec, unit in zip(specs, forwards):
            spec = {k: v for k, v in spec.items() if k != "init"}
            init = {}
            if unit.weights:
                unit.weights.map_read()
                init["weights"] = numpy.array(unit.weights.mem)
            if getattr(unit, "bias", None) and unit.bias:
                unit.bias.map_read()
                init["bias"] = numpy.array(unit.bias.mem)
            if init:
                spec["init"] = init
            lowered_specs.append(spec)
        params, _step, _eval, apply_fn = lower_specs(
            lowered_specs, sample_shape)
        params = [
            {k: v for k, v in state.items()
             if k in ("w", "b", "seed") and v is not None}
            for state in params]
        kwargs.setdefault("quant_axes", cls._quant_axes_of(forwards))
        return cls(params, lambda p, x: apply_fn(p, x, train=False),
                   sample_shape, **kwargs)

    @classmethod
    def from_forwards(cls, forwards, sample_shape=None, live=False,
                      **kwargs):
        """Engine straight from live forward units (the fallback /
        adapter path): compose each unit's ``pure`` with its static
        ``pure_config``; params come from ``pure_params(host=True)``.

        ``live=True`` keeps reading the units' weights on every device
        call (serve-while-training, see ``params_source``).
        """
        forwards = list(forwards)
        stages = forward_stages(forwards)

        def read_params():
            # the old RESTfulAPI critical section, kept: serialize the
            # read against a concurrently-training thread (and the job
            # layer's data exchange, which takes the same lock) —
            # without it a mid-update map_read can mark a stale host
            # copy fresh and serve pre-update weights forever
            import contextlib
            lock = getattr(forwards[0], "data_lock", None)
            with lock() if lock is not None \
                    else contextlib.nullcontext():
                for u in forwards:
                    # host copies may be stale after device training
                    if getattr(u, "weights", None) and u.weights:
                        u.weights.map_read()
                    if getattr(u, "bias", None) and u.bias:
                        u.bias.map_read()
                return [dict(u.pure_params(host=True))
                        for u in forwards]

        def apply_fn(params_list, x):
            h = x
            for (pure, config, skip_at_eval), p in zip(stages,
                                                       params_list):
                if skip_at_eval:
                    continue
                h = pure(p, h, **config)
            return h

        if sample_shape is None:
            sample_shape = cls._infer_sample_shape(None, forwards)
        kwargs.setdefault("quant_axes", cls._quant_axes_of(forwards))
        return cls(read_params(), apply_fn, sample_shape,
                   params_source=read_params if live else None,
                   **kwargs)

    @staticmethod
    def _quant_axes_of(forwards):
        """Per-stage quantization axes from the units' storage
        orientation: transposed weights are (neurons, fan-in), so the
        abs-max reduction runs over axis 1 there, axis 0 otherwise —
        one scale per output neuron either way."""
        return [
            {"w": ((1,) if getattr(u, "weights_transposed", False)
                   else (0,))}
            for u in forwards]

    @classmethod
    def from_snapshot(cls, path, **kwargs):
        """Engine from a :mod:`veles_tpu.snapshotter` artifact (local
        path, ``http(s)://`` URL or ``db://`` row)."""
        from veles_tpu.snapshotter import load_snapshot
        return cls.from_workflow(load_snapshot(path), **kwargs)

    @staticmethod
    def _infer_sample_shape(workflow, forwards):
        shape = infer_sample_shape(workflow, forwards)
        if shape is None:
            raise ValueError(
                "cannot infer sample_shape from the forward chain — "
                "pass sample_shape=(...) explicitly")
        return shape

    # -- sharding ---------------------------------------------------------
    def _build_shardings(self, params, param_specs):
        """``(params_sharding_tree, replicated)`` over the mesh, or
        ``None`` on the single-device path.  Same shape as the gen
        engine's ``_build_shardings``: specs map per leaf, everything
        unspecified replicates."""
        if self.mesh is None:
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        if param_specs is None:
            p_sh = jax.tree.map(lambda _leaf: repl, params)
        elif callable(param_specs):
            p_sh = jax.tree.map(
                lambda leaf: NamedSharding(
                    mesh, param_specs(leaf) or P()), params)
        else:
            p_sh = jax.tree.map(
                lambda spec: NamedSharding(mesh, spec), param_specs,
                is_leaf=lambda x: isinstance(x, P))
        return p_sh, repl

    # -- compilation ------------------------------------------------------
    def _bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _executable(self, batch_size):
        exe = self._compiled.get(batch_size)
        if exe is not None:
            return exe
        with self._compile_lock:
            exe = self._compiled.get(batch_size)
            if exe is not None:
                return exe
            jax = self._jax
            spec = jax.ShapeDtypeStruct(
                (batch_size,) + self.sample_shape, numpy.float32)
            params_spec = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self._params)
            span_args = {"bucket": batch_size,
                         "engine": self.prof_name}
            with trace.span("serve", "compile_bucket", span_args,
                            role="server"):
                exe = self._jit.lower(params_spec, spec).compile()
                # cost rides the span args (recorded at span exit) so
                # an exported trace is a self-contained perf report —
                # same schema as the segment compile instants
                cost, new_args = prof.span_cost_args(
                    exe, span_args, peak_dtype=self.quantized)
                span_args.update(new_args)
                if self._warmed:
                    # in-band steadiness for the offline report
                    span_args["recompile"] = True
            self.compile_count += 1
            entry = self._prof_entries.get(batch_size)
            if entry is None:
                entry = self._prof_entries[batch_size] = \
                    prof.ledger.entry(
                        "bucket", "%s[b%d]" % (self.prof_name,
                                               batch_size))
            if self.quantized:
                # honest MFU denominator for quantized buckets
                # (backends.PEAK_INT8_OPS)
                entry.peak_dtype = self.quantized
            prof.ledger.record_compile(entry, cost=cost,
                                       steady=self._warmed)
            self.debug("compiled bucket %d (compile #%d)", batch_size,
                       self.compile_count)
            # cache BEFORE the sentinel can raise: in strict mode the
            # compile fails the request loudly exactly once — later
            # requests serve from the cached executable instead of
            # re-paying (and re-failing) a full XLA compile per call
            self._compiled[batch_size] = exe
            if self._warmed:
                # warmup() promised zero steady-state compiles — an
                # unwarmed batch shape reached the engine
                prof.flag_recompile(
                    "serve:%s:bucket[%d]" % (self.prof_name,
                                             batch_size),
                    None, None, logger=self,
                    detail="bucket %d compiled after warmup() — the "
                           "batch reached a shape no warmed bucket "
                           "covers" % batch_size)
        return exe

    def quantize_int8(self, calibration=None, tol=None):
        """Quantize the served params in place (per-output-channel
        symmetric int8 over each stage's 2D ``"w"``; biases and
        non-2D kernels stay float) — the ``ModelRegistry.deploy(...,
        quantize="int8")`` hook.  Must run BEFORE :meth:`warmup` so
        every bucket compiles against the quantized tree exactly once
        (the zero-steady-state-recompile contract).

        ``calibration``: optional host batch; when given, the float
        forward (``reference_forward``) is compared against the
        quantized forward and a relative logit drift beyond ``tol``
        (default :data:`veles_tpu.quant.DRIFT_TOL`) raises a typed
        :class:`~veles_tpu.quant.QuantizationError` NAMING the stage
        whose dynamic range does not fit 8 bits (per-stage blame
        probe).  Only engines whose ``apply_fn`` routes through the
        pure-function protocol (``from_workflow``/``from_forwards``)
        can serve quantized pairs; ``params_source`` (live) engines
        are refused — a float refresh would clash with the quantized
        tree's structure.  Returns self (chainable)."""
        from veles_tpu import quant
        jax = self._jax
        if self._warmed or self.compile_count:
            raise RuntimeError(
                "quantize_int8 must run before warmup()/any compile — "
                "a post-warmup dtype flip would recompile every "
                "bucket in steady state")
        if self.params_source is not None:
            raise ValueError(
                "cannot quantize a live (params_source) engine — the "
                "per-call float refresh would clash with the "
                "quantized tree; deploy a frozen snapshot instead")
        if self.mesh is not None:
            raise ValueError(
                "int8-quantized params cannot shard over a mesh yet — "
                "serve the quantized deploy single-device/replicated")
        if self.quantized:
            return self
        tol = quant.DRIFT_TOL if tol is None else tol
        host = jax.tree.map(numpy.asarray, self._params)
        qparams = quant.quantize_stage_params(host, self._quant_axes)
        if calibration is not None:
            calibration = numpy.ascontiguousarray(calibration,
                                                  numpy.float32)
            ref = self.reference_forward(calibration)

            def drift_of(tree):
                return quant.relative_drift(
                    ref, numpy.asarray(self._jit(jax.device_put(tree),
                                                 calibration)))

            def blame():
                per_stage = {
                    index: drift_of(quant.quantize_stage_params(
                        host, self._quant_axes, only=index))
                    for index, state in enumerate(host)
                    if quant.is_quantized_leaf(qparams[index]
                                               .get("w"))}
                worst = max(per_stage, key=per_stage.get)
                return "stage[%d].w" % worst, per_stage[worst]

            quant.check_drift("params", drift_of(qparams), tol, blame)
        self._params = jax.device_put(qparams)
        self.quantized = "int8"
        self._out_struct_ = None
        # re-price the ledger hold from the new (int8) leaves
        from veles_tpu.memory import Watcher
        if (getattr(self, "_params_tracked", False)
                and getattr(self, "_ledger_gen", 0)
                == Watcher.generation):
            Watcher.untrack(self.params_nbytes, "params")
        self.params_nbytes = quant.tree_nbytes(self._params)
        Watcher.track(self.params_nbytes, "params")
        self._params_tracked = True
        self._ledger_gen = Watcher.generation
        self.info("quantized params to int8 (%d bytes resident)",
                  self.params_nbytes)
        return self

    def describe(self):
        """Deploy surface (merged into ``_Model.describe()``): the
        quant mode and the params' actual resident bytes next to the
        compile/bucket plan."""
        return {
            "sample_shape": list(self.sample_shape),
            "quantize": self.quantized,
            "params_bytes": self.params_nbytes,
            "sharded": self.mesh is not None,
        }

    def close(self):
        """Release the params-category ledger hold (the device arrays
        themselves are freed by GC once the last in-flight batch drops
        its reference).  Idempotent — the registry calls this on
        undeploy/stop and when a hot swap retires the engine."""
        if getattr(self, "_params_tracked", False):
            from veles_tpu.memory import Watcher
            # generation-guarded like Vector's release: a
            # Watcher.reset() since the hold was taken already wiped
            # it, and re-releasing would drive the ledger negative
            if (getattr(self, "_ledger_gen", 0)
                    == Watcher.generation):
                Watcher.untrack(self.params_nbytes, "params")
            self._params_tracked = False

    def warmup(self):
        """AOT-compile every bucket; returns self (chainable).  After
        this, serving any batch size never triggers a compile — and
        the recompile sentinel holds the engine to it: any later
        bucket compile is flagged as a steady-state recompile."""
        for b in self.buckets:
            self._executable(b)
        self._warmed = True
        return self

    def padded_capacity(self, n):
        """Total bucket rows a batch of ``n`` occupies on the device
        (splitting beyond ``max_batch_size`` included) — the
        denominator of an honest batch-fill ratio."""
        capacity = 0
        while n > 0:
            chunk = min(n, self.max_batch_size)
            capacity += self._bucket_for(chunk)
            n -= chunk
        return capacity

    def _out_struct(self):
        """Cached (shape, dtype) of one bucket-1 output, via
        ``jax.eval_shape`` — no device work."""
        struct = getattr(self, "_out_struct_", None)
        if struct is None:
            jax = self._jax
            spec = jax.ShapeDtypeStruct((1,) + self.sample_shape,
                                        numpy.float32)
            params_spec = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self._params)
            out = jax.eval_shape(self._jit, params_spec, spec)
            struct = self._out_struct_ = (tuple(out.shape[1:]),
                                          numpy.dtype(str(out.dtype)))
        return struct

    # -- serving ----------------------------------------------------------
    def update_params(self, params):
        """Install new host params (same tree structure/shapes).  The
        swap is a single reference assignment: concurrent ``infer``
        calls see either the old or the new tree, never a mix.  On a
        mesh the new tree lands with the engine's param shardings."""
        if self._shardings is not None:
            self._params = self._jax.device_put(params,
                                                self._shardings[0])
        else:
            self._params = self._jax.device_put(params)

    def infer(self, batch):
        """Host batch → host float32 outputs, same leading length.

        Pads up to the nearest warmed bucket; batches beyond
        ``max_batch_size`` are served in max-bucket chunks.
        """
        batch = numpy.ascontiguousarray(batch, dtype=numpy.float32)
        if batch.ndim == 1:
            batch = batch[None, :]
        if batch.shape[1:] != self.sample_shape:
            raise ValueError("sample shape %s does not match engine %s"
                             % (batch.shape[1:], self.sample_shape))
        n = len(batch)
        if n == 0:
            # statically known answer: no params refresh, no device call
            shape, dtype = self._out_struct()
            return numpy.zeros((0,) + shape, dtype)
        if self.params_source is not None:
            self.update_params(self.params_source())
        pieces = []
        for start in range(0, n, self.max_batch_size):
            pieces.append(self._infer_chunk(
                batch[start:start + self.max_batch_size]))
        return pieces[0] if len(pieces) == 1 else \
            numpy.concatenate(pieces)

    def reference_forward(self, batch):
        """The un-padded jitted forward at the batch's exact shape —
        the verification oracle bucket padding is measured against
        (``tests/test_serve.py`` asserts byte-identity).  Compiles per
        exact shape, so this is NOT a serving path."""
        batch = numpy.ascontiguousarray(batch, dtype=numpy.float32)
        return numpy.asarray(self._jit(self._params, batch))

    def _infer_chunk(self, chunk):
        n = len(chunk)
        bucket = self._bucket_for(n)
        if n != bucket:
            padded = numpy.zeros((bucket,) + self.sample_shape,
                                 numpy.float32)
            padded[:n] = chunk
            chunk = padded
        exe = self._executable(bucket)
        self.infer_calls += 1
        with trace.span("serve", "infer_chunk",
                        {"bucket": bucket, "engine": self.prof_name},
                        role="server"):
            tic = time.perf_counter_ns()
            out = numpy.asarray(exe(self._params, chunk))
            entry = self._prof_entries.get(bucket)
            if entry is not None:
                prof.ledger.record_dispatch(
                    entry, time.perf_counter_ns() - tic)
        return out[:n]
