"""ModelRegistry: named models, snapshot loading, atomic hot swap.

The multi-model layer of the serving engine: each name owns ONE
:class:`~veles_tpu.serve.batcher.DynamicBatcher` (so queued requests
survive a version change) and a current
:class:`~veles_tpu.serve.engine.InferenceEngine`.  Deploying a new
version is one attribute assignment on the batcher — requests already
inside a device call finish on the old engine; every batch formed
after the swap runs on the new one.  Old engines need no teardown
(they are plain objects holding device arrays; the GC reclaims them
once the last in-flight batch drops its reference).

Versions come from anywhere an engine can be built: a live workflow, a
forward-unit chain, or a :mod:`veles_tpu.snapshotter` artifact (local
path / ``http(s)://`` URL / ``db://`` row) — the trained-model hand-off
the Snapshotter side of the platform already produces.
"""

import threading
import time

from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.serve.batcher import DynamicBatcher
from veles_tpu.serve.engine import InferenceEngine


class _Model(object):
    """One served name: stable batcher + swappable engine + metadata."""

    __slots__ = ("name", "batcher", "version", "deployed_at", "swaps",
                 "source")

    def __init__(self, name, batcher):
        self.name = name
        self.batcher = batcher
        self.version = None
        self.deployed_at = None
        self.swaps = 0
        self.source = None

    @property
    def engine(self):
        return self.batcher.engine

    def describe(self):
        return {
            "name": self.name,
            "version": self.version,
            "deployed_at": self.deployed_at,
            "swaps": self.swaps,
            "source": self.source,
            "buckets": list(self.engine.buckets),
            "compile_count": self.engine.compile_count,
            "queue_depth": self.batcher.queue_depth(),
        }


class ModelRegistry(Logger):
    """Name → model map with atomic deploy/swap (thread-safe)."""

    def __init__(self, metrics=None, batcher_config=None, **kwargs):
        super(ModelRegistry, self).__init__(**kwargs)
        self.metrics = metrics
        self.batcher_config = dict(batcher_config or {})
        self._models = {}
        self._lock = threading.Lock()
        if metrics is not None:
            metrics.register_gauge("models", lambda: len(self._models))

    def attach_metrics(self, metrics):
        """Adopt a metrics sink after construction (the ServingServer
        path when it is handed a metrics-less registry): existing AND
        future batchers record into it, so /metrics never silently
        reports zeros under real traffic."""
        self.metrics = metrics
        metrics.register_gauge("models", lambda: len(self._models))
        with self._lock:
            for name, model in self._models.items():
                model.batcher.metrics = metrics
                metrics.register_gauge(
                    'queue_depth{model="%s"}' % name,
                    model.batcher.queue_depth)

    def deploy(self, name, engine, version=None, source=None,
               warmup=True, allow_reshape=False):
        """Install ``engine`` as the current version of ``name``.

        First deploy for a name creates its batcher; later deploys
        hot-swap the engine atomically — in-flight batches finish on
        the previous version, the queue is preserved.  ``warmup=True``
        AOT-compiles the new engine's buckets BEFORE the swap, so the
        first post-swap batch pays zero compile latency.

        A swap that CHANGES the model's sample shape is refused unless
        ``allow_reshape=True`` (queued old-shape requests cannot be
        honored by the new engine — deploy a different topology under
        a new name, or opt in and let those requests fail with a shape
        error while new-shape traffic proceeds).
        """
        if warmup:
            engine.warmup()
        with self._lock:
            model = self._models.get(name)
            if model is None:
                batcher = DynamicBatcher(
                    engine, metrics=self.metrics,
                    gauge_name='queue_depth{model="%s"}' % name,
                    **self.batcher_config)
                model = _Model(name, batcher)
                self._models[name] = model
            else:
                old_shape = getattr(model.engine, "sample_shape", None)
                new_shape = getattr(engine, "sample_shape", None)
                if (not allow_reshape
                        and old_shape is not None
                        and new_shape is not None
                        and tuple(old_shape) != tuple(new_shape)):
                    raise ValueError(
                        "hot swap of %r changes sample shape %s -> %s;"
                        " deploy under a new name or pass "
                        "allow_reshape=True" % (name, tuple(old_shape),
                                                tuple(new_shape)))
                model.batcher.engine = engine   # THE hot swap
                model.swaps += 1
            model.version = version if version is not None \
                else (model.swaps + 1)
            model.deployed_at = time.time()
            model.source = source
        self.info("deployed %s version %s%s", name, model.version,
                  " (hot swap #%d)" % model.swaps if model.swaps
                  else "")
        return model

    def preflight(self, workflow, name=None):
        """Run analyzer passes 1–2 (graph doctor + JAX hazards) on a
        workflow about to be served, per ``root.common.serve
        .preflight``:

        - ``"off"`` — skip entirely;
        - ``"warn"`` (default) — log every finding, deploy anyway;
        - ``"fail"`` — raise :class:`veles_tpu.analyze.PreflightError`
          when the report contains errors (the serve counterpart of
          the engine's warmup guarantee: refuse at load time, not at
          the first request).

        Returns the :class:`~veles_tpu.analyze.Report` (or ``None``
        when off).
        """
        mode = str(root.common.serve.get("preflight",
                                         "warn")).strip().lower()
        if mode not in ("off", "no", "false", "0", "warn", "fail"):
            # a typo'd fail-mode config must not silently downgrade
            # to warn-and-deploy
            raise ValueError(
                "root.common.serve.preflight is %r — want off | warn "
                "| fail" % mode)
        if mode in ("off", "no", "false", "0"):
            return None
        from veles_tpu.analyze import PreflightError, analyze_workflow
        report = analyze_workflow(workflow)
        label = name or type(workflow).__name__
        for finding in report:
            log = {"error": self.error,
                   "warning": self.warning}.get(finding.severity,
                                                self.info)
            log("preflight[%s]: %s", label, finding.render())
        if report.has_errors and mode == "fail":
            raise PreflightError(report)
        if len(report):
            counts = report.counts()
            self.info("preflight[%s]: %d error(s), %d warning(s) "
                      "(mode=%s)", label, counts["error"],
                      counts["warning"], mode)
        return report

    def load_snapshot(self, name, path, version=None, engine_config=None,
                      warmup=True):
        """Build an engine from a snapshot artifact and deploy it
        (pre-flighted per ``root.common.serve.preflight``)."""
        from veles_tpu.snapshotter import load_snapshot
        workflow = load_snapshot(path)
        self.preflight(workflow, name)
        engine = InferenceEngine.from_workflow(
            workflow, **dict(engine_config or {}))
        return self.deploy(name, engine, version=version, source=path,
                           warmup=warmup)

    def load_workflow(self, name, workflow, version=None,
                      engine_config=None, warmup=True):
        self.preflight(workflow, name)
        engine = InferenceEngine.from_workflow(
            workflow, **dict(engine_config or {}))
        return self.deploy(name, engine, version=version,
                           source=type(workflow).__name__,
                           warmup=warmup)

    def get(self, name):
        model = self._models.get(name)
        if model is None:
            raise KeyError("no model %r (serving: %s)"
                           % (name, ", ".join(sorted(self._models))
                              or "<none>"))
        return model

    def __contains__(self, name):
        return name in self._models

    def names(self):
        with self._lock:   # a first deploy may be inserting a key
            return sorted(self._models)

    def describe(self):
        with self._lock:
            models = dict(self._models)
        return {name: model.describe()
                for name, model in sorted(models.items())}

    def submit(self, name, rows):
        """Queue rows on ``name``'s batcher; returns the Future."""
        return self.get(name).batcher.submit(rows)

    def infer(self, name, rows, timeout=30.0):
        return self.submit(name, rows).result(timeout)

    def stop(self, drain=True):
        with self._lock:
            models, self._models = dict(self._models), {}
        if self.metrics is not None:
            # a shared sink outlives this registry: stale gauges would
            # keep reporting dead models (and pin their engines' device
            # params against GC)
            self.metrics.unregister_gauge("models")
            for name in models:
                self.metrics.unregister_gauge(
                    'queue_depth{model="%s"}' % name)
        for model in models.values():
            model.batcher.stop(drain=drain)
