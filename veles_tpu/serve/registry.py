"""ModelRegistry: named models, snapshot loading, atomic hot swap,
replica sets with weighted traffic split.

The multi-model layer of the serving engine: each name owns ONE
:class:`~veles_tpu.serve.batcher.DynamicBatcher` (so queued requests
survive a version change) and a current
:class:`~veles_tpu.serve.engine.InferenceEngine`.  Deploying a new
version is one attribute assignment on the batcher — requests already
inside a device call finish on the old engine; every batch formed
after the swap runs on the new one.  Old engines need no teardown
(they are plain objects holding device arrays; the GC reclaims them
once the last in-flight batch drops its reference).

On top of the atomic swap sit **replica sets**: a
:class:`ReplicaSet` is engine-shaped (the batcher can't tell), but
routes each batch to one member by smooth weighted round-robin —
deterministic, proportional at every prefix, no RNG in the serving
path.  ``deploy_canary`` is the two-member special case: the current
engine keeps ``1 - weight`` of traffic, the candidate gets
``weight``, and ``promote``/another ``deploy`` ends the experiment.
``describe()`` reports every member's version, weight and served
count, so a rollout dashboard (or a test) never reaches into
privates.

Versions come from anywhere an engine can be built: a live workflow, a
forward-unit chain, or a :mod:`veles_tpu.snapshotter` artifact (local
path / ``http(s)://`` URL / ``db://`` row) — the trained-model hand-off
the Snapshotter side of the platform already produces.  Generative
engines (:mod:`veles_tpu.gen`) deploy through
:meth:`ModelRegistry.deploy_generative`, which swaps the batcher for a
continuous-batching scheduler and preflights the KV-cache plan
(analyzer rule V-S01).
"""

import threading
import time

from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.serve.batcher import DynamicBatcher
from veles_tpu.serve.engine import InferenceEngine


class ReplicaSet(object):
    """Engine-shaped weighted router over same-shape engines.

    Members: ``[(engine, weight, version), ...]`` with positive
    weights.  Selection is smooth weighted round-robin (the nginx
    algorithm): each pick adds every member's weight to its running
    credit, serves the max-credit member, and subtracts the weight
    total — so a 3:1 split serves exactly 30/10 over any 40 calls and
    interleaves rather than bursting.  The batcher worker is the only
    caller of :meth:`infer`, so selection needs no lock; a lock still
    guards it because ``ServingServer`` exposes describe() scrapes
    concurrently.
    """

    def __init__(self, replicas):
        members = []
        for item in replicas:
            engine, weight, version = item
            weight = float(weight)
            if weight <= 0:
                raise ValueError("replica weight must be > 0, got %r"
                                 % weight)
            members.append({"engine": engine, "weight": weight,
                            "version": version, "credit": 0.0,
                            "served": 0})
        if not members:
            raise ValueError("empty replica set")
        shapes = {tuple(m["engine"].sample_shape) for m in members
                  if hasattr(m["engine"], "sample_shape")}
        if len(shapes) > 1:
            raise ValueError(
                "replica engines disagree on sample shape: %s"
                % sorted(shapes))
        self._members = members
        self._total_weight = sum(m["weight"] for m in members)
        self._lock = threading.Lock()

    # -- engine protocol (what DynamicBatcher/_Model touch) ---------------
    @property
    def sample_shape(self):
        return self._members[0]["engine"].sample_shape

    @property
    def max_batch_size(self):
        return min(m["engine"].max_batch_size for m in self._members)

    @property
    def buckets(self):
        return self._members[0]["engine"].buckets

    @property
    def compile_count(self):
        return sum(m["engine"].compile_count for m in self._members)

    def warmup(self):
        for member in self._members:
            member["engine"].warmup()
        return self

    def padded_capacity(self, n):
        return self._members[0]["engine"].padded_capacity(n)

    def _next(self):
        with self._lock:
            for member in self._members:
                member["credit"] += member["weight"]
            best = max(self._members, key=lambda m: m["credit"])
            best["credit"] -= self._total_weight
            best["served"] += 1
            return best

    def infer(self, batch):
        return self._next()["engine"].infer(batch)

    def pick(self):
        """One smooth-WRR selection, returned as the member engine —
        the routing surface for callers that dispatch themselves (the
        fleet's decode router) instead of riding :meth:`infer`."""
        return self._next()["engine"]

    def engines(self):
        """Snapshot of every member engine, construction order — lets
        the fleet iterate its decode schedulers (drain, describe,
        signal sampling) without reaching into the member dicts."""
        with self._lock:
            return [m["engine"] for m in self._members]

    # -- reconfiguration (the autoscaler's surface) ------------------------
    def set_weights(self, weights):
        """Re-weight every member in place (positional, construction /
        ``describe()`` order) and RESET the smooth-WRR credits: the
        credits are denominated in the OLD weight total, so carrying
        them across a re-weight skews the first rotation toward
        whoever was owed traffic under the old split — a 3:1 → 1:1
        shift must serve exactly 1:1 from the very next rotation."""
        weights = [float(w) for w in weights]
        if len(weights) != len(self._members):
            raise ValueError(
                "got %d weight(s) for %d member(s)"
                % (len(weights), len(self._members)))
        if any(w <= 0 for w in weights):
            raise ValueError("replica weights must be > 0, got %r"
                             % (weights,))
        with self._lock:
            for member, weight in zip(self._members, weights):
                member["weight"] = weight
                member["credit"] = 0.0
            self._total_weight = sum(weights)

    def add_replica(self, engine, weight=1.0, version=None):
        """Grow the set by one member (scale-up).  Credits reset —
        the new split starts clean, same reasoning as
        :meth:`set_weights`."""
        weight = float(weight)
        if weight <= 0:
            raise ValueError("replica weight must be > 0, got %r"
                             % weight)
        with self._lock:
            if hasattr(engine, "sample_shape"):
                shapes = {tuple(m["engine"].sample_shape)
                          for m in self._members
                          if hasattr(m["engine"], "sample_shape")}
                if shapes and {tuple(engine.sample_shape)} != shapes:
                    raise ValueError(
                        "new replica's sample shape %s disagrees with "
                        "the set's %s" % (tuple(engine.sample_shape),
                                          sorted(shapes)))
            if version is None:
                version = len(self._members)
            self._members.append({"engine": engine, "weight": weight,
                                  "version": version, "credit": 0.0,
                                  "served": 0})
            for member in self._members:
                member["credit"] = 0.0
            self._total_weight = sum(m["weight"]
                                     for m in self._members)
        return version

    def remove_replica(self, version):
        """Shrink the set by the member deployed as ``version``
        (scale-down) and return its engine — the caller drains it.
        Refuses to empty the set."""
        with self._lock:
            if len(self._members) == 1:
                raise ValueError(
                    "cannot remove the last replica — undeploy the "
                    "set instead")
            for index, member in enumerate(self._members):
                if member["version"] == version:
                    break
            else:
                raise KeyError("no replica with version %r (have %s)"
                               % (version,
                                  [m["version"]
                                   for m in self._members]))
            removed = self._members.pop(index)
            for member in self._members:
                member["credit"] = 0.0
            self._total_weight = sum(m["weight"]
                                     for m in self._members)
        return removed["engine"]

    def __len__(self):
        return len(self._members)

    # -- introspection -----------------------------------------------------
    def describe(self):
        with self._lock:
            return [{"version": m["version"],
                     "weight": m["weight"],
                     "served": m["served"],
                     "engine": getattr(m["engine"], "prof_name", None)}
                    for m in self._members]


class _Model(object):
    """One served name: stable batcher + swappable engine + metadata."""

    __slots__ = ("name", "batcher", "version", "deployed_at", "swaps",
                 "source")

    is_generative = False

    def __init__(self, name, batcher):
        self.name = name
        self.batcher = batcher
        self.version = None
        self.deployed_at = None
        self.swaps = 0
        self.source = None

    @property
    def engine(self):
        return self.batcher.engine

    def describe(self):
        info = {
            "name": self.name,
            "version": self.version,
            "deployed_at": self.deployed_at,
            "swaps": self.swaps,
            "source": self.source,
            "buckets": list(self.engine.buckets),
            "compile_count": self.engine.compile_count,
            "queue_depth": self.batcher.queue_depth(),
        }
        if not isinstance(self.engine, ReplicaSet) \
                and hasattr(self.engine, "describe"):
            # engine-level surface (quant mode, actual params bytes,
            # sharding) — dashboards read the int8 win from here; a
            # ReplicaSet's describe() is its per-member LIST and is
            # reported under "replicas" below instead
            info.update(self.engine.describe())
        if isinstance(self.engine, ReplicaSet):
            # per-replica weights/versions/served counts — rollout
            # dashboards and tests assert canary splits from here
            # instead of reaching into privates
            info["replicas"] = self.engine.describe()
        return info


class _GenModel(object):
    """One served GENERATIVE name: engine + continuous scheduler."""

    __slots__ = ("name", "scheduler", "version", "deployed_at",
                 "source")

    is_generative = True

    def __init__(self, name, scheduler):
        self.name = name
        self.scheduler = scheduler
        self.version = None
        self.deployed_at = None
        self.source = None

    @property
    def engine(self):
        return self.scheduler.engine

    def describe(self):
        info = {
            "name": self.name,
            "version": self.version,
            "deployed_at": self.deployed_at,
            "source": self.source,
            "generative": True,
        }
        info.update(self.engine.describe())
        info.update(self.scheduler.describe())
        return info


class _FleetModel(object):
    """One served DISAGGREGATED name: a :class:`veles_tpu.fleet.Fleet`
    facade (prefill role + decode replica set + autoscaler) behind the
    same registry surface as a generative model — ``scheduler`` and
    ``engine`` both resolve to the fleet, whose ``generate`` /
    ``stop`` / ``close`` line up with what the registry and server
    already call."""

    __slots__ = ("name", "fleet", "version", "deployed_at", "source")

    is_generative = True

    def __init__(self, name, fleet):
        self.name = name
        self.fleet = fleet
        self.version = None
        self.deployed_at = None
        self.source = None

    @property
    def scheduler(self):
        return self.fleet

    @property
    def engine(self):
        return self.fleet

    def describe(self):
        info = {
            "name": self.name,
            "version": self.version,
            "deployed_at": self.deployed_at,
            "source": self.source,
            "generative": True,
            "disaggregated": True,
        }
        info.update(self.fleet.describe())
        return info

    def metrics_text(self):
        return self.fleet.metrics_text()


class ModelRegistry(Logger):
    """Name → model map with atomic deploy/swap (thread-safe)."""

    def __init__(self, metrics=None, batcher_config=None, **kwargs):
        super(ModelRegistry, self).__init__(**kwargs)
        self.metrics = metrics
        self.batcher_config = dict(batcher_config or {})
        self._models = {}
        self._lock = threading.Lock()
        if metrics is not None:
            metrics.register_gauge("models", lambda: len(self._models))

    def attach_metrics(self, metrics):
        """Adopt a metrics sink after construction (the ServingServer
        path when it is handed a metrics-less registry): existing AND
        future batchers record into it, so /metrics never silently
        reports zeros under real traffic."""
        self.metrics = metrics
        metrics.register_gauge("models", lambda: len(self._models))
        with self._lock:
            for name, model in self._models.items():
                if model.is_generative:
                    model.scheduler.metrics = metrics
                    model.scheduler._register_gauges(metrics)
                    continue
                model.batcher.metrics = metrics
                metrics.register_gauge(
                    'queue_depth{model="%s"}' % name,
                    model.batcher.queue_depth)

    def _resolve_quantize(self, quantize):
        """The deploy-time quant mode: the explicit argument, else the
        ``root.common.serve.quantize`` knob (default off).  Returns
        ``"int8"`` or ``None``; a typo'd mode raises instead of
        silently deploying float."""
        if quantize is None:
            quantize = root.common.serve.get("quantize", "off")
        mode = str(quantize).strip().lower()
        if mode in ("off", "no", "false", "0", "none", ""):
            return None
        if mode != "int8":
            raise ValueError(
                "quantize mode %r — want off | int8 (the knob is "
                "root.common.serve.quantize)" % (quantize,))
        return "int8"

    def deploy(self, name, engine, version=None, source=None,
               warmup=True, allow_reshape=False, quantize=None,
               calibration=None):
        """Install ``engine`` as the current version of ``name``.

        First deploy for a name creates its batcher; later deploys
        hot-swap the engine atomically — in-flight batches finish on
        the previous version, the queue is preserved.  ``warmup=True``
        AOT-compiles the new engine's buckets BEFORE the swap, so the
        first post-swap batch pays zero compile latency.

        ``quantize="int8"`` (or the ``root.common.serve.quantize``
        knob) quantizes the engine's params in place BEFORE warmup
        (``InferenceEngine.quantize_int8`` — per-output-channel
        symmetric int8, biases float), with ``calibration`` as the
        optional drift-gate batch; replica sets must quantize their
        member engines individually (the set itself is refused).

        A swap that CHANGES the model's sample shape is refused unless
        ``allow_reshape=True`` (queued old-shape requests cannot be
        honored by the new engine — deploy a different topology under
        a new name, or opt in and let those requests fail with a shape
        error while new-shape traffic proceeds).
        """
        mode = self._resolve_quantize(quantize)
        if mode and getattr(engine, "quantized", None) != mode:
            if not hasattr(engine, "quantize_int8"):
                if quantize is not None:
                    raise ValueError(
                        "%s cannot be quantized at deploy — quantize "
                        "the member engines individually"
                        % type(engine).__name__)
                self.warning(
                    "serve.quantize knob is on but %s has no "
                    "quantize_int8 — deploying as-is",
                    type(engine).__name__)
            else:
                engine.quantize_int8(calibration=calibration)
        if warmup:
            engine.warmup()
        with self._lock:
            model = self._models.get(name)
            if model is not None and model.is_generative:
                raise ValueError(
                    "%r serves a generative model — undeploy it (or "
                    "deploy_generative a successor) instead of hot-"
                    "swapping a request/response engine over token "
                    "streams" % name)
            if model is None:
                batcher = DynamicBatcher(
                    engine, metrics=self.metrics,
                    gauge_name='queue_depth{model="%s"}' % name,
                    **self.batcher_config)
                model = _Model(name, batcher)
                self._models[name] = model
            else:
                old_shape = getattr(model.engine, "sample_shape", None)
                new_shape = getattr(engine, "sample_shape", None)
                if (not allow_reshape
                        and old_shape is not None
                        and new_shape is not None
                        and tuple(old_shape) != tuple(new_shape)):
                    raise ValueError(
                        "hot swap of %r changes sample shape %s -> %s;"
                        " deploy under a new name or pass "
                        "allow_reshape=True" % (name, tuple(old_shape),
                                                tuple(new_shape)))
                retired = model.batcher.engine
                model.batcher.engine = engine   # THE hot swap
                model.swaps += 1
                self._retire_engine(retired, engine)
            model.version = version if version is not None \
                else (model.swaps + 1)
            model.deployed_at = time.time()
            model.source = source
        self.info("deployed %s version %s%s", name, model.version,
                  " (hot swap #%d)" % model.swaps if model.swaps
                  else "")
        return model

    @staticmethod
    def _retire_engine(retired, successor):
        """Drop a swapped-out engine's HBM-ledger hold (close() only
        releases accounting; in-flight batches finish unharmed) —
        UNLESS the successor still serves it (the canary path wraps
        the stable engine inside the incoming ReplicaSet)."""
        if retired is None or retired is successor:
            return
        members = []
        if isinstance(successor, ReplicaSet):
            members = [m["engine"] for m in successor._members]
        if retired in members:
            return
        if isinstance(retired, ReplicaSet):
            # promotion: the winning member survives inside successor
            # deploys only if it IS the successor (checked above)
            for member in retired._members:
                if member["engine"] is not successor:
                    ModelRegistry._retire_engine(member["engine"],
                                                 successor)
            return
        close = getattr(retired, "close", None)
        if close is not None:
            close()

    def deploy_replica_set(self, name, replicas, version=None,
                           source=None, warmup=True,
                           allow_reshape=False, quantize=None,
                           calibration=None):
        """Deploy a weighted :class:`ReplicaSet` under ``name``.

        ``replicas``: ``[(engine, weight), ...]`` or ``[(engine,
        weight, version), ...]`` — weights are relative (3 and 1 split
        75/25).  Same atomic swap as :meth:`deploy`: the set IS the
        batcher's engine, so the split applies from the next batch
        boundary and in-flight batches finish where they started.
        """
        normalized = []
        for index, item in enumerate(replicas):
            if len(item) == 2:
                engine, weight = item
                rep_version = index
            else:
                engine, weight, rep_version = item
            normalized.append((engine, weight, rep_version))
        replica_set = ReplicaSet(normalized)
        return self.deploy(name, replica_set, version=version,
                           source=source or "replica_set",
                           warmup=warmup, allow_reshape=allow_reshape,
                           quantize=quantize, calibration=calibration)

    def deploy_canary(self, name, engine, weight=0.1, version=None,
                      warmup=True):
        """Split ``name``'s traffic: the CURRENT engine keeps
        ``1 - weight``, the candidate gets ``weight``.  Promote by
        deploying the candidate plainly (or widen the split with
        another ``deploy_canary``).  The current engine may itself be
        a ReplicaSet — the canary then rides on top of the set as one
        member, which is almost never what a rollout means, so that
        case is refused; finish one experiment before starting the
        next."""
        weight = float(weight)
        if not 0.0 < weight < 1.0:
            raise ValueError("canary weight must be in (0, 1), got %r"
                             % weight)
        model = self.get(name)
        if model.is_generative:
            raise ValueError("canary rollout of generative models is "
                             "not supported yet")
        stable = model.engine
        if isinstance(stable, ReplicaSet):
            raise ValueError(
                "%r already serves a replica set — promote or undeploy "
                "the running experiment before starting a new canary"
                % name)
        return self.deploy_replica_set(
            name,
            [(stable, 1.0 - weight, model.version),
             (engine, weight, version
              if version is not None else "canary")],
            version=model.version, source="canary", warmup=warmup)

    # -- generative deploys ------------------------------------------------
    def preflight_generative(self, engine, name=None):
        """Analyzer rule V-S01 at deploy time, honouring
        ``root.common.serve.preflight`` exactly like the workflow
        preflight: KV-cache footprint vs device HBM, slot/bucket plan
        sanity, and non-causal-model rejection — refuse at the
        registry, not at the first streamed token."""
        mode = str(root.common.serve.get("preflight",
                                         "warn")).strip().lower()
        if mode not in ("off", "no", "false", "0", "warn", "fail"):
            raise ValueError(
                "root.common.serve.preflight is %r — want off | warn "
                "| fail" % mode)
        if mode in ("off", "no", "false", "0"):
            return None
        from veles_tpu.analyze import PreflightError
        from veles_tpu.analyze.shapes import check_generative
        report = check_generative(engine)
        label = name or getattr(engine, "prof_name", "generative")
        for finding in report:
            log = {"error": self.error,
                   "warning": self.warning}.get(finding.severity,
                                                self.info)
            log("preflight[%s]: %s", label, finding.render())
        if report.has_errors and mode == "fail":
            raise PreflightError(report)
        return report

    def deploy_generative(self, name, engine, version=None,
                          source=None, warmup=True,
                          scheduler_config=None, quantize=None,
                          calibration=None):
        """Install a :class:`veles_tpu.gen.engine.GenerativeEngine`
        under ``name`` with its own continuous-batching scheduler
        (started on a worker thread).  Redeploying a generative name
        is a DRAIN swap: the old scheduler finishes its streams, its
        engine releases the KV cache, then the successor takes over —
        token streams cannot migrate between engines mid-request.

        ``quantize="int8"`` (or the ``root.common.serve.quantize``
        knob) quantizes the engine's params BEFORE the V-S01
        preflight and warmup (``GenerativeEngine.quantize_int8``), so
        the preflight prices the deploy from the actual int8 bytes;
        ``calibration`` is the optional drift-gate token prompt."""
        mode = self._resolve_quantize(quantize)
        if mode and getattr(engine, "quantized", None) != mode:
            engine.quantize_int8(calibration_tokens=calibration)
        self.preflight_generative(engine, name)
        if warmup:
            engine.warmup()
        from veles_tpu.gen.scheduler import GenerativeScheduler
        scheduler = GenerativeScheduler(
            engine, metrics=self.metrics, name=name,
            **dict(scheduler_config or {})).start()
        with self._lock:
            old = self._models.get(name)
            if old is not None and not old.is_generative:
                scheduler.stop(drain=False)
                raise ValueError(
                    "%r serves a request/response model — undeploy it "
                    "before deploying a generative engine" % name)
            model = _GenModel(name, scheduler)
            model.version = version if version is not None else (
                (old.version + 1)
                if old is not None and
                isinstance(old.version, int) else 1)
            model.deployed_at = time.time()
            model.source = source
            self._models[name] = model
        if old is not None:
            old.scheduler.stop(drain=True)
            old.engine.close()
        self.info("deployed generative %s version %s (%d slots, "
                  "buckets %s)", name, model.version,
                  engine.max_slots, list(engine.prefill_buckets))
        return model

    def deploy_fleet(self, name, fleet, version=None, source=None):
        """Install a disaggregated :class:`veles_tpu.fleet.Fleet`
        under ``name`` — the registry's serving surface (``generate``,
        ``describe``, ``undeploy``) then routes through the fleet's
        front end.  Fleets do not hot-swap in place (their members do,
        via the autoscaler and ``Fleet.drain_replica``): deploying
        over an existing name is refused."""
        with self._lock:
            old = self._models.get(name)
            if old is not None:
                raise ValueError(
                    "%r is already served — a fleet swaps its MEMBERS "
                    "(drain_replica/add_replica), not itself; undeploy "
                    "first" % name)
            model = _FleetModel(name, fleet)
            model.version = version if version is not None else 1
            model.deployed_at = time.time()
            model.source = source or "fleet"
            self._models[name] = model
        self.info("deployed fleet %s version %s", name, model.version)
        return model

    def generate(self, name, tokens, max_new_tokens=16, timeout=120.0,
                 on_token=None):
        """Stream a generation on ``name``'s scheduler (blocking
        convenience; returns the full token list)."""
        model = self.get(name)
        if not model.is_generative:
            raise ValueError("%r is not a generative model" % name)
        return model.scheduler.generate(tokens, max_new_tokens,
                                        timeout=timeout,
                                        on_token=on_token)

    def preflight(self, workflow, name=None):
        """Run analyzer passes 1–2 (graph doctor + JAX hazards) on a
        workflow about to be served, per ``root.common.serve
        .preflight``:

        - ``"off"`` — skip entirely;
        - ``"warn"`` (default) — log every finding, deploy anyway;
        - ``"fail"`` — raise :class:`veles_tpu.analyze.PreflightError`
          when the report contains errors (the serve counterpart of
          the engine's warmup guarantee: refuse at load time, not at
          the first request).

        Returns the :class:`~veles_tpu.analyze.Report` (or ``None``
        when off).
        """
        mode = str(root.common.serve.get("preflight",
                                         "warn")).strip().lower()
        if mode not in ("off", "no", "false", "0", "warn", "fail"):
            # a typo'd fail-mode config must not silently downgrade
            # to warn-and-deploy
            raise ValueError(
                "root.common.serve.preflight is %r — want off | warn "
                "| fail" % mode)
        if mode in ("off", "no", "false", "0"):
            return None
        from veles_tpu.analyze import PreflightError, analyze_workflow
        report = analyze_workflow(workflow)
        label = name or type(workflow).__name__
        for finding in report:
            log = {"error": self.error,
                   "warning": self.warning}.get(finding.severity,
                                                self.info)
            log("preflight[%s]: %s", label, finding.render())
        if report.has_errors and mode == "fail":
            raise PreflightError(report)
        if len(report):
            counts = report.counts()
            self.info("preflight[%s]: %d error(s), %d warning(s) "
                      "(mode=%s)", label, counts["error"],
                      counts["warning"], mode)
        return report

    def load_snapshot(self, name, path, version=None, engine_config=None,
                      warmup=True):
        """Build an engine from a snapshot artifact and deploy it
        (pre-flighted per ``root.common.serve.preflight``)."""
        from veles_tpu.snapshotter import load_snapshot
        workflow = load_snapshot(path)
        self.preflight(workflow, name)
        engine = InferenceEngine.from_workflow(
            workflow, **dict(engine_config or {}))
        return self.deploy(name, engine, version=version, source=path,
                           warmup=warmup)

    def load_workflow(self, name, workflow, version=None,
                      engine_config=None, warmup=True):
        self.preflight(workflow, name)
        engine = InferenceEngine.from_workflow(
            workflow, **dict(engine_config or {}))
        return self.deploy(name, engine, version=version,
                           source=type(workflow).__name__,
                           warmup=warmup)

    def get(self, name):
        model = self._models.get(name)
        if model is None:
            raise KeyError("no model %r (serving: %s)"
                           % (name, ", ".join(sorted(self._models))
                              or "<none>"))
        return model

    def __contains__(self, name):
        return name in self._models

    def undeploy(self, name, drain=True):
        """Remove ONE served name: stop its batcher (or generative
        scheduler + engine), drop its gauges, free the entry — the
        single-model counterpart of :meth:`stop`, and the remedy the
        kind-mixup errors point at."""
        with self._lock:
            model = self._models.pop(name, None)
        if model is None:
            raise KeyError("no model %r" % name)
        if self.metrics is not None and not model.is_generative:
            self.metrics.unregister_gauge(
                'queue_depth{model="%s"}' % name)
        if model.is_generative:
            model.scheduler.stop(drain=drain)
            model.engine.close()
        else:
            model.batcher.stop(drain=drain)
            self._retire_engine(model.engine, None)
        self.info("undeployed %s", name)
        return model

    def names(self):
        with self._lock:   # a first deploy may be inserting a key
            return sorted(self._models)

    def describe(self):
        with self._lock:
            models = dict(self._models)
        return {name: model.describe()
                for name, model in sorted(models.items())}

    def extra_metrics_text(self):
        """Exposition lines contributed by deployed models themselves —
        today that is the disaggregated fleet's ``veles_fleet_*``
        gauges (``_FleetModel.metrics_text``), so the serving scrape
        shows the autoscaler's signals and its actions on one
        endpoint.  A raising source is skipped, never poisoning the
        scrape."""
        with self._lock:
            models = list(self._models.values())
        parts = []
        for model in models:
            fn = getattr(model, "metrics_text", None)
            if fn is None:
                continue
            try:
                parts.append(fn())
            except Exception:
                self.exception("metrics_text source failed")
        return "".join(parts)

    def submit(self, name, rows):
        """Queue rows on ``name``'s batcher; returns the Future."""
        model = self.get(name)
        if model.is_generative:
            raise ValueError(
                "%r is generative — use generate()/the /generate "
                "route, not the request/response path" % name)
        return model.batcher.submit(rows)

    def infer(self, name, rows, timeout=30.0):
        return self.submit(name, rows).result(timeout)

    def stop(self, drain=True):
        with self._lock:
            models, self._models = dict(self._models), {}
        if self.metrics is not None:
            # a shared sink outlives this registry: stale gauges would
            # keep reporting dead models (and pin their engines' device
            # params against GC)
            self.metrics.unregister_gauge("models")
            for name in models:
                self.metrics.unregister_gauge(
                    'queue_depth{model="%s"}' % name)
        for model in models.values():
            if model.is_generative:
                model.scheduler.stop(drain=drain)
                model.engine.close()
            else:
                model.batcher.stop(drain=drain)
                self._retire_engine(model.engine, None)
